"""HTTP load generator for the serving plane (stdlib-only).

Drives ``--mode serve``'s ``POST /v1/completions`` with N concurrent
clients, either closed-loop (each client fires its next request the moment
the previous completes — the saturation view) or open-loop (Poisson
arrivals at ``--rate`` req/s regardless of completions — the latency-
under-load view; open loop is the honest one for tail latencies, since a
closed loop self-throttles when the server slows down). Prompts draw from
a ``--prompt-len`` mix of random in-vocab token ids (``prompt_ids`` path:
no tokenizer needed on either side), or from ``--prompt`` literals.

``--workload json`` (ISSUE 8) sends schema-constrained requests
(``response_format: json_schema`` against :data:`JSON_WORKLOAD_SCHEMA`)
and asserts every response's assembled text ``json.loads``-parses —
the end-to-end proof that grammar-constrained decoding produced valid
JSON through the whole HTTP plane. Needs a server-side tokenizer.
Invalid responses land in ``json_invalid`` (nonzero exit).

Prints TTFT / TPOT / end-to-end percentiles and aggregate token
throughput; used by ``make serve-smoke`` / ``make constrain-smoke`` and
the ``CAKE_BENCH_SERVE=1`` / ``CAKE_BENCH_CONSTRAIN=1`` bench rows.

Usage:
  python -m cake_tpu.tools.loadgen http://127.0.0.1:8080 \\
      -n 32 -c 4 --max-tokens 64 --prompt-len 8,32,128
  python -m cake_tpu.tools.loadgen http://127.0.0.1:8080 \\
      -n 64 --rate 8 --max-tokens 32        # open loop, 8 req/s Poisson
  python -m cake_tpu.tools.loadgen http://127.0.0.1:8080 \\
      -n 16 --workload json --max-tokens 48  # constrained JSON workload
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request


# the --workload json constraint: small, fully bounded (the lowered
# automaton is acyclic, so every constrained stream terminates within
# its token budget), exercises object/integer/boolean paths
JSON_WORKLOAD_SCHEMA = {
    "type": "object",
    "properties": {
        "a": {"type": "integer"},
        "ok": {"type": "boolean"},
    },
    "required": ["a", "ok"],
}


def _percentile(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(q * (len(s) - 1) + 0.5)))
    return s[i]


def _one_request(url: str, body: dict, timeout: float) -> dict:
    """Fire one streaming completions request; measure TTFT (first SSE
    token event), per-token gaps, and end-to-end wall. Returns a result
    dict ({"error"/"status": ...} on failure)."""
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    out: dict = {"tokens": 0, "ttft_s": None, "gaps_s": [], "ids": [],
                 "text": ""}
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if not body.get("stream"):
                payload = json.loads(resp.read())
                out["tokens"] = payload["usage"]["completion_tokens"]
                out["ids"] = payload.get("token_ids", [])
                out["text"] = payload.get("text", "")
                out["finish_reason"] = payload.get("finish_reason")
                out["ttft_s"] = (payload["usage"].get("ttft_ms", 0)
                                 or 0) / 1e3
                out["wall_s"] = time.perf_counter() - t0
                return out
            t_last = None
            for raw in resp:
                raw = raw.strip()
                if not raw.startswith(b"data: "):
                    continue
                data = raw[len(b"data: "):]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                if "token" in ev:
                    now = time.perf_counter()
                    if t_last is None:
                        out["ttft_s"] = now - t0
                    else:
                        out["gaps_s"].append(now - t_last)
                    t_last = now
                    out["tokens"] += 1
                    out["ids"].append(ev["token"])
                    if ev.get("text"):
                        out["text"] += ev["text"]
                elif "error" in ev:
                    out["error"] = ev["error"]
                    break
                elif ev.get("done"):
                    if ev.get("text"):
                        out["text"] += ev["text"]  # detok tail
                    out["finish_reason"] = ev.get("finish_reason")
            out["wall_s"] = time.perf_counter() - t0
            return out
    except urllib.error.HTTPError as e:
        return {"status": e.code,
                "retry_after": e.headers.get("Retry-After"),
                "wall_s": time.perf_counter() - t0}
    except Exception as e:  # connection refused/reset, timeout, ...
        return {"error": str(e), "wall_s": time.perf_counter() - t0}


def _make_prompts(n: int, lens: list[int], vocab: int, seed: int,
                  literals: list[str]) -> list[dict]:
    """One request-body fragment per planned request: a literal text
    prompt round-robin, or random in-vocab ids from the length mix."""
    rng = random.Random(seed)
    frags = []
    for i in range(n):
        if literals:
            frags.append({"prompt": literals[i % len(literals)]})
        else:
            ln = lens[i % len(lens)]
            frags.append({"prompt_ids": [rng.randrange(1, max(2, vocab))
                                         for _ in range(ln)]})
    return frags


def run_load(url: str, n: int, concurrency: int = 4, max_tokens: int = 32,
             prompt_lens: list[int] | None = None, vocab: int = 256,
             rate: float | None = None, seed: int = 0,
             prompts: list[str] | None = None, stream: bool = True,
             timeout: float = 300.0, workload: str = "text") -> dict:
    """Run the load; returns aggregate stats (also the in-process entry
    the bench row and tests use). ``workload="json"`` attaches the
    schema constraint to every request and json-validates every
    response's text."""
    if workload not in ("text", "json"):
        raise ValueError(f"workload must be 'text' or 'json', "
                         f"got {workload!r}")
    frags = _make_prompts(n, prompt_lens or [8], vocab, seed, prompts or [])
    results: list[dict] = [None] * n  # type: ignore[list-item]
    t_start = time.perf_counter()

    def fire(i: int) -> None:
        body = dict(frags[i], max_tokens=max_tokens, stream=stream)
        if workload == "json":
            body["response_format"] = {"type": "json_schema",
                                       "schema": JSON_WORKLOAD_SCHEMA}
        results[i] = _one_request(url, body, timeout)

    if rate:
        # open loop: Poisson arrivals, one thread per in-flight request
        rng = random.Random(seed + 1)
        threads = []
        t_next = time.perf_counter()
        for i in range(n):
            t_next += rng.expovariate(rate)
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=fire, args=(i,), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=timeout)
    else:
        # closed loop: `concurrency` clients, each back-to-back
        it = iter(range(n))
        lock = threading.Lock()

        def worker() -> None:
            while True:
                with lock:
                    i = next(it, None)
                if i is None:
                    return
                fire(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(concurrency)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout)
    wall = time.perf_counter() - t_start

    done = [r for r in results if r and r.get("tokens")]
    rejected = [r for r in results if r and r.get("status") == 429]
    errors = [r for r in results if r and (
        "error" in r or ("status" in r and r["status"] != 429))]
    json_invalid = 0
    if workload == "json":
        for r in done:
            try:
                json.loads(r.get("text") or "")
            except ValueError:
                json_invalid += 1
                r["json_invalid"] = True
    ttfts = [r["ttft_s"] for r in done if r.get("ttft_s") is not None]
    gaps = [g for r in done for g in r.get("gaps_s", ())]
    total_tokens = sum(r["tokens"] for r in done)
    return {
        "requests": n,
        "completed": len(done),
        "rejected_429": len(rejected),
        "errors": len(errors),
        "json_invalid": json_invalid,
        "wall_s": round(wall, 3),
        "tokens": total_tokens,
        "tok_s": round(total_tokens / wall, 2) if wall > 0 else 0.0,
        "ttft_ms": {
            "p50": round(_percentile(ttfts, 0.5) * 1e3, 1),
            "p95": round(_percentile(ttfts, 0.95) * 1e3, 1),
        },
        "tpot_ms": {
            "p50": round(_percentile(gaps, 0.5) * 1e3, 2),
            "p95": round(_percentile(gaps, 0.95) * 1e3, 2),
        },
        "results": results,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cake-loadgen",
        description="closed/open-loop HTTP load generator for --mode serve",
    )
    p.add_argument("url", help="server base URL, e.g. http://127.0.0.1:8080")
    p.add_argument("-n", "--requests", type=int, default=16)
    p.add_argument("-c", "--concurrency", type=int, default=4,
                   help="closed-loop client count (ignored with --rate)")
    p.add_argument("--rate", type=float, default=None,
                   help="open-loop Poisson arrival rate (req/s); omit for "
                        "closed loop")
    p.add_argument("--max-tokens", type=int, default=32, dest="max_tokens")
    p.add_argument("--prompt-len", default="8", dest="prompt_len",
                   help="comma-separated prompt-length mix for random "
                        "prompt_ids requests (cycled per request)")
    p.add_argument("--vocab", type=int, default=256,
                   help="vocab bound for the random prompt ids")
    p.add_argument("--prompt", action="append", default=[],
                   help="literal text prompt (repeatable; needs a "
                        "server-side tokenizer; overrides --prompt-len)")
    p.add_argument("--no-stream", action="store_true",
                   help="unary JSON responses instead of SSE")
    p.add_argument("--workload", choices=["text", "json"], default="text",
                   help="json: schema-constrained requests "
                        "(response_format json_schema), responses "
                        "asserted json.loads-parseable")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args(argv)
    lens = [int(x) for x in args.prompt_len.split(",") if x.strip()]
    stats = run_load(
        args.url, args.requests, concurrency=args.concurrency,
        max_tokens=args.max_tokens, prompt_lens=lens, vocab=args.vocab,
        rate=args.rate, seed=args.seed, prompts=args.prompt,
        stream=not args.no_stream, timeout=args.timeout,
        workload=args.workload,
    )
    stats = dict(stats)
    stats.pop("results")
    print(json.dumps(stats, indent=1))
    return 0 if stats["errors"] == 0 and stats["json_invalid"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
