"""Price the 70B pipeline's PER-STAGE step on one real chip.

BASELINE.md configs 4/5 (Llama-3-70B layer-sharded over v5e-16) have been
budget-only: `utils.memory.hbm_budget` proves the bytes fit, and the
80-layer file plane is rehearsed at miniature dims
(tests/test_70b_rehearsal.py). This tool adds the missing MEASURED rung
(r4 verdict item 7): one v5e-16 stage is 5 of 80 layers, and a 5-layer
slice of the real 70B geometry (hidden 8192, 64 heads / 8 KV heads,
intermediate 28672) FITS one v5e chip — so its decode-step and prefill
wall-clock can be measured for real, and the full-pipeline numbers follow
by multiplication plus an ICI hop term.

What is measured vs projected (reported explicitly in the JSON):

- MEASURED: per-stage decode step time (B=1, T=1, the serialized pipeline
  regime), per-stage prefill time at T=2048, HBM in use.
- PROJECTED: the inter-stage hop. The activation is ``[1, 1, 8192]``
  bf16 = 16 KiB; public v5e ICI figures and the reference's own
  measurement ladder (tools/ici_probe.py — runs on any >=2-chip slice)
  put a neighbor ppermute of that payload at single-digit microseconds,
  vs the ~5 ms stage step: the hop term is noise. The projection is
  carried at a deliberately pessimistic 50 us so the headline cannot
  lean on the favorable assumption.

Single-stream v5e-16 projection: ``1 / (16 * t_stage + 16 * t_hop)``
(stages serialized per token — the reference's own wall-clock shape,
"upstream workers idle", SURVEY.md §2). The interleaved schedule
(parallel/pipeline.build_interleaved_decode) keeps every stage busy with
S=16 microbatches, so its aggregate upper bound is ``16x`` that — both
reported.

Run on the tunnel chip: ``python -m cake_tpu.tools.stage_slice``
(``--json-out FILE`` to record). ``--mini`` runs the same machinery at
tiny dims on CPU (the machinery-proof regression path, like
tests/test_ici_probe.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.config import LlamaConfig
from cake_tpu.models import llama
from cake_tpu.ops.kvcache import KVCache, init_cache
from cake_tpu.ops.rope import rope_tables

from cake_tpu.utils.chips import HBM_GBPS, device_spec

# deliberately pessimistic inter-stage ppermute projection (see module
# docstring; measured single-digit us on real multi-chip slices)
HOP_S_PROJECTED = 50e-6


def slice_config(layers: int, window: int, mini: bool) -> LlamaConfig:
    """``layers`` of the Llama-3-70B geometry (config.json parity:
    hidden 8192, 64/8 heads, intermediate 28672, vocab 128256)."""
    if mini:
        return LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=layers, num_attention_heads=4,
            num_key_value_heads=2, max_seq_len=window, rope_theta=10000.0,
        )
    return LlamaConfig(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_hidden_layers=layers, num_attention_heads=64,
        num_key_value_heads=8, max_seq_len=window, rope_theta=500000.0,
    )


def _layer_params(cfg: LlamaConfig, quant: str | None):
    """Stacked layer weights only — a stage holds no embed/lm_head (those
    live replicated / vocab-sharded outside the stage loop; the budget
    table prices them separately)."""
    key = jax.random.PRNGKey(0)
    if quant == "int8":
        params = llama.init_params_int8(cfg, key)
    else:
        params = llama.init_params(cfg, key)
    layers = params["layers"]
    del params
    return layers


def _sync(x) -> None:
    for leaf in jax.tree.leaves(x):
        np.asarray(leaf.ravel()[:1])


def _param_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def measure_slice(quant: str | None, layers: int, window: int,
                  steps: int, mini: bool) -> dict:
    cfg = slice_config(layers, window, mini)
    dev = jax.devices()[0]
    layer_w = _layer_params(cfg, quant)
    _sync(layer_w)
    cos, sin = rope_tables(cfg.head_dim, window, cfg.rope_theta,
                           scaling=cfg.rope_scaling)

    decode = jax.jit(
        partial(_stage_decode, config=cfg), donate_argnames=("cache",),
    )
    cache = init_cache(cfg, batch=1, max_seq=window)
    x = jnp.ones((1, 1, cfg.hidden_size), cfg.jax_dtype)
    pos = window // 2  # mid-window frontier: representative mask work

    # compile + warm (2 dispatches)
    x_out, cache = decode(layer_w, x, cache, cos, sin, jnp.int32(pos))
    x_out, cache = decode(layer_w, x_out, cache, cos, sin, jnp.int32(pos + 1))
    _sync(x_out)
    t0 = time.perf_counter()
    for i in range(steps):
        x_out, cache = decode(layer_w, x_out, cache, cos, sin,
                              jnp.int32(pos + 2 + i))
        # activation feeds back so steps chain data-dependently (no
        # artificial pipelining of independent dispatches)
    _sync(x_out)
    t_stage = (time.perf_counter() - t0) / steps

    # prefill slice: one T=2048 chunk through the stage (TTFT side)
    t_pf = None
    pf_t = min(2048, window // 2)
    if pf_t >= 8:
        prefill = jax.jit(partial(_stage_decode, config=cfg),
                          donate_argnames=("cache",))
        cache2 = init_cache(cfg, batch=1, max_seq=window)
        xp = jnp.ones((1, pf_t, cfg.hidden_size), cfg.jax_dtype)
        xo, cache2 = prefill(layer_w, xp, cache2, cos, sin, jnp.int32(0))
        _sync(xo)
        cache2 = init_cache(cfg, batch=1, max_seq=window)
        t0 = time.perf_counter()
        xo, cache2 = prefill(layer_w, xp, cache2, cos, sin, jnp.int32(0))
        _sync(xo)
        t_pf = time.perf_counter() - t0

    gb = _param_bytes(layer_w) / 1e9
    gbps = device_spec(dev, HBM_GBPS, 50.0)
    roofline_s = gb / gbps  # weights-bound floor for one decode step
    hbm = None
    try:
        stats = dev.memory_stats()
        if stats:
            hbm = stats.get("bytes_in_use")
    except Exception:
        pass

    n_stages = 16 if not mini else 4
    t_tok_serial = n_stages * (t_stage + HOP_S_PROJECTED)
    row = {
        "quant": quant or "bf16",
        "layers_per_stage": layers,
        "window": window,
        "device": getattr(dev, "device_kind", "cpu"),
        "platform": dev.platform,
        "stage_weight_gb": round(gb, 3),
        "stage_step_ms_measured": round(t_stage * 1e3, 3),
        "stage_step_ms_roofline": round(roofline_s * 1e3, 3),
        "stage_prefill2048_ms_measured": (
            round(t_pf * 1e3, 1) if t_pf is not None else None),
        "hbm_bytes_in_use": hbm,
        "hop_s_projected": HOP_S_PROJECTED,
        "n_stages": n_stages,
        "single_stream_tok_s_projected": round(1.0 / t_tok_serial, 2),
        "interleaved_aggregate_tok_s_upper": round(
            n_stages / t_tok_serial, 2),
        "stamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    return row


def _stage_decode(layer_w, x, cache: KVCache, cos, sin, pos, *, config):
    """One pipeline stage's compute: forward this stage's stacked layers
    over the incoming activation (exactly what _pipeline_layers runs per
    active stage — parallel/pipeline.py; embed/head excluded)."""
    return llama.forward_layers(layer_w, x, cache, cos, sin, pos, config)


_NOTE = (
    "stage_step/prefill are MEASURED single-chip; the hop term and the "
    "v5e-16 tok/s are PROJECTIONS (no multi-chip hardware in this "
    "environment — tools/ici_probe.py is the measurement of record to "
    "run on a real slice)")


def _write_partial(json_out: str | None, rows: list) -> None:
    if not json_out:
        return
    with open(json_out, "w") as f:
        json.dump({"rows": rows, "note": _NOTE}, f, indent=1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--layers", type=int, default=5,
                    help="layers per stage (70B/v5e-16 = 80/16 = 5)")
    ap.add_argument("--window", type=int, default=8192)
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--mini", action="store_true",
                    help="tiny dims (CPU machinery proof)")
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args(argv)

    if args.mini:
        args.window = min(args.window, 128)
    rows = []
    # int8 (the 70B serving tier of record) runs FIRST and each row is
    # flushed to --json-out the moment it lands: the bf16 variant's ~13 GB
    # peak is tight on a 16 GiB chip, and a crash there must not erase the
    # int8 measurement (the r3 wedge history: evidence dies with the
    # process unless persisted incrementally).
    for quant in ("int8", None):
        try:
            row = measure_slice(quant, args.layers, args.window, args.steps,
                                args.mini)
        except Exception as e:  # OOM/compile failure on one variant
            sys.stderr.write(f"[{quant or 'bf16'}] variant failed: {e}\n")
            rows.append({"quant": quant or "bf16", "error": str(e)[:500]})
            _write_partial(args.json_out, rows)
            continue
        rows.append(row)
        _write_partial(args.json_out, rows)
        sys.stderr.write(
            f"[{row['quant']}] stage({args.layers}L, win {args.window}) on "
            f"{row['device']}: step {row['stage_step_ms_measured']} ms "
            f"(roofline {row['stage_step_ms_roofline']} ms), "
            f"prefill2048 {row['stage_prefill2048_ms_measured']} ms -> "
            f"v5e-16 projection {row['single_stream_tok_s_projected']} "
            f"tok/s single-stream, "
            f"{row['interleaved_aggregate_tok_s_upper']} aggregate "
            f"(interleaved upper bound; hop term projected "
            f"{HOP_S_PROJECTED * 1e6:.0f} us pessimistic)\n"
        )
    out = {"rows": rows, "note": _NOTE}
    print(json.dumps(out))
    # nonzero when nothing was measured: an all-failed run must not look
    # like success to `make stage-slice` / the queue's exit logging
    return 0 if any("error" not in r for r in rows) else 1


if __name__ == "__main__":
    sys.exit(main())
