"""Bench regression gate: newest ledger row vs the best prior run.

``bench.py`` appends one JSON line per figure of merit to
``bench_results.jsonl`` — an append-only ledger that already spans every
preset/quant/plane combination the smokes exercise. This tool turns the
ledger into a GATE: for each metric, compare the NEWEST row against the
best prior row of the same metric (same device tag), print a trend
table, and exit nonzero when any metric regressed past the threshold.
``make bench-diff`` chains it into CI next to ``make lint``, so a perf
regression fails the build the same way a lint finding does.

Direction comes from the row's unit:

- ``tokens/s`` — higher is better; regression is the relative drop from
  the best prior value.
- ``ms`` / ``s`` — lower is better; regression is the relative rise
  over the best (lowest) prior value.
- ``%`` — overhead rows (obs/prof/trace legs); the row itself already
  answers the question ("how much does this plane cost when on"), so
  these gate on the NEWEST value against an absolute points budget
  (``--regress-points``), not against history. CPU A/B legs swing by
  several points run-to-run (the ledger holds -22 .. +14 for the same
  leg), so a min-of-history comparison would be poisoned forever by one
  lucky negative leg, and a relative one is meaningless across zero.

The default ``--regress-pct`` is deliberately loose (80): the CPU smoke
ledger's tok/s rows swing several-fold with host load (the churn row's
history spans 12..879 tok/s). A TPU CI lane pins its own tighter
threshold. ``BASELINE.json``'s ``published`` map (metric -> value)
seeds the comparison for metrics with no prior ledger row.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

HIGHER_BETTER = {"tokens/s", "tok/s"}
LOWER_BETTER = {"ms", "s"}


def load_rows(path: Path) -> list[dict]:
    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    r = json.loads(line)
                except ValueError:
                    continue  # a truncated tail line must not break the gate
                if isinstance(r, dict) and "metric" in r and "value" in r:
                    rows.append(r)
    except OSError as e:
        sys.exit(f"benchdiff: cannot read ledger {path}: {e}")
    return rows


def published_baseline(path: Path) -> dict:
    """BASELINE.json's ``published`` map, tolerating both bare values and
    ``{"value": ...}`` objects; {} when absent."""
    try:
        with open(path) as f:
            pub = json.load(f).get("published") or {}
    except (OSError, ValueError):
        return {}
    out = {}
    for k, v in pub.items():
        if isinstance(v, dict) and "value" in v:
            out[k] = float(v["value"])
        elif isinstance(v, (int, float)):
            out[k] = float(v)
    return out


def best_prior(prior: list[float], unit: str) -> float | None:
    if not prior:
        return None
    if unit in HIGHER_BETTER:
        return max(prior)
    return min(prior)  # ms/s/% — lower is better


def judge(newest: float, best: float, unit: str,
          regress_pct: float, regress_points: float):
    """(delta_str, regressed) for one metric's newest-vs-best pair."""
    if unit == "%":
        # absolute budget on the newest leg; the delta column still shows
        # the trend vs the best (lowest) prior leg for context
        return f"{newest - best:+.2f}pp", newest > regress_points
    if unit in HIGHER_BETTER:
        if best <= 0:
            return "-", False
        pct = (newest - best) / best * 100.0
        return f"{pct:+.1f}%", -pct > regress_pct
    if unit in LOWER_BETTER:
        if best <= 0:
            return "-", False
        pct = (newest - best) / best * 100.0
        return f"{pct:+.1f}%", pct > regress_pct
    return "-", False  # unknown unit: report, never gate


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchdiff",
        description="gate the newest bench_results.jsonl rows against "
                    "the best prior run per metric")
    ap.add_argument("--ledger", default="bench_results.jsonl",
                    help="bench ledger path (default: ./bench_results.jsonl)")
    ap.add_argument("--baseline", default="BASELINE.json",
                    help="published-baseline fallback for metrics with no "
                         "prior ledger row")
    ap.add_argument("--metric", default=None, metavar="SUBSTR",
                    help="only gate metrics containing SUBSTR")
    ap.add_argument("--regress-pct", type=float, default=80.0,
                    dest="regress_pct", metavar="PCT",
                    help="relative regression threshold for tok/s and ms "
                         "rows (default 80 — CPU smoke ledgers are noisy; "
                         "tighten on dedicated hardware)")
    ap.add_argument("--regress-points", type=float, default=10.0,
                    dest="regress_points", metavar="PP",
                    help="absolute percentage-point budget the newest '%%' "
                         "overhead row must stay under (default 10)")
    args = ap.parse_args(argv)

    rows = load_rows(Path(args.ledger))
    pub = published_baseline(Path(args.baseline))
    if args.metric:
        rows = [r for r in rows if args.metric in r["metric"]]
    if not rows:
        print("benchdiff: no ledger rows to gate")
        return 0

    by_metric: dict[str, list[dict]] = {}
    for r in rows:  # file order IS time order (append-only ledger)
        by_metric.setdefault(r["metric"], []).append(r)

    w = max(len(m) for m in by_metric) + 2
    print(f"{'METRIC':<{w}} {'newest':>10} {'best prior':>10} "
          f"{'delta':>9}  verdict")
    regressed = []
    for metric in sorted(by_metric):
        hist = by_metric[metric]
        newest = hist[-1]
        unit = newest.get("unit", "")
        # compare within one device tag — a cpu smoke row must not gate
        # against a tpu run's number that happens to share the metric name
        prior = [float(r["value"]) for r in hist[:-1]
                 if r.get("device") == newest.get("device")]
        best = best_prior(prior, unit)
        if best is None and metric in pub:
            best = pub[metric]
        if best is None:
            print(f"{metric:<{w}} {newest['value']:>10} {'-':>10} "
                  f"{'-':>9}  new ({len(hist)} row)")
            continue
        delta, bad = judge(float(newest["value"]), best, unit,
                           args.regress_pct, args.regress_points)
        verdict = "REGRESSED" if bad else "ok"
        print(f"{metric:<{w}} {newest['value']:>10} {best:>10} "
              f"{delta:>9}  {verdict} ({len(hist)} rows, {unit})")
        if bad:
            regressed.append((metric, delta))
    if regressed:
        print(f"\nbenchdiff: {len(regressed)} metric(s) regressed past "
              f"the gate (--regress-pct {args.regress_pct}, "
              f"--regress-points {args.regress_points}):")
        for metric, delta in regressed:
            print(f"  {metric}: {delta}")
        return 1
    print(f"\nbenchdiff: {len(by_metric)} metric(s) inside the gate")
    return 0


if __name__ == "__main__":
    sys.exit(main())
