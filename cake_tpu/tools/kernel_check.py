"""On-hardware Pallas kernel validation: compiled kernels vs XLA oracle.

The CPU test suite only ever runs the Pallas kernels *interpreted*
(tests/conftest.py forces the CPU platform; pallas.interpret_default).
This harness proves the Mosaic-COMPILED kernels on a real chip: numerical
parity against the reference-math XLA implementations (the f32-scores
convention of `cake-core/src/model/attention.rs:62-77`) and speed.

Usage:  python -m cake_tpu.tools.kernel_check [--json-out PATH]

Prints one JSON line per kernel:
  {"kernel", "device", "compiled", "max_abs_err", "pallas_ms", "xla_ms",
   "speedup"}
plus an end-to-end decode comparison (CAKE_PALLAS=1 vs 0) when run on TPU.
Exit code is non-zero if any kernel's error exceeds its tolerance.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x):
    for leaf in jax.tree.leaves(x):
        np.asarray(leaf.ravel()[:1])


def _time_ms(fn, *args, iters: int = 20, inner: int = 32, chain=None) -> float:
    """Per-call latency with dispatch amortized: each timed dispatch runs
    ``inner`` invocations inside one jitted program (remote-tunnel dispatch
    costs ~3.5 ms, which would otherwise floor every measurement).

    Each iteration's first argument is perturbed by ``prev_out * 1e-30``
    (``chain`` overrides how the output is folded back in) — a genuine data
    dependence, so XLA cannot hoist/CSE the loop body into a single call;
    the perturbation itself is rounded away and does not change the math.
    """
    if chain is None:
        def chain(out, a0):
            return a0 + (out * 1e-30).astype(a0.dtype)

    @jax.jit
    def repeated(*a):
        def body(a0, _):
            out = fn(a0, *a[1:])
            return chain(out, a0), out

        a0, out = jax.lax.scan(body, a[0], None, length=inner)
        return out

    out = repeated(*args)  # compile
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = repeated(*args)
    _sync(out)
    return (time.perf_counter() - t0) / (iters * inner) * 1e3


def _report(name: str, device: str, compiled: bool, err: float,
            p_ms: float, x_ms: float, tol: float, results: list) -> bool:
    ok = err <= tol
    rec = {
        "kernel": name,
        "device": device,
        "compiled": compiled,
        "max_abs_err": float(err),
        "tol": tol,
        "pallas_ms": round(p_ms, 4),
        "xla_ms": round(x_ms, 4),
        "speedup": round(x_ms / p_ms, 3) if p_ms > 0 else None,
        "ok": ok,
    }
    results.append(rec)
    print(json.dumps(rec))
    return ok


def check_kernels(dtype=jnp.bfloat16,
                  results: list | None = None) -> tuple[list, bool]:
    """Run every Pallas kernel at 8B-like shapes vs its XLA oracle.
    ``results``: pass a pre-built list (e.g. the crash-safe
    :class:`_FlushedResults`) to collect rows into."""
    from cake_tpu.ops import norms, quant
    from cake_tpu.ops.attention import _attend_xla
    from cake_tpu.ops.pallas import (
        flash_attention,
        flash_decode,
        interpret_default,
        quant_matmul_pallas,
    )

    dev = jax.devices()[0]
    device = dev.device_kind
    compiled = not interpret_default()
    key = jax.random.PRNGKey(0)
    if results is None:
        results = []
    all_ok = True

    # Llama-3-8B attention geometry: 32 q heads, 8 kv heads, head_dim 128.
    b, h, kvh, d, s = 1, 32, 8, 128, 1024
    ks = jax.random.split(key, 8)
    # bf16 magnitude-1 inputs; KV buffer fully populated, frontier mid-buffer
    q_pf = jax.random.normal(ks[0], (b, h, 512, d), dtype)
    k_all = jax.random.normal(ks[1], (b, kvh, s, d), dtype)
    v_all = jax.random.normal(ks[2], (b, kvh, s, d), dtype)

    # -- flash_attention (prefill, T=512 at pos=137) ------------------------
    pos = jnp.int32(137)
    f_pal = jax.jit(partial(flash_attention, interpret=not compiled))
    f_xla = jax.jit(_attend_xla)
    got = f_pal(q_pf, k_all, v_all, pos)
    want = f_xla(q_pf, k_all, v_all, pos)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    p_ms = _time_ms(f_pal, q_pf, k_all, v_all, pos)
    x_ms = _time_ms(f_xla, q_pf, k_all, v_all, pos)
    all_ok &= _report("flash_attention_prefill_t512_s1024", device, compiled,
                      err, p_ms, x_ms, 0.05, results)

    # -- flash_attention long-context (T=2048 against S=8192) ---------------
    # where the blockwise kernel earns its keep: the XLA path materializes
    # [H, T, S] f32 scores (2 GiB here); flash keeps them in VMEM.
    q_long = jax.random.normal(ks[0], (b, h, 2048, d), dtype)
    k_long = jax.random.normal(ks[1], (b, kvh, 8192, d), dtype)
    v_long = jax.random.normal(ks[2], (b, kvh, 8192, d), dtype)
    pos_l = jnp.int32(0)
    got = f_pal(q_long, k_long, v_long, pos_l)
    want = f_xla(q_long, k_long, v_long, pos_l)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    p_ms = _time_ms(f_pal, q_long, k_long, v_long, pos_l, inner=8)
    x_ms = _time_ms(f_xla, q_long, k_long, v_long, pos_l, inner=8)
    all_ok &= _report("flash_attention_prefill_t2048_s8192", device, compiled,
                      err, p_ms, x_ms, 0.05, results)
    del q_long, k_long, v_long, got, want

    # -- flash_decode (T=1 at pos=1000) -------------------------------------
    q_dec = jax.random.normal(ks[3], (b, h, 1, d), dtype)
    pos_d = jnp.int32(1000)
    fd_pal = jax.jit(partial(flash_decode, interpret=not compiled))
    got = fd_pal(q_dec, k_all, v_all, pos_d)
    want = f_xla(q_dec, k_all, v_all, pos_d)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    p_ms = _time_ms(fd_pal, q_dec, k_all, v_all, pos_d)
    x_ms = _time_ms(f_xla, q_dec, k_all, v_all, pos_d)
    all_ok &= _report("flash_decode_s1024", device, compiled, err, p_ms, x_ms,
                      0.05, results)

    # -- quant_matmul (8B mlp up-proj slice: 4096 x 4096) --------------------
    m, kk, n = 8, 4096, 4096
    x = jax.random.normal(ks[4], (m, kk), dtype)
    w = jax.random.normal(ks[5], (kk, n), dtype)
    ql = quant.quantize_linear(w)
    qm_pal = jax.jit(partial(quant_matmul_pallas, interpret=not compiled))
    qm_xla = jax.jit(quant.quant_matmul_xla)
    got = qm_pal(x, ql.q, ql.scale)
    want = qm_xla(x, ql.q, ql.scale)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    # int8 dequant epilogue vs convert-into-dot: identical math modulo
    # accumulation order; bf16 output quantum at |y|~64 is ~0.5
    p_ms = _time_ms(qm_pal, x, ql.q, ql.scale)
    x_ms = _time_ms(qm_xla, x, ql.q, ql.scale)
    all_ok &= _report("quant_matmul_4096x4096_int8", device, compiled, err,
                      p_ms, x_ms, 1.0, results)

    # -- quant4_matmul: packed int4, per-channel and grouped -----------------
    # proves the Mosaic lowering of the int32 nibble-unpack shifts and the
    # grouped scale index map on real hardware (the CPU suite only ever
    # interprets), and measures the m=1 gemv regime that decides the decode
    # dispatch frontier
    from cake_tpu.ops.pallas import quant4_matmul_pallas

    q4 = quant.quantize_linear4(w)
    q4m_pal = jax.jit(partial(quant4_matmul_pallas, interpret=not compiled))
    q4m_xla = jax.jit(quant.quant4_matmul_xla)
    for label, rows in (("m8", 8), ("m1", 1), ("m16", 16)):
        xr = jax.random.normal(ks[6], (rows, kk), dtype)
        got = q4m_pal(xr, q4.qp, q4.scale)
        want = q4m_xla(xr, q4.qp, q4.scale)
        err = float(jnp.max(jnp.abs(
            got.astype(jnp.float32) - want.astype(jnp.float32))))
        p_ms = _time_ms(q4m_pal, xr, q4.qp, q4.scale)
        x_ms = _time_ms(q4m_xla, xr, q4.qp, q4.scale)
        all_ok &= _report(f"quant4_matmul_4096x4096_{label}", device,
                          compiled, err, p_ms, x_ms, 1.0, results)

    q4g = quant.quantize_linear4(w, group_size=256)  # g2=128: tileable
    got = q4m_pal(x, q4g.qp, q4g.scale)
    want = q4m_xla(x, q4g.qp, q4g.scale)
    err = float(jnp.max(jnp.abs(
        got.astype(jnp.float32) - want.astype(jnp.float32))))
    p_ms = _time_ms(q4m_pal, x, q4g.qp, q4g.scale)
    x_ms = _time_ms(q4m_xla, x, q4g.qp, q4g.scale)
    all_ok &= _report("quant4_matmul_4096x4096_g256", device, compiled, err,
                      p_ms, x_ms, 1.0, results)

    return results, all_ok


def check_end_to_end(results: list) -> None:
    """Decode tok/s with kernels on (CAKE_PALLAS=1) vs off (=0), same process.

    The dispatch mode is read at trace time (pallas.kernels_enabled inside
    attend), so two fresh jit objects traced under different env values give
    the two paths.
    """
    from cake_tpu.models.config import LlamaConfig
    from cake_tpu.models.llama import init_params
    from cake_tpu.ops.kvcache import init_cache
    from cake_tpu.ops.sampling import SamplerSettings, init_history
    from cake_tpu.runtime.generator import decode_scan_fn

    # head_dim 128 (hidden/heads) so the flash gate (_flash_ok) routes the
    # attention to the compiled kernels — the point of the comparison
    config = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=16, num_attention_heads=16, num_key_value_heads=8,
        max_seq_len=1024,
    )
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    params = init_params(config, jax.random.PRNGKey(0))
    steps = 16

    tok_s = {}
    toks_by_mode = {}
    for mode in ("1", "0"):
        os.environ["CAKE_PALLAS"] = mode
        decode = jax.jit(
            partial(decode_scan_fn, config=config, settings=settings,
                    steps=steps),
        )
        cache = init_cache(config, batch=1, max_seq=config.max_seq_len)
        history, hist_slot = init_history(settings.repeat_last_n)
        args = [params, jnp.asarray([7], jnp.int32), cache, jnp.int32(512),
                jax.random.PRNGKey(0), history, hist_slot]
        out = decode(*args)  # compile
        _sync(out)
        toks_by_mode[mode] = np.asarray(out[0])
        t0 = time.perf_counter()
        n = 0
        for _ in range(8):
            out = decode(*args)
            n += steps
        _sync(out)
        tok_s[mode] = n / (time.perf_counter() - t0)
    os.environ.pop("CAKE_PALLAS", None)

    rec = {
        "kernel": "e2e_decode_small_s1024",
        "device": jax.devices()[0].device_kind,
        "tok_s_pallas": round(tok_s["1"], 2),
        "tok_s_xla": round(tok_s["0"], 2),
        "speedup": round(tok_s["1"] / tok_s["0"], 3),
        "tokens_match": bool((toks_by_mode["1"] == toks_by_mode["0"]).all()),
    }
    results.append(rec)
    print(json.dumps(rec))


class _FlushedResults(list):
    """A results list whose append also rewrites ``--json-out``: a
    mid-run crash (the r4w2 wedge killed kernel_check between rows and
    the committed artifact lost every already-measured row) must never
    erase landed evidence again."""

    def __init__(self, path: str | None):
        super().__init__()
        self.path = path

    def append(self, rec) -> None:
        super().append(rec)
        if self.path:
            with open(self.path, "w") as f:
                json.dump(list(self), f, indent=1)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json-out", default=None,
                    help="also write all records to this file (rewritten "
                         "after every row — crash-safe)")
    ap.add_argument("--e2e", action="store_true",
                    help="include the end-to-end decode comparison")
    args = ap.parse_args()

    dev = jax.devices()[0]
    sys.stderr.write(f"device={dev.device_kind} platform={dev.platform}\n")
    results, ok = check_kernels(results=_FlushedResults(args.json_out))
    if args.e2e or dev.platform == "tpu":
        check_end_to_end(results)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
