"""deploy: push code + per-worker bundles to topology hosts and start workers.

Equivalent of the reference's rsync deploy targets
(`/root/reference/Makefile:29-39` — ``sync_bahamut``/``sync_blade``: rsync
the source tree, excluding data/.git/target, plus each host's pre-split
``<name>-node`` bundle), generalized from two hard-coded LAN hosts to every
host in a topology YAML, with the TPU-VM twist that the same command can
also start the worker process remotely (the reference leaves starting
workers to the operator).

For each worker node in the topology:

1. rsync the repo to ``--repo-dest`` (excluding VCS/caches/checkpoints);
2. rsync the worker's ``<name>-node`` bundle (tools/split_model.py layout:
   ``model/reduced.safetensors`` + index + single-worker ``topology.yml``)
   from ``--bundles`` to ``--data-dest``;
3. with ``--start``: launch ``python -m cake_tpu.cli --mode worker`` on the
   host bound to the node's port, its own bundle and topology, via
   ``ssh ... nohup``.

Safety: commands only PRINT by default (the dry run); ``--run`` executes
them. ``--ssh-user``/``--ssh-opts`` thread through to both rsync and ssh.

Usage:
  python -m cake_tpu.tools.deploy --topology topology.yml \\
      --bundles ./bundles --repo-dest /opt/cake-tpu \\
      --data-dest /opt/cake-data [--start] [--run]
"""

from __future__ import annotations

import argparse
import shlex
import subprocess
import sys
from pathlib import Path

from cake_tpu.parallel.topology import Topology

RSYNC_EXCLUDES = (
    ".git", "__pycache__", ".r4_tpu", "*.safetensors", "bundles",
    "cake-data", ".pytest_cache",
    # excluded paths are also protected from --delete: a redeploy must
    # never unlink the logs the started workers are writing into repo_dest
    "worker-*.log",
)


def _host_port(node) -> tuple[str, int]:
    """Split a node's ``host:port`` address (reference topology.yaml
    format); port defaults to the reference's 10128."""
    host = node.host
    if ":" in host:
        h, p = host.rsplit(":", 1)
        return h, int(p)
    return host, 10128


def plan_commands(
    topology: Topology,
    repo_root: str,
    bundles: str | None,
    repo_dest: str,
    data_dest: str,
    start: bool = False,
    ssh_user: str = "",
    ssh_opts: str = "",
    python: str = "python3",
) -> list[list[str]]:
    """Build the per-host command list (pure — this is what the dry run
    prints and the tests assert on)."""
    cmds: list[list[str]] = []
    ssh_base = ["ssh"] + (shlex.split(ssh_opts) if ssh_opts else [])
    # rsync re-splits -e on whitespace: quote per token so an ssh option
    # whose value contains spaces (-o ProxyCommand=...) survives the trip
    rsh = shlex.join(ssh_base) if len(ssh_base) > 1 else "ssh"
    excludes = [f"--exclude={e}" for e in RSYNC_EXCLUDES]
    for name, node in topology.nodes.items():
        host, port = _host_port(node)
        if not host:
            continue  # device:-only node: lives on the mesh, not a host
        target = f"{ssh_user}@{host}" if ssh_user else host
        cmds.append(
            ["rsync", "-rvzc", "--delete", "-e", rsh, *excludes,
             f"{repo_root.rstrip('/')}/", f"{target}:{repo_dest}/"]
        )
        if bundles:
            bundle = str(Path(bundles) / f"{name}-node")
            cmds.append(
                ["rsync", "-rvzc", "-e", rsh, f"{bundle}/",
                 f"{target}:{data_dest}/{name}-node/"]
            )
        if start:
            worker_cmd = (
                f"cd {shlex.quote(repo_dest)} && nohup {python} -m "
                f"cake_tpu.cli --mode worker --address 0.0.0.0:{port} "
                f"--model {shlex.quote(f'{data_dest}/{name}-node/model')} "
                f"--topology "
                f"{shlex.quote(f'{data_dest}/{name}-node/topology.yml')} "
                f"--name {shlex.quote(name)} "
                f"> {shlex.quote(f'worker-{name}.log')} 2>&1 &"
            )
            cmds.append([*ssh_base, target, worker_cmd])
    return cmds


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--topology", required=True)
    ap.add_argument("--bundles", default=None,
                    help="split_model output root holding <name>-node dirs "
                         "(omit to sync code only)")
    ap.add_argument("--repo-dest", default="/opt/cake-tpu")
    ap.add_argument("--data-dest", default="/opt/cake-data")
    ap.add_argument("--start", action="store_true",
                    help="also start each worker over ssh")
    ap.add_argument("--run", action="store_true",
                    help="execute the commands (default: dry-run print)")
    ap.add_argument("--ssh-user", default="")
    ap.add_argument("--ssh-opts", default="")
    ap.add_argument("--python", default="python3")
    args = ap.parse_args(argv)

    topo = Topology.from_path(args.topology)
    repo_root = str(Path(__file__).resolve().parents[2])
    cmds = plan_commands(
        topo, repo_root, args.bundles, args.repo_dest, args.data_dest,
        start=args.start, ssh_user=args.ssh_user, ssh_opts=args.ssh_opts,
        python=args.python,
    )
    if not cmds:
        sys.stderr.write("topology has no host-addressed workers\n")
        return 1
    for cmd in cmds:
        print(" ".join(shlex.quote(c) for c in cmd))
        if args.run:
            r = subprocess.run(cmd)
            if r.returncode != 0:
                sys.stderr.write(
                    f"command failed (rc={r.returncode}); stopping\n")
                return r.returncode
    if not args.run:
        sys.stderr.write(f"dry run: {len(cmds)} commands printed "
                         "(pass --run to execute)\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
