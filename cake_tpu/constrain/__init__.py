"""Structured generation: grammar-constrained decoding (ISSUE 8).

``fsm`` compiles a constraint spec (regex, or JSON Schema lowered to
regex) into a token-level DFA over the tokenizer vocab — cached in
process and on disk; ``guide`` holds the per-stream host-side DFA
cursor the engines advance between compiled decode steps. The mask
application itself lives inside the compiled decode step
(ops/sampling.py + parallel/pipeline.py), gathered from a
device-resident packed bitmask table so constrained decode neither
retraces nor round-trips logits to the host.
"""

from cake_tpu.constrain.fsm import (  # noqa: F401
    RegexError,
    TokenDFA,
    build_token_dfa,
    compile_constraint,
    json_schema_to_regex,
    spec_to_regex,
    token_strings,
)
from cake_tpu.constrain.guide import Guide, guide_for  # noqa: F401
