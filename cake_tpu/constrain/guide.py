"""Per-stream constrained-decoding state: one Guide per request.

A :class:`Guide` holds the host-side DFA cursor for one stream over a
shared (cached) :class:`~cake_tpu.constrain.fsm.TokenDFA`. The split of
labor with the engine is the whole design (ISSUE 8 / CK-JIT): the DFA
*advance* is a host-side table lookup between steps — it never traces —
while the *mask application* is a gather from the device-resident packed
bitmask table inside the compiled decode step, indexed by the engine's
per-slot ``mask_row`` vector. The Guide exposes exactly the two numbers
that plumbing needs: the current ``state`` (= mask row index within its
DFA's block of table rows) and ``dead_end`` (the retire-with-
finish_reason-"constraint" signal, counted in ``constrain.dead_ends``).
"""

from __future__ import annotations

import numpy as np

from cake_tpu.constrain.fsm import (
    TokenDFA,
    cached_token_strings,
    compile_constraint,
    spec_to_regex,
)
from cake_tpu.obs import metrics as obs_metrics

# incremented by the engines when a constrained stream is retired at a
# state with an all-zero mask (no token, not even EOS, can be emitted)
DEAD_ENDS = obs_metrics.counter("constrain.dead_ends")


class Guide:
    """Host-side DFA cursor for one constrained stream.

    ``spec`` (optional) is the serve-plane ``response_format`` body the
    DFA compiled from. Carrying it lets the disagg plane export a
    constrained stream mid-grammar: the snapshot ships the spec + the
    integer cursor, and the importer recompiles the (cached) DFA and
    resumes exactly where the exporter stopped.
    """

    def __init__(self, dfa: TokenDFA, spec: dict | None = None):
        self.dfa = dfa
        self.spec = spec
        self.state = dfa.start

    def reset(self) -> None:
        self.state = self.dfa.start

    def advance(self, tok_id: int) -> bool:
        """Step the cursor on an emitted token. False = the token has no
        transition (cannot happen when sampling was masked by this
        guide's row; defensively treated as a dead end by callers)."""
        nxt = int(self.dfa.trans[self.state, tok_id])
        if nxt < 0:
            return False
        self.state = nxt
        return True

    def allows(self, tok_id: int) -> bool:
        row = self.dfa.mask_bits[self.state]
        return bool((row[tok_id >> 3] >> (tok_id & 7)) & 1)

    @property
    def dead_end(self) -> bool:
        """No emittable token at the current state (not even EOS)."""
        return not self.dfa.mask_bits[self.state].any()

    @property
    def accepting(self) -> bool:
        return bool(self.dfa.accepting[self.state])

    def mask_bool(self) -> np.ndarray:
        """Unpacked [V] bool allowed mask at the current state — for the
        host-side first-token sampling (prefill / admission), where the
        logits are already on the host path."""
        return self.dfa.mask_bool(self.state)


def guide_for(spec: dict, tokenizer, config) -> Guide:
    """A serve-plane ``response_format`` body -> fresh :class:`Guide`
    against this engine's tokenizer + config (compile cached at the
    TokenDFA layer; the Guide itself is per-request state)."""
    if tokenizer is None:
        raise ValueError(
            "response_format needs a server-side tokenizer (the grammar "
            "compiles against the vocab's decoded strings)")
    pattern = spec_to_regex(spec)
    vocab = cached_token_strings(tokenizer, config.vocab_size)
    dfa = compile_constraint(pattern, vocab, eos_ids=config.eos_ids())
    return Guide(dfa, spec=spec)
