"""Grammar -> token-level DFA compiler for constrained decoding.

The Outlines lesson (Willard & Louf 2023, PAPERS.md): a regular grammar
over *characters* lowers to a finite automaton over the *tokenizer
vocabulary* — for every automaton state, walk each vocab token's decoded
string through the character automaton; tokens whose walk survives are
the state's allowed set, and the walk's end state is the transition.
Constrained decoding is then one table lookup per emitted token on the
host plus one mask application on device — no per-token grammar work in
the hot path.

Pipeline here, stdlib + numpy only (no `interegular`/`outlines` in the
container):

1. a small regex engine — parse (literals, classes, escapes, ``.``,
   ``| ( ) * + ? {m,n}``; fullmatch semantics) -> Thompson NFA;
2. JSON Schema lowered to such a regex (``json_schema_to_regex``), with
   *bounded* repetitions everywhere so the lowered automaton is acyclic
   — a constrained stream provably terminates inside its token budget;
3. lazy subset construction driven by the vocab's actual strings
   (`build_token_dfa`): DFA states are discovered NFA-subset closures,
   yielding a ``trans [S, V] int32`` table (-1 = disallowed) and the
   per-state allowed-token masks packed little-endian as a
   ``mask_bits [S, ceil(V/8)] uint8`` array — the exact layout the
   engine uploads to device once and gathers from inside the compiled
   decode step (runtime/batch_generator.py).

EOS token ids never participate as *text* (a toy tokenizer may map the
EOS id onto a printable char — it must not satisfy a ``"`` transition);
they are OR'd into the mask of *accepting* states only, so a stream can
end exactly when its grammar is complete — and MUST end when the
accepting state has no outgoing transitions (the mask forces EOS).

Compiles are cached two ways: an in-process memo and a disk cache keyed
by content hash of (pattern, vocab, eos ids) under ``CAKE_FSM_CACHE_DIR``
(default ``~/.cache/cake_tpu/fsm``), because the vocab walk is
O(states x vocab x token length) and real vocabs are 32k+. Cache traffic
lands in ``constrain.fsm_cache_hits/misses``; compile wall in
``constrain.fsm_compile_ms``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time

import numpy as np

from cake_tpu.obs import metrics as obs_metrics

FSM_COMPILE_MS = obs_metrics.histogram("constrain.fsm_compile_ms")
FSM_CACHE_HITS = obs_metrics.counter("constrain.fsm_cache_hits")
FSM_CACHE_MISSES = obs_metrics.counter("constrain.fsm_cache_misses")

_MAX_CP = 0x10FFFF
_MAX_STATES = 4096  # subset-construction guard: beyond this, refuse
_CACHE_VERSION = "cakefsm1"

# -- regex parsing -----------------------------------------------------------
# AST: ("chars", ranges) | ("cat", [n..]) | ("alt", [n..])
#      | ("rep", node, min, max_or_None)
# ranges: sorted tuple of inclusive (lo, hi) codepoint pairs.

_ESCAPE_CLASSES = {
    "d": ((ord("0"), ord("9")),),
    "w": ((ord("0"), ord("9")), (ord("A"), ord("Z")), (ord("_"), ord("_")),
          (ord("a"), ord("z"))),
    "s": ((9, 10), (12, 13), (32, 32)),
}
_ESCAPE_CHARS = {"n": "\n", "r": "\r", "t": "\t", "f": "\f", "v": "\v",
                 "0": "\0"}


def _norm_ranges(ranges):
    """Sort + merge overlapping/adjacent inclusive ranges."""
    out: list[list[int]] = []
    for lo, hi in sorted(ranges):
        if out and lo <= out[-1][1] + 1:
            out[-1][1] = max(out[-1][1], hi)
        else:
            out.append([lo, hi])
    return tuple((lo, hi) for lo, hi in out)


def _negate_ranges(ranges):
    out, prev = [], 0
    for lo, hi in _norm_ranges(ranges):
        if lo > prev:
            out.append((prev, lo - 1))
        prev = hi + 1
    if prev <= _MAX_CP:
        out.append((prev, _MAX_CP))
    return tuple(out)


def _in_ranges(ranges, cp: int) -> bool:
    for lo, hi in ranges:
        if lo <= cp <= hi:
            return True
        if cp < lo:
            return False
    return False


class RegexError(ValueError):
    pass


class _Parser:
    """Recursive-descent parser for the supported regex subset."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _next(self):
        ch = self._peek()
        if ch is None:
            raise RegexError(f"unexpected end of pattern: {self.p!r}")
        self.i += 1
        return ch

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise RegexError(
                f"unbalanced pattern at char {self.i} of {self.p!r}")
        return node

    def _alt(self):
        arms = [self._concat()]
        while self._peek() == "|":
            self._next()
            arms.append(self._concat())
        return arms[0] if len(arms) == 1 else ("alt", arms)

    def _concat(self):
        items = []
        while self._peek() not in (None, "|", ")"):
            items.append(self._repeat())
        if not items:
            return ("cat", [])
        return items[0] if len(items) == 1 else ("cat", items)

    def _repeat(self):
        node = self._atom()
        ch = self._peek()
        if ch == "*":
            self._next()
            return ("rep", node, 0, None)
        if ch == "+":
            self._next()
            return ("rep", node, 1, None)
        if ch == "?":
            self._next()
            return ("rep", node, 0, 1)
        if ch == "{":
            save = self.i
            self._next()
            body = ""
            while self._peek() not in (None, "}"):
                body += self._next()
            if self._peek() != "}" or not _rep_body_ok(body):
                self.i = save  # literal '{' (e.g. inside JSON skeletons)
                return node
            self._next()
            lo, _, hi = body.partition(",")
            m = int(lo)
            n = m if not _has_comma(body) else (int(hi) if hi else None)
            if n is not None and n < m:
                raise RegexError(f"bad repetition {{{body}}} in {self.p!r}")
            return ("rep", node, m, n)
        return node

    def _atom(self):
        ch = self._next()
        if ch == "(":
            if self.p[self.i:self.i + 2] == "?:":
                self.i += 2  # non-capturing marker; groups never capture
            node = self._alt()
            if self._next() != ")":
                raise RegexError(f"unclosed group in {self.p!r}")
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            # any char except newline (re.fullmatch semantics)
            return ("chars", _negate_ranges(((10, 10),)))
        if ch == "\\":
            return self._escape()
        if ch in ")|*+?":
            raise RegexError(f"dangling {ch!r} in {self.p!r}")
        cp = ord(ch)
        return ("chars", ((cp, cp),))

    def _escape(self):
        ch = self._next()
        if ch in _ESCAPE_CLASSES:
            return ("chars", _norm_ranges(_ESCAPE_CLASSES[ch]))
        if ch.upper() in _ESCAPE_CLASSES and ch.isupper():
            return ("chars",
                    _negate_ranges(_ESCAPE_CLASSES[ch.lower()]))
        if ch in _ESCAPE_CHARS:
            cp = ord(_ESCAPE_CHARS[ch])
            return ("chars", ((cp, cp),))
        cp = ord(ch)  # \. \" \\ \[ \{ ... : the char itself
        return ("chars", ((cp, cp),))

    def _class_atom(self) -> tuple[tuple[tuple[int, int], ...], bool]:
        """One class member -> (ranges, is_single_char)."""
        ch = self._next()
        if ch == "\\":
            nxt = self._next()
            if nxt in _ESCAPE_CLASSES:
                return _norm_ranges(_ESCAPE_CLASSES[nxt]), False
            if nxt.upper() in _ESCAPE_CLASSES and nxt.isupper():
                return _negate_ranges(_ESCAPE_CLASSES[nxt.lower()]), False
            c = _ESCAPE_CHARS.get(nxt, nxt)
            return ((ord(c), ord(c)),), True
        return ((ord(ch), ord(ch)),), True

    def _char_class(self):
        negated = False
        if self._peek() == "^":
            self._next()
            negated = True
        ranges: list[tuple[int, int]] = []
        if self._peek() == "]":  # leading ] is literal
            self._next()
            ranges.append((ord("]"), ord("]")))
        while True:
            ch = self._peek()
            if ch is None:
                raise RegexError(f"unclosed class in {self.p!r}")
            if ch == "]":
                self._next()
                break
            r, single = self._class_atom()
            if (single and self._peek() == "-"
                    and self.p[self.i + 1:self.i + 2] not in ("]", "")):
                self._next()
                r2, single2 = self._class_atom()
                if not single2 or r2[0][0] < r[0][0]:
                    raise RegexError(f"bad range in class: {self.p!r}")
                ranges.append((r[0][0], r2[0][0]))
            else:
                ranges.extend(r)
        out = _norm_ranges(ranges)
        return ("chars", _negate_ranges(out) if negated else out)


def _rep_body_ok(body: str) -> bool:
    lo, comma, hi = body.partition(",")
    if not lo.isdigit():
        return False
    return (not comma) or hi == "" or hi.isdigit()


def _has_comma(body: str) -> bool:
    return "," in body


# -- Thompson NFA ------------------------------------------------------------

class _NFA:
    """eps[s] -> [targets]; chars[s] -> [(ranges, target)]."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.chars: list[list[tuple[tuple, int]]] = []
        self.start = 0
        self.accept = 0

    def new_state(self) -> int:
        self.eps.append([])
        self.chars.append([])
        return len(self.eps) - 1


def _build_frag(nfa: _NFA, node) -> tuple[int, int]:
    """Thompson-construct one AST node; returns (start, accept)."""
    kind = node[0]
    if kind == "chars":
        s, a = nfa.new_state(), nfa.new_state()
        nfa.chars[s].append((node[1], a))
        return s, a
    if kind == "cat":
        s = a = nfa.new_state()
        for child in node[1]:
            cs, ca = _build_frag(nfa, child)
            nfa.eps[a].append(cs)
            a = ca
        return s, a
    if kind == "alt":
        s, a = nfa.new_state(), nfa.new_state()
        for child in node[1]:
            cs, ca = _build_frag(nfa, child)
            nfa.eps[s].append(cs)
            nfa.eps[ca].append(a)
        return s, a
    if kind == "rep":
        _, child, m, n = node
        s = a = nfa.new_state()
        for _ in range(m):  # mandatory copies
            cs, ca = _build_frag(nfa, child)
            nfa.eps[a].append(cs)
            a = ca
        if n is None:  # unbounded tail: one looping copy
            cs, ca = _build_frag(nfa, child)
            nfa.eps[a].append(cs)
            nfa.eps[ca].append(cs)
            end = nfa.new_state()
            nfa.eps[a].append(end)
            nfa.eps[ca].append(end)
            return s, end
        skips = [a]
        for _ in range(n - m):  # optional copies
            cs, ca = _build_frag(nfa, child)
            nfa.eps[a].append(cs)
            a = ca
            skips.append(a)
        end = nfa.new_state()
        for sk in skips[:-1]:
            nfa.eps[sk].append(end)
        nfa.eps[a].append(end)
        return s, end
    raise AssertionError(f"unknown AST node {kind}")


def compile_nfa(pattern: str) -> _NFA:
    ast = _Parser(pattern).parse()
    nfa = _NFA()
    nfa.start, nfa.accept = _build_frag(nfa, ast)
    return nfa


def _closure(nfa: _NFA, states) -> frozenset:
    seen = set(states)
    work = list(states)
    while work:
        s = work.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                work.append(t)
    return frozenset(seen)


# -- token-level DFA ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TokenDFA:
    """A grammar compiled against one tokenizer vocabulary.

    ``trans[s, v]`` is the next state after emitting token ``v`` from
    state ``s`` (-1: disallowed). ``mask_bits[s]`` packs the allowed-token
    bitmask for state ``s`` little-endian (bit ``v & 7`` of byte
    ``v >> 3``) — the row layout the engine's device-resident mask table
    uses verbatim. EOS ids are allowed (mask only) in accepting states.
    """

    trans: np.ndarray          # [S, V] int32
    mask_bits: np.ndarray      # [S, ceil(V/8)] uint8
    accepting: np.ndarray      # [S] bool
    pattern: str
    start: int = 0

    @property
    def num_states(self) -> int:
        return self.trans.shape[0]

    @property
    def vocab_size(self) -> int:
        return self.trans.shape[1]

    def mask_bool(self, state: int) -> np.ndarray:
        """Unpacked [V] bool allowed mask for one state (host-side
        sampling of prefill/admission first tokens)."""
        bits = np.unpackbits(self.mask_bits[state], bitorder="little")
        return bits[: self.vocab_size].astype(bool)


def build_token_dfa(pattern: str, vocab: list[str],
                    eos_ids=()) -> TokenDFA:
    """Subset construction over the vocab's decoded strings (see module
    docstring). Empty-string tokens are never allowed — a zero-width
    transition would let a stream emit forever without advancing the
    grammar. EOS ids never match as text; accepting states allow them
    in the mask only."""
    nfa = compile_nfa(pattern)
    eos = {int(e) for e in eos_ids}
    vocab_n = len(vocab)
    start = _closure(nfa, (nfa.start,))
    index: dict[frozenset, int] = {start: 0}
    order = [start]
    step_memo: dict[tuple[frozenset, str], frozenset] = {}

    def step(sub: frozenset, ch: str) -> frozenset:
        key = (sub, ch)
        hit = step_memo.get(key)
        if hit is not None:
            return hit
        cp = ord(ch)
        nxt = {t for s in sub for rng, t in nfa.chars[s]
               if _in_ranges(rng, cp)}
        out = _closure(nfa, nxt) if nxt else frozenset()
        step_memo[key] = out
        return out

    rows: list[np.ndarray] = []
    w = 0
    while w < len(order):
        sub = order[w]
        w += 1
        row = np.full((vocab_n,), -1, np.int32)
        for tid, text in enumerate(vocab):
            if not text or tid in eos:
                continue
            cur = sub
            for ch in text:
                cur = step(cur, ch)
                if not cur:
                    break
            if not cur:
                continue
            nxt = index.get(cur)
            if nxt is None:
                nxt = index[cur] = len(order)
                order.append(cur)
                if len(order) > _MAX_STATES:
                    raise RegexError(
                        f"constraint too complex: > {_MAX_STATES} token-DFA "
                        f"states for pattern {pattern!r}")
            row[tid] = nxt
        rows.append(row)

    trans = np.stack(rows)
    accepting = np.asarray([nfa.accept in sub for sub in order], bool)
    allowed = trans >= 0
    for e in eos:
        if 0 <= e < vocab_n:
            allowed[accepting, e] = True
    mask_bits = np.packbits(allowed, axis=1, bitorder="little")
    return TokenDFA(trans=trans, mask_bits=mask_bits, accepting=accepting,
                    pattern=pattern)


# -- JSON Schema -> regex ----------------------------------------------------

_JSON_STR_CHAR = '[ !#-\\[\\]-~]'  # printable ASCII minus '"' and '\'
_INT_RE = "(-?(0|[1-9][0-9]{0,8}))"
_NUM_RE = "(-?(0|[1-9][0-9]{0,8})(\\.[0-9]{1,6})?)"


def _esc_literal(text: str) -> str:
    out = []
    for ch in text:
        if ch in ".^$*+?()[]{}|\\":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def json_schema_to_regex(schema: dict, _depth: int = 0) -> str:
    """Lower a JSON Schema subset to a regex over the canonical rendering
    (no insignificant whitespace except one space after ``:`` and ``,``).

    Supported: object (properties in declaration order — all listed
    properties are emitted; JSON-Schema optionality is out of scope),
    array (minItems/maxItems, default 0..4), string (maxLength, default
    48; ``pattern`` used verbatim for the content; ``enum``/``const``),
    integer, number, boolean, null. Every repetition is BOUNDED so the
    lowered automaton is acyclic: a constrained stream always reaches an
    accepting state (where only EOS is allowed if the grammar is done)
    within a computable token budget.
    """
    if _depth > 8:
        raise RegexError("json schema nests deeper than 8 levels")
    if not isinstance(schema, dict):
        raise RegexError("json schema must be an object")
    if "enum" in schema:
        import json as _json

        arms = [_esc_literal(_json.dumps(v)) for v in schema["enum"]]
        if not arms:
            raise RegexError("empty enum")
        return "(" + "|".join(arms) + ")"
    if "const" in schema:
        import json as _json

        return _esc_literal(_json.dumps(schema["const"]))
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties") or {}
        if not props:
            return "\\{\\}"
        parts = []
        for name, sub in props.items():
            parts.append('"%s": %s' % (
                _esc_literal(name), json_schema_to_regex(sub, _depth + 1)))
        return "\\{" + ", ".join(parts) + "\\}"
    if t == "array":
        item = json_schema_to_regex(schema.get("items") or {"type": "integer"},
                                    _depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", max(lo, 4)))
        if hi < lo:
            raise RegexError("maxItems < minItems")
        if hi == 0:
            return "\\[\\]"
        tail = "(, %s){0,%d}" % (item, hi - 1) if hi > 1 else ""
        body = "%s%s" % (item, tail)
        if lo == 0:
            return "\\[(%s)?\\]" % body
        return "\\[%s\\]" % body
    if t == "string":
        if "pattern" in schema:
            return '"%s"' % schema["pattern"]
        lo = int(schema.get("minLength", 0))
        hi = int(schema.get("maxLength", 48))
        return '"%s{%d,%d}"' % (_JSON_STR_CHAR, lo, hi)
    if t == "integer":
        return _INT_RE
    if t == "number":
        return _NUM_RE
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    raise RegexError(f"unsupported json schema: {schema!r}")


def spec_to_regex(spec: dict) -> str:
    """A serve-plane ``response_format`` body -> regex. Accepts
    ``{"type": "regex", "pattern"|"regex": ...}`` and
    ``{"type": "json_schema", "schema": ...}`` (also the OpenAI-style
    nesting ``{"json_schema": {"schema": ...}}``)."""
    if not isinstance(spec, dict):
        raise RegexError("'response_format' must be an object")
    kind = spec.get("type")
    if kind == "regex":
        pat = spec.get("pattern") or spec.get("regex")
        if not isinstance(pat, str) or not pat:
            raise RegexError("regex response_format needs a 'pattern'")
        return pat
    if kind == "json_schema":
        schema = spec.get("schema")
        if schema is None and isinstance(spec.get("json_schema"), dict):
            schema = spec["json_schema"].get("schema")
        if not isinstance(schema, dict):
            raise RegexError("json_schema response_format needs a 'schema'")
        return json_schema_to_regex(schema)
    raise RegexError(
        f"response_format type must be 'json_schema' or 'regex', "
        f"got {kind!r}")


# -- vocab extraction + caching ---------------------------------------------

def token_strings(tokenizer, vocab_size: int) -> list[str]:
    """Decode every vocab id standalone. Ids the tokenizer cannot decode
    (or that decode to nothing) become '' — never allowed by any DFA."""
    out = []
    for i in range(vocab_size):
        try:
            out.append(tokenizer.decode([i]) or "")
        except Exception:
            out.append("")
    return out


_VOCAB_CACHE: dict[int, tuple[object, list[str]]] = {}


def cached_token_strings(tokenizer, vocab_size: int) -> list[str]:
    """Per-tokenizer memo of :func:`token_strings` (the decode sweep is
    O(vocab); serve handlers call this per request)."""
    hit = _VOCAB_CACHE.get(id(tokenizer))
    if hit is not None and hit[0] is tokenizer and len(hit[1]) == vocab_size:
        return hit[1]
    strings = token_strings(tokenizer, vocab_size)
    if len(_VOCAB_CACHE) > 4:
        _VOCAB_CACHE.clear()
    _VOCAB_CACHE[id(tokenizer)] = (tokenizer, strings)
    return strings


def _vocab_digest(vocab: list[str]) -> str:
    h = hashlib.sha256()
    for s in vocab:
        h.update(s.encode("utf-8", "surrogatepass"))
        h.update(b"\x00")
    return h.hexdigest()[:16]


def _cache_dir() -> str:
    return os.environ.get(
        "CAKE_FSM_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "cake_tpu", "fsm"),
    )


# in-process DFA memo, LRU-capped: a trans table can reach
# _MAX_STATES x vocab int32 (~0.5 GB at 32k vocab), and patterns arrive
# from CLIENTS on the serve plane — unbounded growth would be a
# memory-exhaustion vector (the disk cache bounds only compile time,
# not RSS)
_MEMO: dict[str, TokenDFA] = {}
_MEMO_CAP = 16


def _memo_put(key: str, dfa: TokenDFA) -> None:
    _MEMO.pop(key, None)
    _MEMO[key] = dfa
    while len(_MEMO) > _MEMO_CAP:
        _MEMO.pop(next(iter(_MEMO)))


def compile_constraint(pattern: str, vocab: list[str], eos_ids=(),
                       cache_dir: str | None = None) -> TokenDFA:
    """Pattern + vocab -> :class:`TokenDFA`, through the in-process memo
    and the on-disk cache (content-hash keyed; a cache entry is exactly
    the three arrays, np.savez'd). Misses compile and try to populate
    the disk cache (write failures are non-fatal: the cache is an
    optimization, not a dependency)."""
    key = hashlib.sha256("|".join((
        _CACHE_VERSION, pattern, str(sorted(int(e) for e in eos_ids)),
        str(len(vocab)), _vocab_digest(vocab),
    )).encode()).hexdigest()
    hit = _MEMO.get(key)
    if hit is not None:
        FSM_CACHE_HITS.inc()
        _memo_put(key, hit)  # bump to MRU
        return hit
    path = os.path.join(cache_dir or _cache_dir(), key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path, allow_pickle=False) as z:
                dfa = TokenDFA(
                    trans=z["trans"], mask_bits=z["mask_bits"],
                    accepting=z["accepting"], pattern=pattern,
                )
            FSM_CACHE_HITS.inc()
            _memo_put(key, dfa)
            return dfa
        except Exception:
            pass  # corrupt entry: fall through to a fresh compile
    FSM_CACHE_MISSES.inc()
    t0 = time.perf_counter()
    dfa = build_token_dfa(pattern, vocab, eos_ids)
    FSM_COMPILE_MS.observe((time.perf_counter() - t0) * 1e3)
    _memo_put(key, dfa)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, trans=dfa.trans, mask_bits=dfa.mask_bits,
                     accepting=dfa.accepting)
        os.replace(tmp, path)
    except OSError:
        pass
    return dfa
