"""Llama-3 decoder in pure functional JAX.

Equivalent of the reference model stack (`cake-core/src/model/{llama,
transformer,attention,mlp}.rs`): token embedding + N pre-norm decoder blocks +
final RMSNorm + lm_head (llama.rs:61-76,79-143), with each block =
``rms_1 -> attn -> +residual -> rms_2 -> SwiGLU -> +residual``
(transformer.rs:48-64).

TPU-first design decisions:

- **Stacked layer weights + lax.scan.** Every per-layer weight is stored with
  a leading ``[num_layers, ...]`` axis and the block loop is a single
  ``lax.scan`` (llama.rs walks a ``Vec<Box<dyn Forwarder>>`` in Python-style
  loop, llama.rs:88-119). Scan compiles the block body once for 32/80 layers,
  and the layer axis is exactly the axis a pipeline stage shards over.
- **Functional params pytree**, no framework modules: params flow through
  `jit`/`shard_map` and shard with `NamedSharding` without indirection.
- **Static shapes everywhere**: the KV cache is preallocated
  (:mod:`cake_tpu.ops.kvcache`), decode and prefill are two jit signatures.
- `forward_layers` runs an arbitrary contiguous slice of blocks — the same
  entry point serves the single-chip model, a pipeline stage, and a remote
  worker executing its topology-assigned range (worker.rs:85-98).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from cake_tpu.models.config import LlamaConfig
from cake_tpu.ops import quant
from cake_tpu.ops.attention import self_attention_block
from cake_tpu.ops.kvcache import KVCache
from cake_tpu.ops.mlp import swiglu
from cake_tpu.ops.moe import moe_swiglu
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.rope import rope_tables

Params = dict[str, Any]

# Stacked per-layer weight names -> shape builders (L = num layers), for the
# dense-MLP, bias-free Llama base shape. :func:`layer_shapes` extends this
# per model family (Qwen2 q/k/v bias, Mixtral routed experts).
_LAYER_SHAPES = {
    "attn_norm": lambda c: (c.hidden_size,),
    "wq": lambda c: (c.hidden_size, c.num_attention_heads * c.head_dim),
    "wk": lambda c: (c.hidden_size, c.num_key_value_heads * c.head_dim),
    "wv": lambda c: (c.hidden_size, c.num_key_value_heads * c.head_dim),
    "wo": lambda c: (c.num_attention_heads * c.head_dim, c.hidden_size),
    "mlp_norm": lambda c: (c.hidden_size,),
    "w_gate": lambda c: (c.hidden_size, c.intermediate_size),
    "w_up": lambda c: (c.hidden_size, c.intermediate_size),
    "w_down": lambda c: (c.intermediate_size, c.hidden_size),
}

_BIAS_SHAPES = {
    "bq": lambda c: (c.num_attention_heads * c.head_dim,),
    "bk": lambda c: (c.num_key_value_heads * c.head_dim,),
    "bv": lambda c: (c.num_key_value_heads * c.head_dim,),
}

_MOE_SHAPES = {
    "router": lambda c: (c.hidden_size, c.num_local_experts),
    "w_gate": lambda c: (c.num_local_experts, c.hidden_size,
                         c.intermediate_size),
    "w_up": lambda c: (c.num_local_experts, c.hidden_size,
                       c.intermediate_size),
    "w_down": lambda c: (c.num_local_experts, c.intermediate_size,
                         c.hidden_size),
}


def layer_shapes(config: LlamaConfig) -> dict:
    """Per-layer weight name -> shape (without the leading ``[L]`` axis) for
    the given model family: the Llama base, plus q/k/v biases when
    ``attention_bias`` (Qwen2), with the dense MLP replaced by router +
    stacked expert weights when ``num_local_experts > 0`` (Mixtral)."""
    shapes = dict(_LAYER_SHAPES)
    if config.attention_bias:
        shapes.update(_BIAS_SHAPES)
    if config.num_local_experts:
        shapes.update(_MOE_SHAPES)
    return shapes


def init_params(config: LlamaConfig, key: jax.Array, dtype=None) -> Params:
    """Random-init params pytree (test fixtures / benchmarks; real weights
    come from :mod:`cake_tpu.utils.weights`)."""
    dt = dtype or config.jax_dtype
    L = config.num_hidden_layers
    shapes = layer_shapes(config)
    keys = iter(jax.random.split(key, len(shapes) + 3))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(dt)

    layers = {}
    for name, shape_fn in shapes.items():
        shape = shape_fn(config)
        k = next(keys)
        if name.endswith("norm"):
            layers[name] = jnp.ones((L,) + shape, dt)
        elif name.startswith("b"):
            # biases: small random so tests exercise a nonzero bias path
            layers[name] = (0.02 * jax.random.normal(k, (L,) + shape,
                                                     jnp.float32)).astype(dt)
        else:
            # fan_in is the next-to-last axis for 3D expert stacks
            # ([E, in, out]) and the first axis for plain [in, out] linears
            fan_in = shape[-2] if len(shape) == 3 else shape[0]
            layers[name] = dense(k, (L,) + shape, fan_in)
    return {
        "embed": dense(next(keys), (config.vocab_size, config.hidden_size),
                       config.hidden_size),
        "layers": layers,
        "norm_f": jnp.ones((config.hidden_size,), dt),
        "lm_head": dense(next(keys), (config.hidden_size, config.vocab_size),
                         config.hidden_size),
    }


def init_params_int8(config: LlamaConfig, key: jax.Array, dtype=None) -> Params:
    """Random-init params with every linear quantized to int8 — *without*
    ever materializing the full bf16/f32 model on device.

    :func:`init_params` + ``quantize_params`` peaks at full-precision bytes
    plus int8 bytes, which cannot fit Llama-3-8B on a 16 GiB v5e chip
    (~14.5 GiB usable). Here each stacked linear is generated and quantized
    inside one jitted ``lax.map`` over layers, so the f32 temporaries are
    per-layer-sized and freed at jit exit; peak stays near the int8 total.
    """
    return _init_params_quantized(config, key, dtype, bits=8)


def init_params_int4(config: LlamaConfig, key: jax.Array, dtype=None) -> Params:
    """Random-init params with every linear packed-int4 quantized
    (:class:`cake_tpu.ops.quant.Quantized4Linear`) — quarter the bf16 weight
    bytes, the bandwidth tier below :func:`init_params_int8`."""
    return _init_params_quantized(config, key, dtype, bits=4)


def _init_params_quantized(config, key, dtype, *, bits: int) -> Params:
    from functools import partial as _partial

    if config.num_local_experts and bits == 4:
        from cake_tpu.ops.quant import reject_int4_moe

        reject_int4_moe()

    from cake_tpu.ops.quant import (
        LAYER_LINEARS,
        Quantized4Linear,
        QuantizedLinear,
        quantize_linear,
        quantize_linear4,
    )

    if bits == 8:
        qfn, cls = quantize_linear, QuantizedLinear
        fields = ("q", "scale")
    else:
        qfn, cls = quantize_linear4, Quantized4Linear
        fields = ("qp", "scale")

    dt = dtype or config.jax_dtype
    L = config.num_hidden_layers
    shapes = layer_shapes(config)
    keys = iter(jax.random.split(key, len(shapes) + 3))

    @_partial(jax.jit, static_argnums=(1, 2, 3))
    def qdense(k, shape, fan_in, stacked):
        def one(kk):
            w = jax.random.normal(kk, shape, jnp.float32) / jnp.sqrt(fan_in)
            ql = qfn(w)  # the one quantization convention per tier
            return tuple(getattr(ql, f) for f in fields)

        if not stacked:
            return one(k)
        return jax.lax.map(one, jax.random.split(k, L))

    layers = {}
    for name, shape_fn in shapes.items():
        shape = shape_fn(config)
        k = next(keys)
        if name in LAYER_LINEARS:
            fan_in = shape[-2] if len(shape) == 3 else shape[0]
            q, scale = qdense(k, shape, fan_in, True)
            layers[name] = cls(q, scale)
        elif name == "router":  # tiny, stays full precision
            layers[name] = (
                jax.random.normal(k, (L,) + shape, jnp.float32)
                / jnp.sqrt(shape[0])
            ).astype(dt)
        elif name.startswith("b"):  # q/k/v biases stay full precision
            layers[name] = (0.02 * jax.random.normal(k, (L,) + shape,
                                                     jnp.float32)).astype(dt)
        else:  # norms
            layers[name] = jnp.ones((L,) + shape, dt)

    embed = (
        jax.random.normal(
            next(keys), (config.vocab_size, config.hidden_size), jnp.float32
        )
        / jnp.sqrt(config.hidden_size)
    ).astype(dt)
    hq, hscale = qdense(
        next(keys), (config.hidden_size, config.vocab_size),
        config.hidden_size, False,
    )
    return {
        "embed": embed,
        "layers": layers,
        "norm_f": jnp.ones((config.hidden_size,), dt),
        "lm_head": cls(hq, hscale),
    }


def embed_tokens(params: Params, tokens, config: LlamaConfig) -> jax.Array:
    """Token embedding lookup — THE embedding entry point for every
    execution path (local, pipeline builders, admission, speculation).
    Gemma multiplies the embedding output by sqrt(hidden) (``embed_scale``),
    with the normalizer rounded to the activation dtype exactly as HF does,
    so family deltas cannot drift between paths."""
    x = params["embed"][tokens].astype(config.jax_dtype)
    if config.embed_scale:
        x = x * jnp.asarray(config.hidden_size ** 0.5, config.jax_dtype)
    return x


def block_forward(
    layer: Params,  # one layer's weights (no leading L axis)
    x: jax.Array,  # [B, T, hidden]
    k_cache: jax.Array,  # [B, kv_heads, S, D]
    v_cache: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    pos,
    config: LlamaConfig,
    num_heads: int | None = None,
    num_kv_heads: int | None = None,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    sp_size: int = 1,
    write_gate: jax.Array | None = None,
    sp_prefill: bool | None = None,
    sp_chunk: bool = False,
    ep_axis: str | None = None,
    ep_size: int | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One pre-norm decoder block (transformer.rs:48-64).

    Under tensor parallelism (inside shard_map), ``num_heads``/``num_kv_heads``
    are the per-device local counts and ``tp_axis`` names the mesh axis the
    row-parallel projections reduce over; the norm weights are replicated.
    ``sp_axis``/``sp_size``: sequence-parallel attention (ring prefill /
    distributed flash decode, see :mod:`cake_tpu.ops.ring`); the MLP needs no
    communication — it is elementwise over the sharded sequence.
    ``ep_axis``/``ep_size``: expert parallelism for MoE layers
    (:mod:`cake_tpu.ops.moe`) — the expert stack is sharded over it and the
    routed combine psums across it.

    Model-family deltas dispatch on the layer pytree itself: q/k/v bias
    arrays (``bq``/``bk``/``bv``, Qwen2) and a ``router`` + expert-stacked
    MLP (Mixtral) are used iff present; ``config.sliding_window`` (Mistral)
    narrows the causal mask.
    """
    h = rms_norm(x, layer["attn_norm"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
    attn_out, k_cache, v_cache = self_attention_block(
        h, layer["wq"], layer["wk"], layer["wv"], layer["wo"],
        k_cache, v_cache, cos, sin, pos,
        num_heads or config.num_attention_heads,
        num_kv_heads or config.num_key_value_heads,
        tp_axis=tp_axis,
        sp_axis=sp_axis,
        sp_size=sp_size,
        write_gate=write_gate,
        sp_prefill=sp_prefill,
        sp_chunk=sp_chunk,
        bq=layer.get("bq"),
        bk=layer.get("bk"),
        bv=layer.get("bv"),
        bo=layer.get("bo"),
        window=config.sliding_window,
    )
    x = x + attn_out
    h = rms_norm(x, layer["mlp_norm"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
    if "router" in layer:
        x = x + moe_swiglu(
            h, layer["router"], layer["w_gate"], layer["w_up"],
            layer["w_down"], top_k=config.num_experts_per_tok,
            ep_axis=ep_axis, ep_size=ep_size, tp_axis=tp_axis,
        )
    else:
        x = x + swiglu(h, layer["w_gate"], layer["w_up"], layer["w_down"],
                       tp_axis=tp_axis, act=config.hidden_act)
    return x, k_cache, v_cache


def forward_layers(
    layers: Params,  # stacked [L', ...] weights (any contiguous block range)
    x: jax.Array,  # [B, T, hidden]
    cache: KVCache,  # k/v: [L', B, kv_heads, S, D]
    cos: jax.Array,
    sin: jax.Array,
    pos,
    config: LlamaConfig,
    num_heads: int | None = None,
    num_kv_heads: int | None = None,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    sp_size: int = 1,
    write_gate: jax.Array | None = None,
    sp_prefill: bool | None = None,
    sp_chunk: bool = False,
    ep_axis: str | None = None,
    ep_size: int | None = None,
) -> tuple[jax.Array, KVCache]:
    """Run a contiguous run of decoder blocks via ``lax.scan``.

    This is the TPU-native `Forwarder::forward_batch` (cake/mod.rs:143-150,
    worker.rs:208-219): one call executes any number of contiguous layers with
    no per-layer dispatch.
    """

    def body(carry, per_layer):
        h = carry
        layer, kc, vc = per_layer
        h, kc, vc = block_forward(layer, h, kc, vc, cos, sin, pos, config,
                                  num_heads=num_heads, num_kv_heads=num_kv_heads,
                                  tp_axis=tp_axis, sp_axis=sp_axis,
                                  sp_size=sp_size, write_gate=write_gate,
                                  sp_prefill=sp_prefill, sp_chunk=sp_chunk,
                                  ep_axis=ep_axis, ep_size=ep_size)
        return h, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(body, x, (layers, cache.k, cache.v))
    return x, KVCache(k=k_new, v=v_new)


def forward(
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cache: KVCache,
    pos,
    config: LlamaConfig,
) -> tuple[jax.Array, KVCache]:
    """Full forward: embed -> blocks -> ln_f -> last position -> lm_head.

    Returns ``(logits [B, vocab] f32, new_cache)`` — logits taken at the last
    position and upcast to f32 exactly as the reference (llama.rs:124-143).
    """
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    x = embed_tokens(params, tokens, config)
    x, cache = forward_layers(params["layers"], x, cache, cos, sin, pos, config)
    x = rms_norm(x, params["norm_f"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
    x_last = x[:, -1, :]
    logits = quant.dense(x_last, params["lm_head"]).astype(jnp.float32)
    return logits, cache


def hidden_forward_layers(
    layers: Params,
    x: jax.Array,
    cache: KVCache,
    pos,
    config: LlamaConfig,
    max_seq: int | None = None,
) -> tuple[jax.Array, KVCache]:
    """Convenience wrapper that builds RoPE tables internally — the entry
    point a worker jits for its assigned block range (worker.rs:203-224)."""
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    return forward_layers(layers, x, cache, cos, sin, pos, config)
