"""Model architecture configuration.

TPU-native equivalent of the reference's config plane
(`cake-core/src/model/config.rs`): a dataclass deserialized from a HuggingFace
`config.json` (hidden/intermediate sizes, layer/head counts, `rms_norm_eps`,
`rope_theta`, bos/eos ids — config.rs:13-26), plus the generation-time maximum
sequence length (the reference hard-caps MAX_SEQ_LEN=4096, config.rs:6; here it
is a tunable because the TPU build supports long context).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Sequence

import jax.numpy as jnp

# Reference default (config.rs:6). Overridable per-config here.
DEFAULT_MAX_SEQ_LEN = 4096


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Llama-family architecture hyper-parameters.

    Field names mirror the HF ``config.json`` keys the reference reads
    (`config.rs:13-26`) so `from_hf_dict` is a direct mapping.
    """

    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    # HF `rope_scaling` dict, e.g. Llama-3.1's {"rope_type": "llama3",
    # "factor": 8.0, ...} or {"rope_type": "linear", "factor": N}. None = no
    # scaling (Llama-3.0, the reference's model of record).
    rope_scaling: dict | None = None
    bos_token_id: int | None = 128000
    eos_token_id: int | Sequence[int] | None = 128001
    tie_word_embeddings: bool = False
    max_seq_len: int = DEFAULT_MAX_SEQ_LEN
    dtype: str = "bfloat16"
    # --- model-family axes (all default to the Llama-3 shape) -------------
    # HF `model_type`: "llama" | "mistral" | "qwen2" | "mixtral". The same
    # functional decoder serves every family; the fields below are the only
    # architectural deltas (the reference serves exactly one family,
    # llama.rs — families are a capability extension of the Generator seam,
    # model/mod.rs:21-29).
    model_type: str = "llama"
    # q/k/v projection bias (Qwen2; HF Llama's `attention_bias` key maps
    # here too). Qwen2 itself is o-bias-free, but llama-arch
    # `attention_bias` checkpoints may carry an o_proj bias — the loaders
    # detect it per-checkpoint (utils/weights detect_family o_bias) and
    # attention plumbs it through, so no config field gates it.
    attention_bias: bool = False
    # Sliding-window attention (Mistral): key positions more than `window`
    # behind the query are masked out. None = full causal.
    sliding_window: int | None = None
    # MoE (Mixtral): 0 = dense MLP; >0 = routed SwiGLU experts per layer.
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    # Explicit per-head width (Gemma: heads * head_dim != hidden_size).
    # None resolves to hidden_size // num_attention_heads in __post_init__,
    # so every consumer reads a concrete int.
    head_dim: int | None = None
    # Gated-MLP activation: "silu" (SwiGLU — every Llama-family model) or
    # "gelu_tanh" (GeGLU — Gemma; HF spells it gelu_pytorch_tanh).
    hidden_act: str = "silu"
    # Gemma normalization deltas: RMSNorm scales by (1 + w), and the
    # embedding output is multiplied by sqrt(hidden_size).
    rms_norm_offset: bool = False
    embed_scale: bool = False

    def __post_init__(self):
        if self.head_dim is None:
            object.__setattr__(
                self, "head_dim",
                self.hidden_size // self.num_attention_heads,
            )
        # validate at construction, not as a KeyError deep in a jit trace
        if self.hidden_act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"hidden_act must be 'silu' or 'gelu_tanh', got "
                f"{self.hidden_act!r} (HF's 'gelu_pytorch_tanh' maps to "
                "'gelu_tanh' via from_hf_dict)"
            )
        if self.num_local_experts and self.hidden_act != "silu":
            raise ValueError(
                "MoE expert MLPs are SwiGLU-only (ops/moe.py has no "
                "activation plumbing); hidden_act must be 'silu' when "
                "num_local_experts > 0"
            )

    @property
    def num_kv_groups(self) -> int:
        """Query heads per KV head (GQA group size, attention.rs:84-89)."""
        return self.num_attention_heads // self.num_key_value_heads

    @property
    def jax_dtype(self):
        return jnp.dtype(self.dtype)

    def eos_ids(self) -> tuple[int, ...]:
        """Normalized EOS id set (reference checks config ids or "</s>",
        llama.rs:17,26-29,271)."""
        if self.eos_token_id is None:
            return ()
        if isinstance(self.eos_token_id, int):
            return (self.eos_token_id,)
        return tuple(self.eos_token_id)

    @classmethod
    def from_hf_dict(cls, d: dict, **overrides) -> "LlamaConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in d.items() if k in known}
        # HF configs carry torch_dtype, not dtype.
        td = d.get("torch_dtype")
        if td and "dtype" not in overrides:
            kwargs["dtype"] = {"float16": "bfloat16", "bfloat16": "bfloat16",
                               "float32": "float32"}.get(td, "bfloat16")
        # Family defaults not spelled out in the HF config dict: Qwen2's
        # q/k/v bias is unconditional in its architecture (the HF config has
        # no attention_bias key to read); Gemma's (1+w) RMSNorm, GeGLU, and
        # sqrt(hidden) embedding scaling are likewise architectural.
        if d.get("model_type") == "qwen2" and "attention_bias" not in d:
            kwargs["attention_bias"] = True
        if d.get("model_type") == "gemma":
            kwargs.setdefault("rms_norm_offset", True)
            kwargs.setdefault("embed_scale", True)
            # HF Gemma spells the activation in `hidden_activation` (newer
            # configs) or `hidden_act`; both default to the tanh gelu
            act = d.get("hidden_activation") or d.get("hidden_act")
            if act in (None, "gelu", "gelu_pytorch_tanh"):
                kwargs["hidden_act"] = "gelu_tanh"
            else:
                raise ValueError(f"unsupported gemma activation {act!r}")
        elif d.get("hidden_act") not in (None, "silu"):
            raise ValueError(
                f"unsupported hidden_act {d['hidden_act']!r} for "
                f"model_type {d.get('model_type')!r}"
            )
        # Qwen2 configs ship a sliding_window VALUE with the feature gated
        # off (`use_sliding_window: false`); honoring the value alone would
        # force windowed masking (and forfeit the flash kernels) on a model
        # that attends fully. When the gate is on, HF additionally windows
        # only layers >= max_window_layers — full-depth (0) and no-depth
        # (>= num layers) are uniform and supported; a partial depth would
        # need per-layer masks the stacked scan doesn't carry, so it is
        # rejected rather than silently diverging.
        if "use_sliding_window" in d and d.get("sliding_window") is not None:
            if not d["use_sliding_window"]:
                kwargs["sliding_window"] = None
            else:
                mwl = d.get("max_window_layers", 0)
                layers = kwargs.get("num_hidden_layers",
                                    cls.num_hidden_layers)
                if mwl >= layers:
                    kwargs["sliding_window"] = None
                elif mwl > 0:
                    raise ValueError(
                        f"partial-depth sliding window "
                        f"(max_window_layers={mwl} of {layers}) is not "
                        "supported; all-or-none windowing only"
                    )
        kwargs.update(overrides)
        return cls(**kwargs)

    @classmethod
    def from_hf_json(cls, path: str | Path, **overrides) -> "LlamaConfig":
        with open(path) as f:
            return cls.from_hf_dict(json.load(f), **overrides)

    def to_hf_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("max_seq_len")
        d.pop("dtype")
        if d["rope_scaling"] is None:
            d.pop("rope_scaling")
        if d["sliding_window"] is None:
            d.pop("sliding_window")
        if not d["num_local_experts"]:
            d.pop("num_local_experts")
            d.pop("num_experts_per_tok")
        if not d["attention_bias"]:
            d.pop("attention_bias")
        if d["hidden_act"] == "silu":
            d.pop("hidden_act")
        else:  # HF spelling
            d["hidden_act"] = "gelu_pytorch_tanh"
        if not d["rms_norm_offset"]:
            d.pop("rms_norm_offset")
        if not d["embed_scale"]:
            d.pop("embed_scale")
        return d


def llama3_8b(**overrides) -> LlamaConfig:
    """Meta-Llama-3-8B — the reference's model of record (cake/mod.rs:88-96)."""
    return LlamaConfig(**overrides)


def llama2_7b(**overrides) -> LlamaConfig:
    """Llama-2-7B: MHA (kv_heads == heads, GQA group 1), 11008 intermediate,
    32000 vocab, rope_theta 10000 — the pre-GQA family the reference's
    candle stack also serves; exercises the group=1 attention path."""
    base = dict(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=11008,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=32,
        rope_theta=10000.0,
        max_seq_len=4096,
        bos_token_id=1,  # sentencepiece ids, NOT the Llama-3 defaults
        eos_token_id=2,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def llama3_70b(**overrides) -> LlamaConfig:
    base = dict(
        hidden_size=8192,
        intermediate_size=28672,
        num_hidden_layers=80,
        num_attention_heads=64,
        num_key_value_heads=8,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def mistral_7b(**overrides) -> LlamaConfig:
    """Mistral-7B-v0.1: Llama geometry with a 4096-token sliding window and
    32000 vocab — exercises the windowed-mask attention path."""
    base = dict(
        model_type="mistral",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        rope_theta=10000.0,
        sliding_window=4096,
        bos_token_id=1,
        eos_token_id=2,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def qwen2_7b(**overrides) -> LlamaConfig:
    """Qwen2-7B: GQA with q/k/v projection bias, 152k vocab, tied-embedding
    variants in the smaller sizes — exercises the biased-projection path."""
    base = dict(
        model_type="qwen2",
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_hidden_layers=28,
        num_attention_heads=28,
        num_key_value_heads=4,
        rope_theta=1000000.0,
        rms_norm_eps=1e-6,
        attention_bias=True,
        bos_token_id=151643,
        eos_token_id=151643,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def mixtral_8x7b(**overrides) -> LlamaConfig:
    """Mixtral-8x7B: Mistral geometry with 8 routed SwiGLU experts per
    layer, top-2 — the MoE family (expert-parallel over the mesh's ep
    axis, ops/moe.py)."""
    base = dict(
        model_type="mixtral",
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_hidden_layers=32,
        num_attention_heads=32,
        num_key_value_heads=8,
        rope_theta=1000000.0,
        num_local_experts=8,
        num_experts_per_tok=2,
        bos_token_id=1,
        eos_token_id=2,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def gemma_7b(**overrides) -> LlamaConfig:
    """Gemma-7B: MHA with explicit head_dim 256 (16 x 256 != hidden 3072),
    GeGLU MLP, (1+w) RMSNorm, sqrt(hidden)-scaled embeddings, tied head —
    the structurally-different fifth family."""
    base = dict(
        model_type="gemma",
        vocab_size=256000,
        hidden_size=3072,
        intermediate_size=24576,
        num_hidden_layers=28,
        num_attention_heads=16,
        num_key_value_heads=16,
        head_dim=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
        hidden_act="gelu_tanh",
        rms_norm_offset=True,
        embed_scale=True,
        tie_word_embeddings=True,
        bos_token_id=2,
        eos_token_id=1,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def tiny(**overrides) -> LlamaConfig:
    """Tiny random-weight config for tests (SURVEY.md §4 test strategy)."""
    base = dict(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=10000.0,
        bos_token_id=1,
        eos_token_id=2,
        max_seq_len=128,
        dtype="float32",
    )
    base.update(overrides)
    return LlamaConfig(**base)


def tiny_moe(**overrides) -> LlamaConfig:
    """Tiny Mixtral-shaped fixture (4 experts, top-2)."""
    base = dict(model_type="mixtral", num_local_experts=4,
                num_experts_per_tok=2)
    base.update(overrides)
    return tiny(**base)
