"""Single-program pipeline + tensor-parallel execution over a device mesh.

This replaces the reference's entire distributed hot path. There, the master
walks decoder blocks per token and ships activations to workers over TCP with
length-prefixed bitcode frames (`llama.rs:88-119`, `client.rs:101-126`,
`worker.rs:180-224`) — one socket round-trip per contiguous layer group per
token. Here the *whole* per-token step (embed -> all pipeline stages -> norm
-> lm_head -> sample) is ONE compiled XLA program over the mesh:

- the stacked layer axis is sharded over the ``stage`` mesh axis (the
  equivalent of topology layer ranges, topology.rs:46-69);
- activations travel stage-to-stage by ``lax.ppermute`` — compiler-scheduled
  ICI DMA, the TPU-native replacement for `RawTensor` TCP serialization
  (proto/message.rs:11-34), which disappears entirely on-pod;
- within each stage, attention heads and the MLP intermediate dim shard over
  the ``tp`` axis (Megatron column/row parallelism, psum on the row-parallel
  outputs) — parallelism the reference does not have (SURVEY.md §2);
- the KV cache shards over (stage, dp, tp): each stage holds only its own
  layers' cache, like the reference workers (worker.rs:52-61), and each tp
  shard holds only its heads.

Pipeline schedule: single-stream autoregressive decode is inherently
sequential across layers, so the loop runs stages in turn (`lax.fori_loop`
over S steps with a ppermute between steps; after S steps the fully-processed
activation has returned to stage 0). For SPMD validity every stage executes
the layer math every step — collectives may not sit behind a per-stage
branch — and only the active stage's effects land, via a gated KV write and
an activation select (see `_pipeline_layers`). Wall-clock matches the
reference's "upstream workers idle while downstream compute" semantics
(SURVEY.md §2); inactive stages compute into a discarded select instead of
idling.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cake_tpu.models.config import LlamaConfig
from cake_tpu.models import llama
from cake_tpu.ops import quant, sampling
from cake_tpu.ops.kvcache import KVCache
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.rope import rope_tables
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import (
    DP,
    EP,
    SP,
    STAGE,
    TP,
    MeshPlan,
    cache_specs,
    param_specs,
    shard_map,
)


def _local_counts(config: LlamaConfig, tp: int) -> tuple[int, int]:
    return config.num_attention_heads // tp, config.num_key_value_heads // tp


def _pipeline_layers(
    x: jax.Array,  # [Bl, T, hidden] local activation
    layers,  # local stacked layer weights [L/S, ...]
    ck: jax.Array,  # local cache k [L/S, Bl, KVl, S, D]
    cv: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    pos,
    config: LlamaConfig,
    num_stages: int,
    heads_l: int,
    kv_heads_l: int,
    sp: int = 1,
    sp_prefill: bool = False,
    sp_chunk: bool = False,
):
    """Run the staged pipeline loop. Returns (x_on_stage0, ck, cv).

    SPMD-uniformity: every stage executes the layer math (and therefore every
    collective — tp psum, sp ring ppermute, sp decode psum/pmax) on every
    step. Collectives inside a per-stage ``lax.cond`` are invalid SPMD — XLA's
    CollectivePermute is a whole-program rendezvous, so divergent branches
    deadlock or pair mismatched iterations. Instead the *effects* are
    predicated: the KV write is gated on ``step == my_stage`` and the
    activation is selected. Wall-clock cost is identical — single-stream
    pipeline stages are serialized either way ("upstream workers idle",
    SURVEY.md §2); inactive stages just compute concurrently into a discarded
    select instead of idling.
    """
    my_stage = jax.lax.axis_index(STAGE)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def body(step, carry):
        x, ck, cv = carry
        active = step == my_stage
        h, new_cache = llama.forward_layers(
            layers, x, KVCache(k=ck, v=cv), cos, sin, pos, config,
            num_heads=heads_l, num_kv_heads=kv_heads_l, tp_axis=TP, ep_axis=EP,
            sp_axis=SP, sp_size=sp, write_gate=active, sp_prefill=sp_prefill,
            sp_chunk=sp_chunk,
        )
        x = jnp.where(active, h, x)
        x = jax.lax.ppermute(x, STAGE, perm)
        return x, new_cache.k, new_cache.v

    return jax.lax.fori_loop(0, num_stages, body, (x, ck, cv))


def _pipelined_prefill_layers(
    x_chunks: jax.Array,  # [M, B, C, hidden] embedded chunks (stage 0's feed)
    layers,
    ck: jax.Array,
    cv: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    config: LlamaConfig,
    num_stages: int,
    heads_l: int,
    kv_heads_l: int,
):
    """GPipe-style pipelined prefill: prompt chunks stream through the
    stages so all stages compute concurrently.

    The reference has "no micro-batching and no pipelining overlap" —
    upstream workers idle while downstream compute (SURVEY.md §2), and the
    plain staged prefill here inherits that wall-clock shape (S serialized
    passes over the full prompt). Prefill is MXU-bound, so overlap is real
    throughput: chunk ``j`` enters stage 0 at iteration ``j`` and stage
    ``s`` processes it at iteration ``j + s``; once the pipeline fills,
    every stage works every iteration — ~S× prefill/TTFT on S stages,
    minus the (S-1)-iteration fill/drain bubble.

    Causality holds by construction: chunks traverse each stage in order,
    so when chunk ``j`` reaches a stage, that stage's KV rows for chunks
    ``0..j-1`` are already written; attention over the fixed cache buffer
    at ``pos = j*C`` masks everything beyond the frontier as usual.

    Returns ``(y [M, B, C, hidden] — final activations, valid on stage 0
    only), ck, cv``.
    """
    my_stage = jax.lax.axis_index(STAGE)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    m_chunks, b, c, hidden = x_chunks.shape

    y0 = jnp.zeros_like(x_chunks)
    x0 = jnp.zeros((b, c, hidden), x_chunks.dtype)

    def body(t, carry):
        x, ck, cv, y = carry
        # 1) collect: the permuted-in x on stage 0 is chunk t-S, finished
        j_done = jnp.clip(t - num_stages, 0, m_chunks - 1)
        collect = (my_stage == 0) & (t >= num_stages)
        cur = jax.lax.dynamic_slice_in_dim(y, j_done, 1, axis=0)
        y = jax.lax.dynamic_update_slice_in_dim(
            y, jnp.where(collect, x[None], cur), j_done, axis=0
        )
        # 2) inject: stage 0 feeds chunk t into the pipeline
        j_in = jnp.clip(t, 0, m_chunks - 1)
        xin = jax.lax.dynamic_slice_in_dim(x_chunks, j_in, 1, axis=0)[0]
        x = jnp.where((my_stage == 0) & (t < m_chunks), xin, x)
        # 3) compute: this stage holds chunk j = t - my_stage (SPMD-uniform;
        # invalid iterations compute into a discarded select, gated KV)
        j = t - my_stage
        valid = (j >= 0) & (j < m_chunks)
        pos = jnp.clip(j, 0, m_chunks - 1) * c
        h, new_cache = llama.forward_layers(
            layers, x, KVCache(k=ck, v=cv), cos, sin, pos, config,
            num_heads=heads_l, num_kv_heads=kv_heads_l, tp_axis=TP, ep_axis=EP,
            write_gate=valid,
        )
        x = jnp.where(valid, h, x)
        x = jax.lax.ppermute(x, STAGE, perm)
        return x, new_cache.k, new_cache.v, y

    # M injections + S iterations for the last chunk to traverse and land
    # back on stage 0 (collection happens at the top of the iteration)
    _, ck, cv, y = jax.lax.fori_loop(
        0, m_chunks + num_stages, body, (x0, ck, cv, y0)
    )
    return y, ck, cv


def _select_stage0(x: jax.Array) -> jax.Array:
    """Broadcast stage 0's value to all stages (the activation is only valid
    where the pipeline completed)."""
    my_stage = jax.lax.axis_index(STAGE)
    return jax.lax.psum(jnp.where(my_stage == 0, x, jnp.zeros_like(x)), STAGE)


def _select_last_sp(x: jax.Array, last_index: jax.Array, sp: int) -> jax.Array:
    """Pick the hidden state at per-batch global position ``last_index`` from
    a sequence-sharded activation ``x [B, T_l, H]``; the owner shard
    contributes, everyone else zero, reassembled by psum over sp."""
    idx = last_index.reshape(-1, 1, 1).astype(jnp.int32)
    if sp == 1:
        return jnp.take_along_axis(x, idx, axis=1)[:, 0, :]
    t_l = x.shape[1]
    local = idx - jax.lax.axis_index(SP) * t_l
    ok = (local >= 0) & (local < t_l)
    val = jnp.take_along_axis(x, jnp.clip(local, 0, t_l - 1), axis=1)[:, 0, :]
    val = jnp.where(ok[:, 0, :], val, jnp.zeros_like(val))
    return jax.lax.psum(val, SP)


def _head_logits(params, x_last: jax.Array, config: LlamaConfig) -> jax.Array:
    """ln_f + vocab-sharded lm_head; full logits gathered over tp."""
    x_last = rms_norm(x_last, params["norm_f"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
    logits_local = quant.dense(x_last, params["lm_head"]).astype(jnp.float32)
    return jax.lax.all_gather(logits_local, TP, axis=-1, tiled=True)


def _dp_fold(key: jax.Array, dp: int) -> jax.Array:
    """Give each dp shard a distinct sampling key stream; identity at
    dp == 1 so the single-stream mesh path reproduces the local generator's
    key schedule exactly."""
    if dp == 1:
        return key
    return jax.random.fold_in(key, jax.lax.axis_index(DP))


def build_sharded_decode(
    config: LlamaConfig, settings: SamplerSettings, plan: MeshPlan,
    params_like: dict | None = None, steps: int = 1, per_row: bool = False,
    kv_quant: str | None = None, masked: bool = False, logprobs_k: int = 0,
    paged: bool = False,
):
    """Compile the fused multi-chip decode step.

    Signature: ``(params, token [B], cache, pos, key, history [B, N],
    hist_slot) -> (next_token, cache, history, hist_slot)`` for
    ``steps == 1``; with ``steps > 1`` the signature gains a trailing
    ``index0`` argument (absolute token index of the first emitted token)
    and ``next_token`` is ``[steps, B]``. The K-token loop — pipeline,
    sampling, token feedback — then runs inside the one compiled program
    (lax.scan), amortizing dispatch latency exactly like the single-chip
    ``decode_scan_fn``; per-step sampling keys are ``fold_in(key,
    index0 + i)``, the same token-index schedule as every other execution
    path, so one seed yields one stream regardless of sharding or block
    size. ``params_like``: pass the params pytree (or a structural twin)
    when some linears are int8-quantized so the shard_map specs match.

    ``per_row=True`` is the multi-stream serving mode: ``pos`` becomes
    ``[B]`` (each stream decodes at its own position — right-padded prompts
    of different lengths run concurrently), ``key`` becomes per-stream
    keys ``[B, 2] uint32``, and ``index0`` becomes ``[B]`` (each stream's
    absolute token index — a stream admitted into a running batch starts
    its own schedule at 1); the program folds each stream's token index
    into its key (``fold_in(row_key, index0[b] + i)``), so a stream's
    output depends only on (its key, its prompt) — invariant to batch
    composition, mesh layout, and admission time. The signature always
    ends with ``index0`` in this mode. ``per_row`` composes with ``sp > 1``
    (r4): each stream decodes at its own frontier against the
    sequence-sharded cache — the per-row positions flow through the sp
    owner-masked KV write and the per-row-masked distributed flash decode
    (ops/ring.py), which is what lets MULTI-stream serving ride a window
    sharded across chips.

    ``masked=True`` (requires ``per_row`` and ``steps == 1``) is the
    constrained-decoding variant (constrain/): the signature gains two
    trailing operands — ``mask_table [M, ceil(V/8)] uint8`` (the
    device-resident packed per-state allowed-token bitmasks; row 0 is
    all-ones for unconstrained streams) and ``mask_row [B] int32`` (each
    stream's current DFA-state row) — and the compiled body gathers each
    stream's row, unpacks it, and applies it inside the sampler. The DFA
    advance stays host-side between dispatches (CK-JIT: nothing
    stateful traces); both shapes are static, so constrained decode
    never retraces per token. Single-step only by design: a fused block
    would need the host-side DFA advance mid-program.

    ``logprobs_k > 0`` (requires ``per_row``) additionally returns the
    top-k log-softmax of the RAW logits per emitted token — outputs gain
    trailing ``(lp_vals, lp_ids)`` (``[B, k]``, or ``[steps, B, k]`` for
    fused blocks). The sampled stream is unchanged: the top-k is a pure
    extra read of logits the program already computed.

    ``paged=True`` (requires ``per_row``; composes with ``masked`` and
    ``logprobs_k``) is the page-pool layout (:mod:`cake_tpu.kvpool`):
    the ``cache`` operand becomes the pooled page array
    ``[L, P, KH, page_size, D]`` and the signature gains two trailing
    int32 operands — ``page_map [B, pages_per_stream]`` (each stream's
    logical->physical page list, sink-padded past its frontier) and
    ``scatter_ids [B, W]`` (the physical pages receiving this dispatch's
    KV writes; sink for retired/dummy rows). The body gathers each
    stream's pages into the standard contiguous view, runs the UNCHANGED
    decode math over it (bit-identity with the slot layout by
    construction), and scatters only the written pages back. Both
    operand shapes are static, so page-table churn never retraces —
    admitting or retiring a stream is a host-side table edit.
    Requires ``plan.dp == 1`` and ``plan.sp == 1`` (the page axis is
    unsharded; batch and sequence sharding of pooled pages is future
    work — ``BatchGenerator`` enforces this at construction).
    """
    heads_l, kv_heads_l = _local_counts(config, plan.tp)
    if masked and (not per_row or steps != 1):
        raise ValueError("masked decode requires per_row=True, steps=1 "
                         "(the DFA advance is host-side between steps)")
    if logprobs_k and not per_row:
        raise ValueError("logprobs_k requires the per_row serving mode")
    if paged and not per_row:
        raise ValueError("paged decode requires the per_row serving mode")
    if paged and (plan.dp != 1 or plan.sp != 1):
        raise ValueError("paged decode requires dp == 1 and sp == 1 "
                         "(the page axis is unsharded)")

    def one_step(params, token, cache, pos, key, history, hist_slot,
                 mask=None):
        # cache.max_seq inside shard_map is the per-shard slice; RoPE tables
        # must cover global positions.
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        x = llama.embed_tokens(params, token[:, None], config)
        x, ck, cv = _pipeline_layers(
            x, params["layers"], cache.k, cache.v, cos, sin, pos, config,
            plan.num_stages, heads_l, kv_heads_l, sp=plan.sp,
            sp_prefill=False,
        )
        x_last = _select_stage0(x[:, -1, :])
        logits = _head_logits(params, x_last, config)
        lp = sampling.topk_logprobs(logits, logprobs_k) if logprobs_k \
            else None
        if per_row:
            tok = sampling.sample_tokens_keyed(logits, key, history,
                                               settings, mask=mask)
        else:
            tok = sampling.sample_tokens(logits, _dp_fold(key, plan.dp),
                                         history, settings)
        history, hist_slot = sampling.push_history_batched(history, hist_slot, tok)
        return tok, KVCache(k=ck, v=cv), history, hist_slot, lp

    def fold_key(key, index):
        if per_row:  # key [B, 2], index [B] (per-stream schedules)
            return jax.vmap(jax.random.fold_in)(key, index)
        return jax.random.fold_in(key, index)

    if paged:
        from cake_tpu.kvpool import pool_specs

        kv_specs = pool_specs(kv_quant)
    else:
        kv_specs = cache_specs(kv_quant)
    in_specs = [
        param_specs(params_like),
        P(DP),
        kv_specs,
        P(DP) if per_row else P(),
        P(DP, None) if per_row else P(None),
        P(DP, None),
        P(DP) if per_row else P(),  # hist_slot: per-stream ring positions
    ]
    if steps == 1 and not per_row:
        def step(params, token, cache, pos, key, history, hist_slot):
            tok, cache, history, hist_slot, _ = one_step(
                params, token, cache, pos, key, history, hist_slot)
            return tok, cache, history, hist_slot
    else:
        def step(params, token, cache, pos, key, history, hist_slot,
                 index0, *rest):
            rest = list(rest)
            if masked:
                mask_table, mask_row = rest[0], rest[1]
                del rest[:2]
                # one gather + unpack per dispatch: each stream's current
                # DFA-state bitmask row, from the table uploaded once
                row_mask = sampling.unpack_mask_bits(
                    mask_table[mask_row], config.vocab_size)
            else:
                row_mask = None
            if paged:
                from cake_tpu import kvpool

                page_map, scatter_ids = rest
                pool_in = cache
                ps = kvpool.page_size_of(pool_in)
                ppp = page_map.shape[1]
                w = scatter_ids.shape[1]
                # the contiguous view of every stream's pages; the decode
                # body below is untouched, so paged streams reproduce the
                # slot layout's math bit for bit
                cache = kvpool.gather_view(pool_in, page_map)
                first_page = jnp.minimum(pos // ps, ppp - w)

            def body(carry, i):
                token, cache, history, hist_slot = carry
                tok, cache, history, hist_slot, lp = one_step(
                    params, token, cache, pos + i, fold_key(key, index0 + i),
                    history, hist_slot, mask=row_mask,
                )
                ys = (tok, lp[0], lp[1]) if logprobs_k else tok
                return (tok, cache, history, hist_slot), ys

            (_, cache, history, hist_slot), ys = jax.lax.scan(
                body, (token, cache, history, hist_slot),
                jnp.arange(steps, dtype=jnp.int32),
            )
            if paged:
                # only the pages this dispatch wrote go back to the pool
                cache = kvpool.scatter_back(pool_in, cache, first_page,
                                            scatter_ids)
            if logprobs_k:
                toks, lpv, lpi = ys
            else:
                toks, lpv, lpi = ys, None, None
            if steps == 1:
                out = (toks[0], cache, history, hist_slot)
                return out + ((lpv[0], lpi[0]) if logprobs_k else ())
            out = (toks, cache, history, hist_slot)
            return out + ((lpv, lpi) if logprobs_k else ())

        in_specs.append(P(DP) if per_row else P())  # index0
        if masked:
            in_specs.append(P(None, None))  # mask_table: replicated
            in_specs.append(P(DP))          # mask_row: per-stream
        if paged:
            in_specs.append(P(None, None))  # page_map
            in_specs.append(P(None, None))  # scatter_ids

    lp_specs = ()
    if logprobs_k:
        lp_specs = ((P(DP, None),) * 2 if steps == 1
                    else (P(None, DP, None),) * 2)
    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=tuple(in_specs),
        out_specs=(
            P(DP) if steps == 1 else P(None, DP),
            kv_specs,
            P(DP, None),
            P(DP) if per_row else P(),
        ) + lp_specs,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def _head_split_safe(hw, S: int) -> bool:
    """Whether vocab-splitting the lm_head over S stages cannot change
    which quant_matmul backend the program gets: the pallas kernel's
    256-column tileability gate sees ``chunk`` on a split head but
    ``v_local`` on the serialized full-width head, so a backend-divergent
    split would make interleaved and serialized programs' logits differ in
    low-order bits and break their bit-identity contract. Split when the
    backend provably cannot differ — all-XLA (kernels off or an "xla"
    pin), all-pallas (interpret mode), or both widths on the same side of
    the tileability gate. Evaluate at TRACE time so a BatchGenerator's pin
    (quant.pinned_impl around the dispatch) is visible. bf16 heads slice
    bitwise-safely at any width."""
    v_local = quant.out_features(hw)
    if v_local % S:
        return False
    if not isinstance(hw, (quant.QuantizedLinear, quant.Quantized4Linear)):
        return True
    from cake_tpu.ops import pallas as pk

    pin = quant.pinned()
    if not pk.kernels_enabled() or pin == "xla":
        return True  # everything runs XLA either way
    if pin == "pallas" and pk.interpret_default():
        return True  # everything runs (interpreted) pallas
    return ((v_local // S) % 256 == 0) == (v_local % 256 == 0)


def _head_chunk(hw, my_stage, S: int):
    """This stage's V/S column slice of the (possibly int8) lm_head — the
    one shared implementation behind every vocab-split head, so the
    schedules that must stay bit-identical can never drift apart."""
    chunk = quant.out_features(hw) // S
    start = my_stage * chunk
    if isinstance(hw, quant.QuantizedLinear):
        return quant.QuantizedLinear(
            q=jax.lax.dynamic_slice_in_dim(hw.q, start, chunk, 1),
            scale=jax.lax.dynamic_slice_in_dim(hw.scale, start, chunk, 0),
        )
    if isinstance(hw, quant.Quantized4Linear):
        # vocab (out) axis slice — the packed in-axis is untouched; the
        # out axis is the LAST scale axis for both per-channel [V] and
        # grouped [ngroups, V] scales
        return quant.Quantized4Linear(
            qp=jax.lax.dynamic_slice_in_dim(hw.qp, start, chunk, 1),
            scale=jax.lax.dynamic_slice_in_dim(
                hw.scale, start, chunk, hw.scale.ndim - 1),
        )
    return jax.lax.dynamic_slice_in_dim(hw, start, chunk, 1)


def build_interleaved_decode(
    config: LlamaConfig, settings: SamplerSettings, plan: MeshPlan,
    params_like: dict | None = None, steps: int = 1,
    kv_quant: str | None = None,
):
    """Compile the interleaved-microbatch serving decode: the decode twin of
    :func:`_pipelined_prefill_layers`.

    The plain staged decode (`build_sharded_decode`) serializes the S
    pipeline stages for every token — each of the S inner steps runs the
    layer math for the FULL batch on every stage and keeps one stage's
    result, so (S-1)/S of the mesh's compute and KV-cache reads are
    discarded every dispatch (the SPMD analogue of the reference's
    "upstream workers idle while downstream compute", SURVEY.md §2). Here
    the dp-local batch is split into S microbatches round-robined over the
    stages: at cycle ``t`` stage ``s`` runs its layers on microbatch
    ``(t - s) mod S``, so every stage does useful layer work on B/S rows
    every cycle — per-cycle layer FLOPs and KV traffic drop S×, and a
    microbatch finishing its token step re-enters stage 0 on the next
    cycle, keeping the pipeline full across the whole ``steps`` block
    (utilization ``steps*S / (steps*S + S)``; the one-token bubble is the
    fill/drain).

    Schedule (cycle ``t`` of ``S*(steps+1)``):

    - microbatch ``m = t mod S`` arrives finished at stage 0 (valid from
      ``t >= S``); its next token is sampled and re-injected the same cycle;
    - the head runs on every stage with the vocab split S ways
      (stage-0's hidden is psum-broadcast — [B/S, H], tiny — and each stage
      computes its ``V/(S*tp)`` logit slice from a dynamic slice of the
      replicated lm_head, reassembled by all_gather over stage then tp), so
      per-cycle head weight reads stay at the serialized schedule's average
      and sampling is computed bit-identically on every device — the
      sampled-token / history / position state stays replicated-uniform
      with no trailing cross-stage select;
    - sampling keys are ``fold_in(row_key, index0[row] + k)`` — the same
      per-stream token-index schedule as every other execution path, so the
      emitted streams are bit-identical to `build_sharded_decode(per_row)`.

    Same signature as ``build_sharded_decode(per_row=True)``:
    ``(params, token [B], cache, pos [B], keys [B,2], history, hist_slot,
    index0 [B])``; requires ``B_local % num_stages == 0`` (B_local =
    B/dp). ``plan.sp > 1`` (r5) composes: each cycle's resident
    microbatch decodes against its sequence-sharded KV rows (owner-masked
    sp write + distributed flash attend inside ``forward_layers``; the
    sp collectives run unconditionally every cycle, so SPMD uniformity
    holds), and the head/sampling state stays sp-replicated.

    Bit-identity scope: bf16 weights are bit-identical to the serialized
    program unconditionally. Int8 weights need a pinned quant backend
    (``quant.pinned_impl`` — BatchGenerator always pins): without a pin
    the m>=16 row-count gate sees B rows on the serialized head but B/S
    here and could pick different backends.
    """
    heads_l, kv_heads_l = _local_counts(config, plan.tp)
    S = plan.num_stages

    def step(params, token, cache, pos, keys, history, hist_slot, index0):
        b = token.shape[0]
        if b % S:
            raise ValueError(
                f"interleaved decode needs the dp-local batch ({b}) "
                f"divisible by num_stages ({S})"
            )
        bm = b // S
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        my_stage = jax.lax.axis_index(STAGE)
        perm = [(i, (i + 1) % S) for i in range(S)]
        hw = params["lm_head"]
        v_local = quant.out_features(hw)
        split_safe = _head_split_safe(hw, S)  # trace-time: sees the pin

        def head_logits(x_n):
            """Full [bm, V] f32 logits with the vocab additionally split
            over the stage axis (falls back to per-stage full width when
            the local vocab does not divide or the split would change the
            quantized head's backend class)."""
            if S > 1 and split_safe:
                lg = quant.dense(x_n, _head_chunk(hw, my_stage, S)).astype(
                    jnp.float32)
                lg = jax.lax.all_gather(lg, STAGE, axis=-1, tiled=True)
            else:
                lg = quant.dense(x_n, hw).astype(jnp.float32)
            return jax.lax.all_gather(lg, TP, axis=-1, tiled=True)

        def body(t, carry):
            x, ck, cv, pos_all, history, hist_slot, toks = carry
            m_fin = jnp.mod(t, S)           # arriving at / injected by stage 0
            base_fin = m_fin * bm
            k_arr = jnp.maximum(t // S - 1, 0)  # token index of the arrival
            arriving = t >= S               # stage 0 holds a real finished mb
            injecting = t < steps * S

            # ---- head + sample (uniform on every device) ----
            x_fin = _select_stage0(x[:, -1, :])  # [bm, H]
            x_n = rms_norm(x_fin, params["norm_f"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
            logits = head_logits(x_n)            # [bm, V] f32
            key_rows = jax.lax.dynamic_slice_in_dim(keys, base_fin, bm, 0)
            idx_rows = jax.lax.dynamic_slice_in_dim(index0, base_fin, bm, 0)
            hist_rows = jax.lax.dynamic_slice_in_dim(history, base_fin, bm, 0)
            slot_rows = jax.lax.dynamic_slice_in_dim(hist_slot, base_fin, bm, 0)
            step_keys = jax.vmap(jax.random.fold_in)(key_rows,
                                                     idx_rows + k_arr)
            sampled = sampling.sample_tokens_keyed(logits, step_keys,
                                                   hist_rows, settings)

            # commit the arrival's token + history rows (uniform predication)
            cur = jax.lax.dynamic_slice(toks, (k_arr, base_fin), (1, bm))
            toks = jax.lax.dynamic_update_slice(
                toks, jnp.where(arriving, sampled[None], cur),
                (k_arr, base_fin),
            )
            h_new, s_new = sampling.push_history_batched(hist_rows, slot_rows,
                                                         sampled)
            history = jax.lax.dynamic_update_slice(
                history, jnp.where(arriving, h_new, hist_rows), (base_fin, 0))
            hist_slot = jax.lax.dynamic_update_slice(
                hist_slot, jnp.where(arriving, s_new, slot_rows), (base_fin,))

            # the re-injected microbatch decodes at its next position
            pos_rows = jax.lax.dynamic_slice_in_dim(pos_all, base_fin, bm, 0)
            pos_rows = jnp.where(arriving & injecting, pos_rows + 1, pos_rows)
            pos_all = jax.lax.dynamic_update_slice(pos_all, pos_rows,
                                                   (base_fin,))

            # stage 0 embeds + injects: the caller's token on first entry,
            # the just-sampled token thereafter
            tok_rows = jax.lax.dynamic_slice_in_dim(token, base_fin, bm, 0)
            tok_inj = jnp.where(arriving, sampled, tok_rows)
            x_inj = llama.embed_tokens(params, tok_inj[:, None], config)
            x = jnp.where((my_stage == 0) & injecting, x_inj, x)

            # ---- layer pass on this stage's resident microbatch ----
            m_res = jnp.mod(t - my_stage, S)
            base_res = m_res * bm
            valid = (t >= my_stage) & (t < my_stage + steps * S)
            pos_res = jax.lax.dynamic_slice_in_dim(pos_all, base_res, bm, 0)
            rows = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, base_res, bm, 1),
                KVCache(k=ck, v=cv),
            )
            h, rows = llama.forward_layers(
                params["layers"], x, rows, cos, sin, pos_res, config,
                num_heads=heads_l, num_kv_heads=kv_heads_l, tp_axis=TP, ep_axis=EP,
                sp_axis=SP, sp_size=plan.sp, sp_prefill=False,
                write_gate=valid,
            )
            x = jnp.where(valid, h, x)
            # gated-off forward_layers rewrites current contents unchanged,
            # so the row write-back is unconditional
            ck, cv = jax.tree.map(
                lambda buf, r: jax.lax.dynamic_update_slice_in_dim(
                    buf, r, base_res, 1),
                (ck, cv), (rows.k, rows.v),
            )
            x = jax.lax.ppermute(x, STAGE, perm)
            return x, ck, cv, pos_all, history, hist_slot, toks

        x0 = jnp.zeros((bm, 1, config.hidden_size), config.jax_dtype)
        toks0 = jnp.zeros((steps, b), jnp.int32)
        _, ck, cv, _, history, hist_slot, toks = jax.lax.fori_loop(
            0, S * (steps + 1), body,
            (x0, cache.k, cache.v, pos, history, hist_slot, toks0),
        )
        if steps == 1:
            return toks[0], KVCache(k=ck, v=cv), history, hist_slot
        return toks, KVCache(k=ck, v=cv), history, hist_slot

    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=(
            param_specs(params_like),
            P(DP),
            cache_specs(kv_quant),
            P(DP),
            P(DP, None),
            P(DP, None),
            P(DP),
            P(DP),
        ),
        out_specs=(
            P(DP) if steps == 1 else P(None, DP),
            cache_specs(kv_quant),
            P(DP, None),
            P(DP),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def build_admit_prefill(config: LlamaConfig, plan: MeshPlan,
                        params_like: dict | None = None,
                        kv_quant: str | None = None):
    """Compile the continuous-batching admission prefill: ONE prompt row
    (replicated over dp, not dp discarded copies) processed one chunk per
    dispatch into a standalone staging cache, so a running batch's decode
    dispatches interleave with a new prompt's prefill instead of stalling
    behind it.

    Signature: ``(params, tokens [1, C], cache1, pos0, last_local [1]) ->
    (logits [1, vocab] f32, cache1)`` where ``cache1`` is a batch-1 cache
    with the batch axis replicated over dp
    (``mesh.cache_specs(batch_replicated=True)``), ``pos0`` is the chunk's
    global position offset, and ``last_local`` is the in-chunk index of the
    prompt's final token (meaningful on the final chunk; ignored
    otherwise). Chunked prefill is exact: chunk ``j`` attends the staging
    cache's committed positions ``< pos0`` plus its own causal prefix, the
    same math as a single full-prompt pass.

    ``plan.sp > 1`` (r5): the chunk's tokens run REPLICATED over the sp
    axis against the sequence-sharded staging cache — owner-masked range
    write (``ring.sp_range_cache_write``) plus the T>1 distributed-flash
    chunk attend, so continuous admission composes with the
    sequence-sharded serving window.
    """
    heads_l, kv_heads_l = _local_counts(config, plan.tp)

    def step(params, tokens, cache, pos0, last_local):
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        x = llama.embed_tokens(params, tokens, config)
        x, ck, cv = _pipeline_layers(
            x, params["layers"], cache.k, cache.v, cos, sin, pos0, config,
            plan.num_stages, heads_l, kv_heads_l, sp=plan.sp,
            sp_chunk=plan.sp > 1,
        )
        # the chunk activations are replicated over sp (every shard computes
        # the full chunk), so the sp==1 last-index selection applies
        x_last = _select_last_sp(x, last_local, 1)
        x_last = _select_stage0(x_last)
        logits = _head_logits(params, x_last, config)
        return logits, KVCache(k=ck, v=cv)

    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=(
            param_specs(params_like),
            P(None, None),
            cache_specs(kv_quant, batch_replicated=True),
            P(),
            P(None),
        ),
        out_specs=(
            P(None, None),
            cache_specs(kv_quant, batch_replicated=True),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def build_sharded_verify(config: LlamaConfig, plan: MeshPlan,
                         params_like: dict | None = None,
                         kv_quant: str | None = None):
    """Compile the speculation-verification pass over the mesh: forward
    ``tokens [1, T]`` (the last emitted token + K proposals) from position
    ``pos`` and return logits at EVERY position (``[T, vocab] f32``) — the
    multi-chip twin of :func:`cake_tpu.runtime.speculative.verify_fn`.
    KV for all T slots is written; slots past the accepted frontier hold
    rejected garbage that later steps overwrite before it becomes
    attendable. Requires ``plan.dp == 1`` (the single-stream speculation
    plane); ``plan.sp > 1`` (r5) runs the fed block chunk-replicated over
    sp against the sequence-sharded cache (range write + chunk attend).
    """
    heads_l, kv_heads_l = _local_counts(config, plan.tp)
    if plan.dp != 1:
        raise ValueError("speculative verification requires dp == 1 "
                         "(single-stream plane)")

    def step(params, tokens, cache, pos):
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        x = llama.embed_tokens(params, tokens, config)
        x, ck, cv = _pipeline_layers(
            x, params["layers"], cache.k, cache.v, cos, sin, pos, config,
            plan.num_stages, heads_l, kv_heads_l, sp=plan.sp,
            sp_chunk=plan.sp > 1,
        )
        x = _select_stage0(x[0])  # [T, hidden], valid on stage 0
        logits = _head_logits(params, x, config)  # [T, vocab] f32
        return logits, KVCache(k=ck, v=cv)

    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=(
            param_specs(params_like),
            P(None, None),
            cache_specs(kv_quant),
            P(),
        ),
        out_specs=(
            P(None, None),
            cache_specs(kv_quant),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def build_sharded_verify_rows(config: LlamaConfig, plan: MeshPlan,
                              params_like: dict | None = None,
                              kv_quant: str | None = None):
    """Compile the PER-ROW speculation-verification pass: forward
    ``tokens [B, T]`` (each row: its last emitted token + K proposals,
    0-padded) from per-row positions ``pos [B]`` and return logits at
    EVERY position for every row (``[B, T, vocab] f32``) — the serving
    twin of :func:`build_sharded_verify`. Each row writes its own K+1 KV
    slots at its own frontier; rejected slots hold garbage that the next
    round's fed range fully overwrites before it becomes attendable (the
    same invariant as the single-stream speculation plane). ``plan.sp > 1``
    (r5): every row's fed block runs chunk-replicated over sp against the
    sequence-sharded cache — per-row range writes
    (``ring.sp_range_cache_write`` with ``pos [B]``, rows may straddle
    shard boundaries) + the per-row-masked chunk attend.
    """
    heads_l, kv_heads_l = _local_counts(config, plan.tp)

    def step(params, tokens, cache, pos):
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        x = llama.embed_tokens(params, tokens, config)
        x, ck, cv = _pipeline_layers(
            x, params["layers"], cache.k, cache.v, cos, sin, pos, config,
            plan.num_stages, heads_l, kv_heads_l, sp=plan.sp,
            sp_chunk=plan.sp > 1,
        )
        x = _select_stage0(x)  # [B, T, hidden], valid on stage 0
        logits = _head_logits(params, x, config)
        return logits, KVCache(k=ck, v=cv)

    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=(
            param_specs(params_like),
            P(DP, None),
            cache_specs(kv_quant),
            P(DP),
        ),
        out_specs=(
            P(DP, None, None),
            cache_specs(kv_quant),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def build_interleaved_verify_rows(config: LlamaConfig, plan: MeshPlan,
                                  params_like: dict | None = None,
                                  kv_quant: str | None = None):
    """Interleaved-microbatch twin of :func:`build_sharded_verify_rows`.

    The serialized per-row verify runs S pipeline cycles with EVERY stage
    computing the full batch and one result kept. Here the dp-local batch's
    S microbatches stream through the stages GPipe-style (microbatch ``m``
    is at stage ``t - m`` on cycle ``t``; 2S-1 cycles total), so each cycle
    does B/S rows of useful layer work per stage — total layer FLOPs and KV
    traffic drop ~S/2× (one pass has a fill/drain bubble the steady-state
    interleaved decode does not). Stage S-1 collects each microbatch's
    final hidden states; the head (rms_norm + lm_head + tp gather) then
    runs on the reassembled ``[B, T, H]`` exactly like the serialized
    program, so logits are bit-identical per row.

    Same signature and specs as ``build_sharded_verify_rows``; requires
    ``B_local % num_stages == 0``. ``plan.sp > 1`` (r5) composes the same
    way as the serialized verify: each microbatch's fed block runs
    chunk-replicated over sp with per-row range writes. Int8 weights need
    a pinned quant backend for bit-identity with the serialized program
    (same contract as ``build_interleaved_decode``)."""
    heads_l, kv_heads_l = _local_counts(config, plan.tp)
    S = plan.num_stages

    def step(params, tokens, cache, pos):
        b, t = tokens.shape
        if b % S:
            raise ValueError(
                f"interleaved verify needs the dp-local batch ({b}) "
                f"divisible by num_stages ({S})"
            )
        bm = b // S
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        my_stage = jax.lax.axis_index(STAGE)
        perm = [(i, (i + 1) % S) for i in range(S)]
        x_all = llama.embed_tokens(params, tokens, config)  # [B,T,H]

        def body(c_t, carry):
            x, ck, cv, y = carry
            # stage 0 injects microbatch c_t
            base_in = jnp.minimum(c_t, S - 1) * bm
            xin = jax.lax.dynamic_slice_in_dim(x_all, base_in, bm, 0)
            x = jnp.where((my_stage == 0) & (c_t < S), xin, x)
            # this stage's resident microbatch
            m_res = c_t - my_stage
            valid = (m_res >= 0) & (m_res < S)
            base = jnp.clip(m_res, 0, S - 1) * bm
            pos_rows = jax.lax.dynamic_slice_in_dim(pos, base, bm, 0)
            rows = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, base, bm, 1),
                KVCache(k=ck, v=cv),
            )
            h, rows = llama.forward_layers(
                params["layers"], x, rows, cos, sin, pos_rows, config,
                num_heads=heads_l, num_kv_heads=kv_heads_l, tp_axis=TP, ep_axis=EP,
                sp_axis=SP, sp_size=plan.sp, sp_chunk=plan.sp > 1,
                write_gate=valid,
            )
            x = jnp.where(valid, h, x)
            ck, cv = jax.tree.map(
                lambda buf, r: jax.lax.dynamic_update_slice_in_dim(
                    buf, r, base, 1),
                (ck, cv), (rows.k, rows.v),
            )
            # stage S-1 collects the finished microbatch's hidden states
            collect = valid & (my_stage == S - 1)
            cur = jax.lax.dynamic_slice_in_dim(y, base, bm, 0)
            y = jax.lax.dynamic_update_slice_in_dim(
                y, jnp.where(collect, x, cur), base, 0)
            x = jax.lax.ppermute(x, STAGE, perm)
            return x, ck, cv, y

        x0 = jnp.zeros((bm, t, config.hidden_size), config.jax_dtype)
        y0 = jnp.zeros((b, t, config.hidden_size), config.jax_dtype)
        _, ck, cv, y = jax.lax.fori_loop(
            0, 2 * S - 1,
            lambda c_t, carry: body(c_t, carry),
            (x0, cache.k, cache.v, y0),
        )
        # broadcast stage S-1's collection, then the head — vocab-split
        # over the stage axis when that cannot change the quant backend
        # class (same _head_split_safe gate as the interleaved decode), so
        # each stage reads V/S of the lm_head instead of all of it
        y = jax.lax.psum(
            jnp.where(my_stage == S - 1, y, jnp.zeros_like(y)), STAGE)
        y = rms_norm(y, params["norm_f"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
        hw = params["lm_head"]
        if S > 1 and _head_split_safe(hw, S):
            logits = quant.dense(y, _head_chunk(hw, my_stage, S)).astype(
                jnp.float32)
            logits = jax.lax.all_gather(logits, STAGE, axis=-1, tiled=True)
        else:
            logits = quant.dense(y, hw).astype(jnp.float32)
        logits = jax.lax.all_gather(logits, TP, axis=-1, tiled=True)
        return logits, KVCache(k=ck, v=cv)

    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=(
            param_specs(params_like),
            P(DP, None),
            cache_specs(kv_quant),
            P(DP),
        ),
        out_specs=(
            P(DP, None, None),
            cache_specs(kv_quant),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))


def build_sharded_prefill(config: LlamaConfig, plan: MeshPlan,
                          params_like: dict | None = None,
                          microbatch: int = 1,
                          kv_quant: str | None = None,
                          with_offset: bool = False):
    """Compile the multi-chip prompt pass.

    Signature: ``(params, tokens [B, T], cache, last_index [B]) ->
    (logits [B, vocab] f32, cache)``. With ``plan.sp == 1``, ``T`` may be any
    bucketed length; with sequence parallelism (``sp > 1``) ``T`` must be a
    multiple of sp no larger than max_seq — each sp shard runs ring attention
    over its ``T/sp`` chunk (:mod:`cake_tpu.ops.ring`), so prefill FLOPs and
    ring traffic scale with the prompt, not the window, and the roped KV is
    redistributed into the range-sharded cache layout
    (``ring.sp_chunked_cache_write``). Positions past the prompt hold zero KV
    that decode steps overwrite slot-by-slot before they ever become
    attendable.

    ``microbatch = M > 1`` (requires ``sp == 1``, ``num_stages > 1``,
    ``T % M == 0``) selects GPipe-style pipelined prefill: the prompt is
    split into M chunks that stream through the stages concurrently
    (:func:`_pipelined_prefill_layers`) — ~num_stages× prompt throughput
    once the pipeline fills, identical results.

    ``with_offset = True`` (requires ``microbatch == 1``) appends a
    trailing scalar ``pos0`` argument: the fed tokens occupy global
    positions ``pos0..pos0+T-1`` and attend the cache's committed
    positions below ``pos0`` — the shared-prefix serving path, where a
    common system prompt is prefilled once and each stream's remainder is
    prefilled at the prefix boundary. With ``sp > 1`` (r5) the remainder
    bucket runs REPLICATED over the sp axis against the range-sharded
    cache (``ring.sp_range_cache_write`` + the T>1 distributed-flash
    chunk attend) — sp× redundant FLOPs on the remainder in exchange for
    composing the prefix store with a sequence-sharded window.
    """
    heads_l, kv_heads_l = _local_counts(config, plan.tp)
    if microbatch > 1 and plan.sp != 1:
        raise ValueError("pipelined (microbatch) prefill requires sp == 1")
    if microbatch > 1 and plan.num_stages < 2:
        raise ValueError(
            "pipelined (microbatch) prefill requires num_stages > 1 — with "
            "one stage there is nothing to overlap, only per-chunk overhead"
        )
    if with_offset and microbatch > 1:
        raise ValueError("offset prefill requires microbatch == 1")
    chunk_mode = with_offset and plan.sp > 1

    def step(params, tokens, cache, last_index, *rest):
        pos0 = rest[0] if with_offset else 0
        cos, sin = rope_tables(
            config.head_dim, cache.max_seq * plan.sp, config.rope_theta,
            scaling=config.rope_scaling,
        )
        x = llama.embed_tokens(params, tokens, config)
        if microbatch > 1:
            b, t = tokens.shape
            if t % microbatch:
                raise ValueError(
                    f"prompt bucket {t} not divisible into {microbatch} "
                    "pipeline chunks"
                )
            chunk = t // microbatch
            # [B, T, H] -> [M, B, C, H]
            x_chunks = x.reshape(b, microbatch, chunk, -1).transpose(
                1, 0, 2, 3
            )
            y, ck, cv = _pipelined_prefill_layers(
                x_chunks, params["layers"], cache.k, cache.v, cos, sin,
                config, plan.num_stages, heads_l, kv_heads_l,
            )
            # [M, B, C, H] -> [B, T, H] (valid on stage 0; selected below)
            x = y.transpose(1, 0, 2, 3).reshape(b, t, -1)
        else:
            # sp_prefill explicit: a bucketed prompt can give each shard a
            # ONE-token chunk, which the T>1 heuristic would misroute to the
            # decode branch (silently wrong logits — r2 code-review finding)
            x, ck, cv = _pipeline_layers(
                x, params["layers"], cache.k, cache.v, cos, sin, pos0,
                config, plan.num_stages, heads_l, kv_heads_l, sp=plan.sp,
                sp_prefill=not chunk_mode, sp_chunk=chunk_mode,
            )
        # slice the wanted position first so the cross-stage select moves
        # [B, hidden], not the whole [B, T, hidden] activation
        # (chunk mode computes the bucket replicated over sp, so the sp==1
        # owner-select applies)
        x_last = _select_last_sp(x, last_index, 1 if chunk_mode else plan.sp)
        x_last = _select_stage0(x_last)
        logits = _head_logits(params, x_last, config)
        return logits, KVCache(k=ck, v=cv)

    in_specs = [
        param_specs(params_like),
        P(DP, None) if chunk_mode else P(DP, SP),
        cache_specs(kv_quant),
        P(DP),
    ]
    if with_offset:
        in_specs.append(P())
    sharded = shard_map(
        step,
        mesh=plan.mesh,
        in_specs=tuple(in_specs),
        out_specs=(
            P(DP, None),
            cache_specs(kv_quant),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(2,))
