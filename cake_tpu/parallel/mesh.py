"""Device mesh construction and parameter sharding specs.

The TPU-native replacement for the reference's distribution plane: instead of
one TCP worker per host with activations serialized over sockets
(`cake-core/src/cake/{client,worker,proto}`), the devices form a
`jax.sharding.Mesh` with axes

- ``stage`` — pipeline stages: the stacked layer axis shards here, the
  equivalent of the reference topology's contiguous ``model.layers.N-M``
  ranges per worker (topology.rs:46-69); activations move stage-to-stage by
  ICI ``ppermute`` inside one compiled program.
- ``tp`` — tensor parallelism (Megatron-style): attention heads and MLP
  intermediate shard here; row-parallel projections psum over it. The
  reference has no tensor parallelism (SURVEY.md §2 "not present") — on TPU
  it is the main single-token latency lever, so it is first-class.
- ``sp`` — sequence/context parallelism: the KV cache's sequence axis shards
  here; long prefill runs ring attention around the ``sp`` ring
  (:mod:`cake_tpu.ops.ring`) and decode reassembles exact softmax from
  per-shard partials. The reference hard-caps context at 4096 with no
  sequence parallelism at all (SURVEY.md §5) — on TPU this is the
  long-context axis.
- ``dp`` — data/batch parallelism for multi-stream serving (also absent in
  the single-request reference).

All collectives ride ICI when the mesh maps onto one slice; DCN only across
slices (mesh construction keeps axis order ``(dp, stage, sp, tp)`` so ``tp``
— the chattiest axis — lands on the innermost, fastest rings, with the
``sp`` ring next).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cake_tpu.models.config import LlamaConfig

# shard_map's public home moved from jax.experimental to the jax namespace
# (and its replication-check knob was renamed check_rep -> check_vma on the
# way); resolve both once here so every mesh program builder works on
# either side of the move. Callers use the current spelling (check_vma).
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # older jax: the experimental home + the old knob name
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, *args, check_vma: bool | None = None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map_compat(f, *args, **kwargs)

DP, STAGE, SP, EP, TP = "dp", "stage", "sp", "ep", "tp"


def make_mesh(
    num_stages: int = 1,
    tp: int = 1,
    dp: int = 1,
    sp: int = 1,
    ep: int = 1,
    devices=None,
) -> Mesh:
    """Build a ``(dp, stage, sp, ep, tp)`` mesh from the flat device list.

    ``ep`` — expert parallelism (MoE families only): the expert axis of the
    routed-MLP weight stacks shards here and the combine psums over it
    (:mod:`cake_tpu.ops.moe`). Dense models leave it 1; every non-expert
    tensor is replicated over ep, so the axis is invisible to them."""
    devices = list(devices if devices is not None else jax.devices())
    need = num_stages * tp * dp * sp * ep
    if len(devices) < need:
        raise ValueError(
            f"need {need} devices for dp={dp} x stage={num_stages} x sp={sp} "
            f"x ep={ep} x tp={tp}, have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(dp, num_stages, sp, ep, tp)
    return Mesh(grid, (DP, STAGE, SP, EP, TP))


def validate_shardable(config: LlamaConfig, num_stages: int, tp: int,
                       sp: int = 1, ep: int = 1) -> None:
    """Divisibility requirements for the (stage, sp, ep, tp) sharding."""
    if sp > 1 and config.max_seq_len % sp:
        raise ValueError(
            f"max_seq_len {config.max_seq_len} not divisible by sp {sp}"
        )
    if config.num_hidden_layers % num_stages:
        raise ValueError(
            f"num_hidden_layers {config.num_hidden_layers} not divisible by "
            f"stage count {num_stages}"
        )
    if ep > 1:
        if not config.num_local_experts:
            raise ValueError(
                "ep > 1 requires an MoE config (num_local_experts > 0)"
            )
        if config.num_local_experts % ep:
            raise ValueError(
                f"num_local_experts {config.num_local_experts} not "
                f"divisible by ep {ep}"
            )
    for name, dim in [
        ("num_attention_heads", config.num_attention_heads),
        ("num_key_value_heads", config.num_key_value_heads),
        ("intermediate_size", config.intermediate_size),
        ("vocab_size", config.vocab_size),
    ]:
        if dim % tp:
            raise ValueError(f"{name} {dim} not divisible by tp {tp}")


def param_specs(params: dict | None = None) -> dict:
    """PartitionSpec pytree matching the params layout (models/llama.py):
    layer axis -> stage; head/intermediate out-features -> tp (column-
    parallel); wo/w_down in-features -> tp (row-parallel); norms and embed
    replicated; lm_head vocab -> tp. Family extensions: q/k/v biases shard
    with their projection's out-features (tp); an MoE layer's expert stacks
    ``[L, E, in, out]`` shard the expert axis over ep (router replicated —
    it is tiny and every rank routes every token).

    Pass ``params`` to get specs matching its structure where linears may be
    int8-quantized (ops.quant.QuantizedLinear): the q tensor takes the
    weight's spec, the per-output-channel scale takes the spec minus the
    in-features axis."""
    base = {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(STAGE, None),
            "wq": P(STAGE, None, TP),
            "wk": P(STAGE, None, TP),
            "wv": P(STAGE, None, TP),
            "wo": P(STAGE, TP, None),
            "mlp_norm": P(STAGE, None),
            "w_gate": P(STAGE, None, TP),
            "w_up": P(STAGE, None, TP),
            "w_down": P(STAGE, TP, None),
        },
        "norm_f": P(None),
        "lm_head": P(None, TP),
    }
    if params is None:
        return base
    layers = params.get("layers", {})
    if "bq" in layers:
        base["layers"]["bq"] = P(STAGE, TP)
        base["layers"]["bk"] = P(STAGE, TP)
        base["layers"]["bv"] = P(STAGE, TP)
    if "bo" in layers:
        # applied after the tp psum -> replicated over tp
        base["layers"]["bo"] = P(STAGE, None)
    if "router" in layers:
        base["layers"]["router"] = P(STAGE, None, None)
        base["layers"]["w_gate"] = P(STAGE, EP, None, TP)
        base["layers"]["w_up"] = P(STAGE, EP, None, TP)
        base["layers"]["w_down"] = P(STAGE, EP, TP, None)
    from cake_tpu.ops.quant import Quantized4Linear, QuantizedLinear

    def refine(p, s):
        if isinstance(p, dict):
            return {k: refine(p[k], s[k]) for k in p}
        if isinstance(p, QuantizedLinear):
            scale_spec = P(*(tuple(s)[:-2] + (s[-1],)))
            return QuantizedLinear(q=s, scale=scale_spec)
        if isinstance(p, Quantized4Linear):
            # The packed qp takes the weight's spec unchanged: adjacent-pair
            # packing (ops/quant.py) makes packed rows [a, b) the contiguous
            # original rows [2a, 2b), so in-axis (row-parallel tp) sharding
            # of the packed array is exactly the packing of the shard.
            # Per-channel scale [..., out] drops the in axis; a grouped
            # scale [..., ngroups, out] keeps the weight's spec verbatim —
            # its group axis lives along (and shards with) the in axis.
            if p.scale.ndim == p.qp.ndim:
                scale_spec = s
            else:
                scale_spec = P(*(tuple(s)[:-2] + (s[-1],)))
            return Quantized4Linear(qp=s, scale=scale_spec)
        return s

    return refine(params, base)


# KV cache [L, B, kv_heads, max_seq, head_dim]: layers over stage, batch over
# dp, kv heads over tp, sequence over sp — KV memory splits across all of
# stage, tp and sp, which is what lets 70B-class KV fit 16 GB chips.
CACHE_SPEC = P(STAGE, DP, TP, SP, None)


def cache_specs(kv_quant: str | None = None, batch_replicated: bool = False):
    """PartitionSpec pytree matching :func:`cake_tpu.ops.kvcache.init_cache`'s
    structure: plain buffers take CACHE_SPEC; int8 buffers take it for the
    q bytes and the same layout minus head_dim for the per-slot scales.

    ``batch_replicated``: don't shard the batch axis over dp — the layout of
    a single-row staging cache (continuous-batching admission) that must
    exist on every dp shard."""
    from cake_tpu.ops.kvcache import KVCache, QuantizedKV

    bd = None if batch_replicated else DP
    spec = P(STAGE, bd, TP, SP, None)
    if kv_quant == "int8":
        half = QuantizedKV(q=spec, scale=P(STAGE, bd, TP, SP))
        return KVCache(k=half, v=half)
    return KVCache(k=spec, v=spec)


def shard_params(params: dict, mesh: Mesh) -> dict:
    """Place a (host or single-device) params pytree onto the mesh."""
    specs = param_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def shard_cache(cache, mesh: Mesh):
    from cake_tpu.ops.kvcache import QuantizedKV

    specs = cache_specs("int8" if isinstance(cache.k, QuantizedKV) else None)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), cache, specs
    )


# compiled cache-zeros programs, keyed by geometry — a fresh jit closure
# per call would re-trace and recompile on every invocation, stalling e.g.
# each continuous-batching admission behind a compile
_CACHE_PROGRAMS: dict = {}


def init_cache_on_mesh(config, mesh: Mesh, batch: int = 1,
                       max_seq: int | None = None, quant: str | None = None,
                       batch_replicated: bool = False):
    """Allocate a zeroed, mesh-sharded KV cache WITHOUT a host-side copy.

    ``shard_cache(init_cache(...))`` device_puts host zeros — invalid for
    shards this process cannot address on a multi-host pod (and a pointless
    host allocation even on one). Emitting the zeros from a compiled
    program with explicit output shardings allocates each shard directly on
    its owner device, on every host of the pod identically. Programs are
    memoized by (mesh, cache geometry), so repeat allocations — one per
    serving admission — reuse the compiled executable."""
    from functools import partial

    from cake_tpu.ops.kvcache import init_cache

    key = (mesh, config.num_hidden_layers, config.num_key_value_heads,
           config.head_dim, str(config.dtype), batch,
           max_seq or config.max_seq_len, quant, batch_replicated)
    make = _CACHE_PROGRAMS.get(key)
    if make is None:
        specs = cache_specs(quant, batch_replicated=batch_replicated)
        out_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                              is_leaf=lambda x: isinstance(x, P))

        @partial(jax.jit, out_shardings=out_sh)
        def make():
            return init_cache(config, batch=batch, max_seq=max_seq,
                              quant=quant)

        _CACHE_PROGRAMS[key] = make
    return make()


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Resolved parallel layout for a model on a mesh."""

    mesh: Mesh
    num_stages: int
    tp: int
    dp: int
    sp: int = 1
    ep: int = 1

    @classmethod
    def build(cls, config: LlamaConfig, num_stages: int = 1, tp: int = 1,
              dp: int = 1, sp: int = 1, ep: int = 1,
              devices=None) -> "MeshPlan":
        validate_shardable(config, num_stages, tp, sp, ep)
        return cls(mesh=make_mesh(num_stages, tp, dp, sp, ep, devices),
                   num_stages=num_stages, tp=tp, dp=dp, sp=sp, ep=ep)

    @classmethod
    def from_topology(cls, config: LlamaConfig, topology, tp: int = 1,
                      dp: int = 1, sp: int = 1, ep: int = 1,
                      devices=None) -> "MeshPlan":
        """Derive the stage layout from a topology whose nodes carry mesh
        ``device`` indices.

        The single-program mesh pipeline shards the stacked layer axis
        *uniformly*, so the topology's ranges must be exactly that uniform
        split, in device order. Arbitrary/uneven layer ranges (which the
        reference allows, topology.rs:46-69) are served by the master/worker
        runtime instead; here they raise so a user's explicit placement is
        never silently replaced.
        """
        staged = sorted(
            (n for n in topology if n.device is not None),
            key=lambda n: n.device,
        )
        num_stages = max(1, len(staged))
        if staged:
            if [n.device for n in staged] != list(range(num_stages)):
                raise ValueError(
                    "topology device indices must be 0..S-1 with no gaps; got "
                    f"{[n.device for n in staged]}"
                )
            L = config.num_hidden_layers
            if L % num_stages:
                raise ValueError(
                    f"{L} layers not divisible into {num_stages} stages"
                )
            per = L // num_stages
            for s, node in enumerate(staged):
                want = list(range(s * per, (s + 1) * per))
                if node.layer_indices() != want:
                    raise ValueError(
                        f"mesh pipeline requires the uniform layer split: node "
                        f"'{node.name}' (device {s}) must own layers "
                        f"{want[0]}-{want[-1]}, got {node.layer_indices()}; "
                        "use the master/worker runtime for uneven ranges"
                    )
        return cls.build(config, num_stages=num_stages, tp=tp, dp=dp, sp=sp,
                         ep=ep, devices=devices)
