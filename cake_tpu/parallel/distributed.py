"""Multi-host bootstrap: one SPMD program across TPU pod hosts.

The reference scales across hosts with hand-rolled TCP between a master and
workers (`cake-core/src/cake/{client,worker}.rs`) — every hop serializes
tensors through sockets. On a TPU pod the idiomatic scale-out is the other
way around: every host runs the SAME program under `jax.distributed`, the
global mesh spans all hosts' chips, and stage/tp/sp/dp collectives ride ICI
(DCN only across slices) with zero application-level serialization. The
cross-host TCP plane (runtime/{master,worker}) remains for heterogeneous or
non-pod deployments; this module is the pod path.

Usage (same command on every host; the env is auto-populated on Cloud TPU):

    cake_tpu.parallel.distributed.initialize()          # env-driven
    # or explicitly:
    initialize(coordinator="10.0.0.2:8476", num_processes=4, process_id=h)

then build the mesh over `jax.devices()` (all hosts' chips) as usual —
`MeshPlan.build(...)` already consumes the global device list.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("cake_tpu.distributed")


def initialize(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Join (or trivially form) the multi-host runtime; returns a summary.

    With no arguments on Cloud TPU, `jax.distributed.initialize()` resolves
    everything from the TPU metadata/env. A single-process call (or
    ``num_processes=1``) is a no-op beyond importing jax — the same code
    path runs laptop, single VM, and pod.
    """
    import jax

    if num_processes is None:
        env_n = int(os.environ.get("CAKE_NUM_PROCESSES", "1"))
        if env_n > 1:
            num_processes = env_n
    if num_processes is not None or coordinator is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    info = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
    log.info(
        "distributed runtime: process %d/%d, %d local / %d global devices",
        info["process_index"], info["process_count"],
        info["local_devices"], info["global_devices"],
    )
    return info
