"""Location-transparent block execution: the `Forwarder` seam.

Equivalent of the reference's central abstraction (`cake/mod.rs:116-159`):
anything that can run decoder layer(s) forward — a local device or a remote
worker — behind one interface, so the generation loop is placement-blind
(llama.rs:88-119). Differences by design:

- A runner owns a contiguous *segment* of layers, not a single layer: the
  reference coalesces contiguous same-worker layers per step at runtime
  (llama.rs:100-119) and still opens one TCP connection per layer
  (llama.rs:179-184); here the static topology is planned into segments once
  (topology.segments) and each remote segment holds exactly one connection.
- The local path is a jitted `lax.scan` over the segment's stacked weights —
  one XLA dispatch per segment per token, zero per-layer overhead.
- Activations cross runners as numpy arrays (device transfers only at remote
  boundaries, matching worker.rs:203,224 semantics).
"""

from __future__ import annotations

import logging
import time
from abc import ABC, abstractmethod
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import LlamaConfig
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs.trace import span
from cake_tpu.ops.kvcache import KVCache, init_cache

log = logging.getLogger("cake_tpu.runner")


class BlockRunner(ABC):
    """One contiguous run of decoder blocks, local or remote."""

    start: int
    stop: int
    # per-forward accounting the master folds into flight records: remote
    # runners fill wire bytes + codec times here each call, local runners
    # leave it empty (per-instance dict — a shared class default would
    # cross-contaminate segments on an in-place write)
    last_call: dict

    @abstractmethod
    def forward(self, x: np.ndarray, pos: int) -> np.ndarray:
        """Run blocks [start, stop) on ``x [B, T, hidden]`` at ``pos``."""

    def forward_jax(self, x, pos: int):
        """Device-aware entry the master's segment walk uses: takes a
        jax.Array OR numpy, returns whatever is cheapest for this placement
        (a device array for local runners, numpy for remote hops). Default:
        materialize on host and run :meth:`forward` — remote runners ship
        numpy anyway, so the host copy here IS the wire boundary."""
        return self.forward(np.asarray(x), pos)

    @abstractmethod
    def ident(self) -> str:
        """Placement identity ('local' or worker address), cake/mod.rs:156-158."""

    def layer_names(self) -> list[str]:
        return [f"model.layers.{i}" for i in range(self.start, self.stop)]

    def reset(self) -> None:
        """Fresh KV state for a new stream (cache.as_new, cache.rs:138-146)."""

    def close(self) -> None:
        pass


class LocalRunner(BlockRunner):
    """Jitted on-device execution of a stacked layer slice."""

    def __init__(self, config: LlamaConfig, layers, start: int, stop: int,
                 batch: int = 1, max_seq: int | None = None):
        assert next(iter(layers.values())).shape[0] == stop - start
        self.config = config
        self.start, self.stop = start, stop
        self.last_call = {}
        self.layers = layers
        self.max_seq = max_seq or config.max_seq_len
        self.batch = batch
        # span tag formatted once, not per token (the disabled-tracer path
        # must stay near-zero on the decode hot loop)
        self._span_tag = f"{start}-{stop}"
        self.cache = init_cache(config, batch=batch, max_seq=self.max_seq,
                                num_layers=stop - start)
        self._fn = jax.jit(
            partial(llama.hidden_forward_layers, config=config),
            donate_argnames=("cache",),
        )

    def forward(self, x: np.ndarray, pos: int) -> np.ndarray:
        return np.asarray(self.forward_jax(x, pos))

    def forward_jax(self, x, pos) -> jax.Array:
        """Device-resident execution (no device->host copy): the master's
        segment walk keeps activations on device across consecutive local
        segments and only materializes numpy at remote boundaries."""
        with span("segment.local_scan", layers=self._span_tag):
            h, self.cache = self._fn(
                self.layers, jnp.asarray(x, self.config.jax_dtype),
                self.cache, jnp.int32(pos),
            )
            return h

    def ident(self) -> str:
        return "local"

    def reset(self) -> None:
        self.cache = self.cache.as_new()


class RemoteRunner(BlockRunner):
    """Proxy to a worker over the wire (the reference `Client`,
    client.rs:14-135): handshake measures latency, forward ships one Batch
    per call for the whole segment."""

    def __init__(self, host: str, start: int, stop: int, timeout_ms: int = 30000,
                 max_seq: int | None = None, wire_codec: str = "none"):
        from cake_tpu.runtime import protocol, wire
        from cake_tpu.runtime.protocol import MsgType

        self._protocol, self._wire, self._MsgType = protocol, wire, MsgType
        self.wire_codec = protocol.check_codec(wire_codec)
        self.start, self.stop = start, stop
        self._timeout_ms = timeout_ms
        self._expected_max_seq = max_seq
        if ":" in host:
            addr, port = host.rsplit(":", 1)
        else:
            addr, port = host, "10128"
        self.addr = f"{addr}:{port}"
        self.last_call = {}
        self._span_tag = f"{start}-{stop}"
        self._ser_hist = obs_metrics.histogram("wire.serialize_ms")
        self._de_hist = obs_metrics.histogram("wire.deserialize_ms")
        self._handshake()

    def _handshake(self) -> None:
        """Connect + Hello/WorkerInfo exchange, recording RTT latency and
        verifying layer coverage (client.rs:41-47)."""
        addr, port = self.addr.rsplit(":", 1)
        t0 = time.perf_counter()
        self.conn = self._wire.connect(addr, int(port),
                                       timeout_ms=self._timeout_ms)
        self.conn.send(self._MsgType.HELLO)
        t, payload = self.conn.recv()
        if t != self._MsgType.WORKER_INFO:
            raise RuntimeError(f"handshake failed: got message type {t}")
        self.info = self._protocol.WorkerInfo.from_bytes(payload)
        self.info.latency_ms = (time.perf_counter() - t0) * 1000
        # Version skew between master and worker is legal on the wire (both
        # sides ignore unknown fields) but worth a loud notice: a skewed pair
        # previously handshook silently.
        from cake_tpu import __version__ as local_version

        if self.info.version != local_version:
            log.warning(
                "version skew: master %s vs worker %s (%s@%s)",
                local_version, self.info.version, self.info.name, self.addr,
            )
        missing = [n for n in self.layer_names() if n not in self.info.layers]
        if missing:
            raise RuntimeError(
                f"worker {self.info.name}@{self.addr} does not serve {missing}"
            )
        # KV capacity must agree: a smaller worker cache would silently clamp
        # KV writes past its max_seq (dynamic_update_slice semantics) and
        # corrupt the stream long after the handshake.
        if (
            self._expected_max_seq
            and self.info.max_seq
            and self.info.max_seq != self._expected_max_seq
        ):
            raise RuntimeError(
                f"worker {self.info.name}@{self.addr} max_seq "
                f"{self.info.max_seq} != master max_seq {self._expected_max_seq}"
            )
        # Codec negotiation: the worker advertises what it accepts (and will
        # mirror); a codec it never heard of would decode as garbage — fail
        # at handshake, not mid-stream.
        if self.wire_codec != "none" and self.wire_codec not in (
            self.info.codecs or ["none"]
        ):
            raise RuntimeError(
                f"worker {self.info.name}@{self.addr} does not accept wire "
                f"codec {self.wire_codec!r} (advertises {self.info.codecs})"
            )

    def forward(self, x: np.ndarray, pos: int) -> np.ndarray:
        x = np.asarray(x)
        ops = [(name, pos) for name in self.layer_names()]
        with span("segment.remote_rtt", addr=self.addr,
                  layers=self._span_tag):
            t0 = time.perf_counter()
            # buffer sequence straight into the gather-write transport: the
            # activation payload is never copied into a contiguous frame
            req = self._protocol.encode_ops_parts(x, ops, self.wire_codec)
            req_len = sum(len(p) for p in req)
            t_ser = time.perf_counter() - t0
            with span("wire.send", bytes=req_len):
                self.conn.send(self._MsgType.BATCH, req)
            with span("wire.recv"):
                t, payload = self.conn.recv()
            if t == self._MsgType.ERROR:
                raise self._protocol.WorkerOpError(
                    f"worker {self.addr}: "
                    f"{self._protocol.decode_error(payload)}"
                )
            if t != self._MsgType.TENSOR:
                # protocol desync is a transport-level fault: classify as a
                # wire error so the master's reconnect+replay recovery applies
                raise self._wire.WireError(f"unexpected reply type {t}")
            t0 = time.perf_counter()
            out, _ = self._protocol.decode_activation(payload)
            t_de = time.perf_counter() - t0
        # per-call accounting: payload-level bytes, so the master's flight
        # totals line up with the worker's own bytes_in/bytes_out counters.
        # raw_bytes is the pre-codec activation size both ways — the flight
        # record's view of what the wire codec saved this call.
        self.last_call = {
            "wire_bytes_out": req_len, "wire_bytes_in": len(payload),
            "wire_bytes_raw": int(x.nbytes + out.nbytes),
            "serialize_ms": t_ser * 1e3, "deserialize_ms": t_de * 1e3,
        }
        self._ser_hist.observe(t_ser * 1e3)
        self._de_hist.observe(t_de * 1e3)
        return out

    def ident(self) -> str:
        return self.addr

    def reset(self) -> None:
        # Reference semantics: a fresh connection gets a fresh cache clone
        # (worker.rs:52-61). Reconnecting is the reset.
        self.close()
        self._handshake()

    def close(self) -> None:
        try:
            self.conn.send(self._MsgType.GOODBYE)
        except Exception:
            pass
        self.conn.close()
