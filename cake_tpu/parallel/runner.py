"""Location-transparent block execution: the `Forwarder` seam.

Equivalent of the reference's central abstraction (`cake/mod.rs:116-159`):
anything that can run decoder layer(s) forward — a local device or a remote
worker — behind one interface, so the generation loop is placement-blind
(llama.rs:88-119). Differences by design:

- A runner owns a contiguous *segment* of layers, not a single layer: the
  reference coalesces contiguous same-worker layers per step at runtime
  (llama.rs:100-119) and still opens one TCP connection per layer
  (llama.rs:179-184); here the static topology is planned into segments once
  (topology.segments) and each remote segment holds exactly one connection.
- The local path is a jitted `lax.scan` over the segment's stacked weights —
  one XLA dispatch per segment per token, zero per-layer overhead.
- Activations cross runners as numpy arrays (device transfers only at remote
  boundaries, matching worker.rs:203,224 semantics).
"""

from __future__ import annotations

import logging
import struct
import threading
import time
from abc import ABC, abstractmethod
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import LlamaConfig
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import trace as obs_trace
from cake_tpu.obs.clock import ClockSync
from cake_tpu.obs.trace import span
from cake_tpu.ops.kvcache import KVCache, init_cache

log = logging.getLogger("cake_tpu.runner")


class BlockRunner(ABC):
    """One contiguous run of decoder blocks, local or remote."""

    start: int
    stop: int
    # per-forward accounting the master folds into flight records: remote
    # runners fill wire bytes + codec times here each call, local runners
    # leave it empty (per-instance dict — a shared class default would
    # cross-contaminate segments on an in-place write)
    last_call: dict

    @abstractmethod
    def forward(self, x: np.ndarray, pos: int) -> np.ndarray:
        """Run blocks [start, stop) on ``x [B, T, hidden]`` at ``pos``."""

    def forward_jax(self, x, pos: int):
        """Device-aware entry the master's segment walk uses: takes a
        jax.Array OR numpy, returns whatever is cheapest for this placement
        (a device array for local runners, numpy for remote hops). Default:
        materialize on host and run :meth:`forward` — remote runners ship
        numpy anyway, so the host copy here IS the wire boundary."""
        return self.forward(np.asarray(x), pos)

    @abstractmethod
    def ident(self) -> str:
        """Placement identity ('local' or worker address), cake/mod.rs:156-158."""

    def layer_names(self) -> list[str]:
        return [f"model.layers.{i}" for i in range(self.start, self.stop)]

    def reset(self) -> None:
        """Fresh KV state for a new stream (cache.as_new, cache.rs:138-146)."""

    def recover(self) -> bool:
        """Bring this runner back after a transport fault: reconnect with
        backoff under the recovery deadline, failing over to the next
        replica when the live address's budget expires (RemoteRunner).
        Returns True when the live address CHANGED (a failover — the
        master counts those apart from plain recoveries). Local runners
        just reset."""
        self.reset()
        return False

    def close(self) -> None:
        pass


class LocalRunner(BlockRunner):
    """Jitted on-device execution of a stacked layer slice."""

    def __init__(self, config: LlamaConfig, layers, start: int, stop: int,
                 batch: int = 1, max_seq: int | None = None):
        assert next(iter(layers.values())).shape[0] == stop - start
        self.config = config
        self.start, self.stop = start, stop
        self.last_call = {}
        self.layers = layers
        self.max_seq = max_seq or config.max_seq_len
        self.batch = batch
        # span tag formatted once, not per token (the disabled-tracer path
        # must stay near-zero on the decode hot loop)
        self._span_tag = f"{start}-{stop}"
        self.cache = init_cache(config, batch=batch, max_seq=self.max_seq,
                                num_layers=stop - start)
        self._fn = jax.jit(
            partial(llama.hidden_forward_layers, config=config),
            donate_argnames=("cache",),
        )

    def forward(self, x: np.ndarray, pos: int) -> np.ndarray:
        return np.asarray(self.forward_jax(x, pos))

    def forward_jax(self, x, pos) -> jax.Array:
        """Device-resident execution (no device->host copy): the master's
        segment walk keeps activations on device across consecutive local
        segments and only materializes numpy at remote boundaries."""
        with span("segment.local_scan", layers=self._span_tag):
            h, self.cache = self._fn(
                self.layers, jnp.asarray(x, self.config.jax_dtype),
                self.cache, jnp.int32(pos),
            )
            return h

    def ident(self) -> str:
        return "local"

    def reset(self) -> None:
        self.cache = self.cache.as_new()


class RemoteRunner(BlockRunner):
    """Proxy to a worker over the wire (the reference `Client`,
    client.rs:14-135): handshake measures latency + clock offset (ping
    exchange, CAP_PING), forward ships one Batch per call for the whole
    segment — carrying a Dapper-style trace context to CAP_TRACE workers
    when the tracer is on, and stitching the returned span digest into the
    master's timeline."""

    # ping exchange: samples at handshake, then refreshed between forwards
    # once the estimate is older than this (clock drift over a long run)
    CLOCK_PINGS = 5
    CLOCK_REFRESH_S = 30.0
    # per-replica reconnect budget during mid-stream recovery; overridden
    # by --recover-deadline
    RECOVER_DEADLINE_S = 30.0

    def __init__(self, host: str | list[str], start: int, stop: int,
                 timeout_ms: int = 30000,
                 max_seq: int | None = None, wire_codec: str = "none",
                 op_timeout_s: float | None = None,
                 connect_retries: int = 0,
                 recover_deadline_s: float | None = None):
        """``host`` — one address, or the segment's replica set in
        failover order (every replica must serve the same layers).
        ``op_timeout_s`` bounds each forward/STATS wire round trip (a
        wedged worker faults into reconnect+replay instead of hanging the
        decode loop); the default scales with segment size since a longer
        segment legitimately computes longer. ``connect_retries`` retries
        the INITIAL handshake with backoff — a master may start before
        its workers. ``recover_deadline_s`` is the per-replica reconnect
        budget :meth:`recover` spends before failing over."""
        from cake_tpu.runtime import protocol, wire
        from cake_tpu.runtime.protocol import MsgType

        self._protocol, self._wire, self._MsgType = protocol, wire, MsgType
        self.wire_codec = protocol.check_codec(wire_codec)
        self.start, self.stop = start, stop
        self._timeout_ms = timeout_ms
        self._expected_max_seq = max_seq
        hosts = [host] if isinstance(host, str) else list(host)
        if not hosts:
            raise ValueError("RemoteRunner needs at least one address")

        def _norm(h: str) -> str:
            return h if ":" in h else f"{h}:10128"

        self.addrs = [_norm(h) for h in hosts]
        self._addr_idx = 0
        # generous per-op deadline, scaled to segment size: the op is one
        # forward over (stop-start) layers plus (worst case) a per-shape
        # XLA compile; it exists to catch WEDGED peers, not slow ones
        self.op_timeout_s = (
            op_timeout_s if op_timeout_s is not None
            else 120.0 + 2.0 * (stop - start)
        )
        self.recover_deadline_s = (
            recover_deadline_s if recover_deadline_s is not None
            else self.RECOVER_DEADLINE_S
        )
        self.last_call = {}
        self._span_tag = f"{start}-{stop}"
        self._ser_hist = obs_metrics.histogram("wire.serialize_ms")
        self._de_hist = obs_metrics.histogram("wire.deserialize_ms")
        # serializes connection use between the forward loop and the
        # cluster scraper/top thread (fetch_stats shares the socket)
        self._lock = threading.RLock()
        self.clock = ClockSync()
        self.caps: set[str] = set()
        self._seq = 0
        self._clock_refreshed = 0.0
        # set by a STATS exchange that died mid-flight (scraper thread):
        # the frame stream may carry a late reply, so the next forward
        # must fault into the master's reconnect+replay instead of
        # tripping on a stale STATS frame
        self._poisoned: Exception | None = None
        if connect_retries > 0:
            from cake_tpu.runtime import retry

            # transport failures only: a deterministic handshake rejection
            # (layer coverage, max_seq, codec — RuntimeError) must not be
            # hammered against a correctly-refusing worker
            retry.retry_call(
                self._handshake,
                retry.RetryPolicy(deadline_s=None,
                                  max_attempts=connect_retries + 1,
                                  base_s=0.2, cap_s=2.0),
                retry_on=(OSError, wire.WireError),
                describe=f"connect to {self.addr}",
            )
        else:
            self._handshake()

    @property
    def addr(self) -> str:
        """The LIVE address (current replica) — every log line, metric
        label, and ident() reads this, so a failover is visible
        everywhere at once."""
        return self.addrs[self._addr_idx]

    def _handshake(self) -> None:
        """Connect + Hello/WorkerInfo exchange, recording RTT latency and
        verifying layer coverage (client.rs:41-47)."""
        stale = getattr(self, "conn", None)
        if stale is not None:  # retried handshake: drop the failed socket
            stale.close()
            self.conn = None
        addr, port = self.addr.rsplit(":", 1)
        t0 = time.perf_counter()
        conn = self._wire.connect(addr, int(port),
                                  timeout_ms=self._timeout_ms)
        try:
            conn.send(self._MsgType.HELLO)
            # the WorkerInfo reply is a control frame: bound it by the
            # connect budget, never the (possibly larger) op deadline
            t, payload = conn.recv(
                timeout=self._timeout_ms / 1000
                if self._timeout_ms and self._timeout_ms > 0 else None)
        except Exception:
            # retried handshakes must not leak half-open sockets
            conn.close()
            raise
        self.conn = conn
        if t != self._MsgType.WORKER_INFO:
            raise RuntimeError(f"handshake failed: got message type {t}")
        self.info = self._protocol.WorkerInfo.from_bytes(payload)
        self.info.latency_ms = (time.perf_counter() - t0) * 1000
        # Version skew between master and worker is legal on the wire (both
        # sides ignore unknown fields) but worth a loud notice: a skewed pair
        # previously handshook silently.
        from cake_tpu import __version__ as local_version

        if self.info.version != local_version:
            log.warning(
                "version skew: master %s vs worker %s (%s@%s)",
                local_version, self.info.version, self.info.name, self.addr,
            )
        missing = [n for n in self.layer_names() if n not in self.info.layers]
        if missing:
            raise RuntimeError(
                f"worker {self.info.name}@{self.addr} does not serve {missing}"
            )
        # KV capacity must agree: a smaller worker cache would silently clamp
        # KV writes past its max_seq (dynamic_update_slice semantics) and
        # corrupt the stream long after the handshake.
        if (
            self._expected_max_seq
            and self.info.max_seq
            and self.info.max_seq != self._expected_max_seq
        ):
            raise RuntimeError(
                f"worker {self.info.name}@{self.addr} max_seq "
                f"{self.info.max_seq} != master max_seq {self._expected_max_seq}"
            )
        # Codec negotiation: the worker advertises what it accepts (and will
        # mirror); a codec it never heard of would decode as garbage — fail
        # at handshake, not mid-stream.
        if self.wire_codec != "none" and self.wire_codec not in (
            self.info.codecs or ["none"]
        ):
            raise RuntimeError(
                f"worker {self.info.name}@{self.addr} does not accept wire "
                f"codec {self.wire_codec!r} (advertises {self.info.codecs})"
            )
        # Capability set gates every post-seed wire extension: an old peer
        # advertises nothing and is never sent a PING/STATS frame or a
        # trace trailer — its op stream stays byte-identical to the seed.
        self.caps = set(self.info.caps or [])
        if self._protocol.CAP_PING in self.caps:
            self._sync_clock(self.CLOCK_PINGS)

    # -- clock alignment -----------------------------------------------------
    def _sync_clock(self, n: int = 3) -> None:
        """NTP-style ping exchange (obs.clock): n samples, min-RTT wins.
        Caller must hold the connection (handshake or the forward lock)."""
        for _ in range(n):
            t0 = time.perf_counter()
            self.conn.send(self._MsgType.PING, struct.pack("<d", t0))
            # a ping reply is a control frame, never behind model compute
            # (the lock is held): a peer silent this long is wedged
            t, payload = self.conn.recv(timeout=min(self.op_timeout_s, 15.0))
            t1 = self.conn.last_recv_t or time.perf_counter()
            if t != self._MsgType.PING or len(payload) < 16:
                raise self._wire.WireError(
                    f"bad ping reply from {self.addr}: type {t}"
                )
            echo, tw = struct.unpack_from("<dd", payload)
            self.clock.add(echo, tw, t1)
        self._clock_refreshed = time.monotonic()

    def _maybe_refresh_clock(self) -> None:
        if (
            self._protocol.CAP_PING in self.caps
            and time.monotonic() - self._clock_refreshed > self.CLOCK_REFRESH_S
        ):
            try:
                self._sync_clock(3)
            except self._wire.WireError:
                raise
            except Exception as e:
                # A partial ping exchange poisons the stream: the PING went
                # out, so a late reply frame is (or will be) sitting where
                # the next forward() expects its TENSOR. Surface a wire
                # fault NOW so the master's reconnect+replay recovery runs
                # deliberately, instead of the next decode step tripping
                # over a stale PING frame mid-call.
                raise self._wire.WireError(
                    f"clock refresh to {self.addr} failed mid-exchange: {e}"
                ) from e

    def forward(self, x: np.ndarray, pos: int) -> np.ndarray:
        x = np.asarray(x)
        ops = [(name, pos) for name in self.layer_names()]
        tr = obs_trace.tracer()
        t_w0 = time.perf_counter()
        with self._lock:
            # Waiting here means the cluster scraper held the connection
            # for a STATS round trip; report the wait via last_call so the
            # master keeps scraper contention out of the per-segment
            # histogram the straggler signal reads.
            lock_wait_ms = (time.perf_counter() - t_w0) * 1e3
            if self._poisoned is not None:
                e, self._poisoned = self._poisoned, None
                raise self._wire.WireError(
                    f"frame stream to {self.addr} poisoned by a failed "
                    f"stats exchange: {e}"
                ) from e
            # Refresh before opening the RTT span, and report the time it
            # took via last_call: the periodic 3-ping exchange otherwise
            # lands inside the master's per-segment timing every 30s and
            # smears the worker's apparent tail latency (the straggler
            # signal is built on that histogram's p99).
            t_r0 = time.perf_counter()
            self._maybe_refresh_clock()
            refresh_ms = (time.perf_counter() - t_r0) * 1e3
            with span("segment.remote_rtt", addr=self.addr,
                      layers=self._span_tag):
                tc = None
                if tr.enabled and self._protocol.CAP_TRACE in self.caps:
                    # Dapper-style propagation: the worker's handler spans
                    # join this trace under the span we are inside right now
                    self._seq += 1
                    tc = {"tid": tr.trace_id,
                          "psid": obs_trace.current_span_id(),
                          "seq": self._seq, "pos": int(pos)}
                t0 = time.perf_counter()
                # buffer sequence straight into the gather-write transport:
                # the activation payload is never copied into a contiguous
                # frame
                req = self._protocol.encode_ops_parts(
                    x, ops, self.wire_codec, trace_ctx=tc)
                req_len = sum(len(p) for p in req)
                t_ser = time.perf_counter() - t0
                t_send0 = time.perf_counter()
                with span("wire.send", bytes=req_len):
                    self.conn.send(self._MsgType.BATCH, req)
                with span("wire.recv"):
                    # per-op deadline: a wedged worker (hung driver call,
                    # blackholed link) faults as WireTimeout into the
                    # master's reconnect+replay instead of blocking the
                    # decode loop forever (the seed's settimeout(None)
                    # hole, wire.py:287 pre-ISSUE-4)
                    t, payload = self.conn.recv(timeout=self.op_timeout_s)
                t_recv1 = self.conn.last_recv_t or time.perf_counter()
                if t == self._MsgType.ERROR:
                    raise self._protocol.WorkerOpError(
                        f"worker {self.addr}: "
                        f"{self._protocol.decode_error(payload)}"
                    )
                if t != self._MsgType.TENSOR:
                    # protocol desync is a transport-level fault: classify
                    # as a wire error so the master's reconnect+replay
                    # recovery applies
                    raise self._wire.WireError(f"unexpected reply type {t}")
                t0 = time.perf_counter()
                act, trailer = self._protocol.split_activation(payload)
                out, _ = self._protocol.decode_activation(act)
                t_de = time.perf_counter() - t0
        if tc is not None and trailer:
            self._stitch_digest(trailer.get("digest"), tc, t_send0, t_recv1)
        # per-call accounting: payload-level bytes, so the master's flight
        # totals line up with the worker's own bytes_in/bytes_out counters.
        # raw_bytes is the pre-codec activation size both ways — the flight
        # record's view of what the wire codec saved this call.
        # clock_refresh_ms lets the master keep the refresh out of its
        # per-segment steady-state histogram.
        self.last_call = {
            "wire_bytes_out": req_len, "wire_bytes_in": len(payload),
            "wire_bytes_raw": int(x.nbytes + out.nbytes),
            "serialize_ms": t_ser * 1e3, "deserialize_ms": t_de * 1e3,
            "clock_refresh_ms": refresh_ms, "lock_wait_ms": lock_wait_ms,
        }
        self._ser_hist.observe(t_ser * 1e3)
        self._de_hist.observe(t_de * 1e3)
        return out

    def _stitch_digest(self, digest: dict | None, tc: dict,
                       t_send0: float, t_recv1: float) -> None:
        """Inline the worker's reply span digest into this process's trace:
        rebase worker perf_counter stamps onto the master timebase via the
        ping-estimated offset, then clamp the whole digest into this call's
        own send->recv window (Jaeger-style skew adjustment — the residual
        offset error is bounded by half the ping RTT asymmetry, and
        causality says the worker's handling happened inside the window, so
        any overhang is estimation error, not information)."""
        if not digest or not digest.get("spans"):
            return
        spans = digest["spans"]
        rebased = [(n, self.clock.to_master(ts), d) for n, ts, d in spans]
        t_lo = min(ts for _, ts, _ in rebased)
        t_hi = max(ts + d for _, ts, d in rebased)
        shift = 0.0
        if t_hi + shift > t_recv1:
            shift = t_recv1 - t_hi
        if t_lo + shift < t_send0:
            # start wins when the window is tighter than the digest (can
            # only happen on estimator failure): keep causal order visible
            shift = t_send0 - t_lo
        tr = obs_trace.tracer()
        source = f"{digest.get('name', '?')}@{self.addr}"
        args = {"trace_id": tc["tid"], "parent_span_id": tc["psid"],
                "seq": tc["seq"], "pos": tc["pos"]}
        if abs(shift) > 0:
            args["skew_adjust_us"] = round(shift * 1e6, 1)
        for name, ts, dur in rebased:
            tr.record_remote(source, name, ts + shift, dur, args)

    def fetch_stats(self) -> dict | None:
        """Worker status/registry snapshot over the op connection
        (MsgType.STATS; CAP_STATS workers only — returns None for an old
        peer). Serialized against forward() by the connection lock, so the
        cluster scraper can run next to a live decode. An exchange that
        dies mid-flight poisons the frame stream (a late STATS reply would
        surface where the next forward expects its TENSOR), so it flags
        the connection: the next forward raises a wire fault and the
        master's reconnect+replay recovery runs deliberately."""
        import json

        if self._protocol.CAP_STATS not in self.caps:
            return None
        with self._lock:
            try:
                self.conn.send(self._MsgType.STATS)
                # holding the lock means no forward is in flight; a STATS
                # reply is assembled inline on the worker, so a long
                # silence here is a wedged peer, not a busy one
                t, payload = self.conn.recv(timeout=min(self.op_timeout_s,
                                                        15.0))
            except Exception as e:
                self._poisoned = e
                raise self._wire.WireError(
                    f"stats fetch from {self.addr} failed mid-exchange: {e}"
                ) from e
            if t != self._MsgType.STATS:
                e = self._wire.WireError(f"unexpected STATS reply type {t}")
                self._poisoned = e
                raise e
        return json.loads(payload.decode())

    def ident(self) -> str:
        return self.addr

    def reset(self) -> None:
        # Reference semantics: a fresh connection gets a fresh cache clone
        # (worker.rs:52-61). Reconnecting is the reset.
        with self._lock:
            self.close()
            self._poisoned = None  # a fresh frame stream is clean
            # a restarted worker process has a new perf_counter epoch:
            # samples estimated against the old one would poison the
            # min-RTT pick with an offset that is wrong by the whole
            # inter-epoch delta
            self.clock = ClockSync()
            self._handshake()

    def recover(self, rng=None, sleep=time.sleep) -> bool:
        """Reconnect after a transport fault: retry the LIVE address's
        handshake with full-jitter backoff under ``recover_deadline_s``
        (a worker restarting for a couple of seconds must not kill the
        stream — the seed raised on the first refused connect), then fail
        over to the next replica in ``addrs``, each with its own budget.
        Returns True when the surviving address differs from the one we
        started on (the master counts that as a failover). Deterministic
        handshake rejections (layer coverage, max_seq, codec) propagate
        immediately — retrying a correctly-refusing worker is useless and
        failing over to a MISCONFIGURED replica set deserves a loud
        error, not a silent stream."""
        from cake_tpu.runtime import retry

        policy = retry.RetryPolicy(deadline_s=self.recover_deadline_s)
        start_idx = self._addr_idx
        last: Exception | None = None
        # clamp the per-attempt CONNECT timeout to the recovery budget: a
        # blackholed primary (SYN dropped, no RST) must not hold failover
        # hostage for the full steady-state connect timeout
        saved_timeout_ms = self._timeout_ms
        self._timeout_ms = min(
            saved_timeout_ms, max(100, int(self.recover_deadline_s * 1000))
        )
        try:
            for k in range(len(self.addrs)):
                self._addr_idx = (start_idx + k) % len(self.addrs)
                try:
                    retry.retry_call(
                        self.reset, policy,
                        retry_on=(OSError, self._wire.WireError),
                        describe=f"reconnect to {self.addr} "
                                 f"(layers {self.start}-{self.stop - 1})",
                        rng=rng, sleep=sleep,
                    )
                    # the clamp above bounds CONNECT attempts only; the
                    # surviving connection's steady-state default deadline
                    # must be the configured one, not the recovery budget
                    self.conn.timeout_s = (
                        saved_timeout_ms / 1000
                        if saved_timeout_ms and saved_timeout_ms > 0
                        else None
                    )
                    if self._addr_idx != start_idx:
                        log.warning(
                            "failed over: layers %d-%d now served by %s "
                            "(replica %d/%d)", self.start, self.stop - 1,
                            self.addr, self._addr_idx + 1, len(self.addrs),
                        )
                    return self._addr_idx != start_idx
                except (OSError, self._wire.WireError) as e:
                    last = e
                    if k + 1 < len(self.addrs):
                        log.warning(
                            "recovery deadline (%.1fs) for %s expired (%s); "
                            "failing over to %s", self.recover_deadline_s,
                            self.addr, e,
                            self.addrs[(self._addr_idx + 1)
                                       % len(self.addrs)],
                        )
        finally:
            self._timeout_ms = saved_timeout_ms
        self._addr_idx = start_idx  # next recovery starts from the primary
        raise self._wire.WireError(
            f"no replica for layers {self.start}-{self.stop - 1} "
            f"recovered within {self.recover_deadline_s:.1f}s each "
            f"(tried {', '.join(self.addrs)}): {last}"
        ) from last

    def close(self) -> None:
        with self._lock:
            conn = getattr(self, "conn", None)
            if conn is None:  # a failed retried handshake left no socket
                return
            try:
                conn.send(self._MsgType.GOODBYE)
            except Exception:
                pass
            conn.close()
