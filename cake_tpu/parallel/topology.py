"""Topology: the distribution config plane.

Equivalent of `cake-core/src/cake/topology.rs`: a YAML map of worker-name ->
``{host, description, layers}`` (topology.rs:13-21) where each layers entry is
either a single layer name or a range ``model.layers.0-5`` expanded to
individual names (regex ``^(.+[^\\d])(\\d+)-(\\d+)$``, topology.rs:8-10,46-69)
with ``stop > start`` validated (topology.rs:54). Lookups:
``get_node_for_layer`` (topology.rs:75-84) and prefix-match
``is_layer_owner`` used by the weight splitter (topology.rs:25-32).

TPU-native extension: a node may carry ``device: <int>`` assigning it to a
mesh stage index instead of (or in addition to) a TCP host — the same YAML
file then drives either the cross-host worker deployment (reference
semantics) or a single-program ICI pipeline over a device mesh.
"""

from __future__ import annotations

import dataclasses
import re
from pathlib import Path

import yaml

_RANGE_RE = re.compile(r"^(.+[^\d])(\d+)-(\d+)$")


def expand_layer_ranges(entries: list[str]) -> list[str]:
    """Expand range entries to individual layer names (topology.rs:46-69)."""
    out: list[str] = []
    for entry in entries:
        m = _RANGE_RE.match(entry)
        if m:
            prefix, start, stop = m.group(1), int(m.group(2)), int(m.group(3))
            if stop <= start:
                raise ValueError(
                    f"invalid layer range '{entry}': stop must be > start"
                )
            out.extend(f"{prefix}{i}" for i in range(start, stop + 1))
        else:
            out.append(entry)
    return out


@dataclasses.dataclass
class Node:
    """One worker's assignment (topology.rs:13-32).

    ``host`` may be given in YAML as a single address OR a list of
    addresses — the replica set for this segment, in failover order. The
    master connects to the first and, when a mid-stream recovery deadline
    for it expires, fails over to the next (every replica must serve the
    same layers; the handshake enforces it). ``host`` always holds the
    primary; ``hosts`` the full ordered set."""

    name: str
    host: str = ""
    description: str = ""
    layers: list[str] = dataclasses.field(default_factory=list)
    device: int | None = None  # TPU extension: mesh stage index
    hosts: list[str] | None = None  # replica addresses (failover order)

    def __post_init__(self):
        if isinstance(self.host, (list, tuple)):  # YAML list under `host:`
            self.hosts = [str(h) for h in self.host]
            self.host = self.hosts[0] if self.hosts else ""
        elif self.hosts is None:
            self.hosts = [self.host] if self.host else []
        elif self.host and self.host not in self.hosts:
            self.hosts = [self.host] + list(self.hosts)
        elif not self.host and self.hosts:
            self.host = self.hosts[0]

    def is_layer_owner(self, full_name: str) -> bool:
        """Prefix match used by the splitter (topology.rs:25-32): does this
        node own the layer a tensor like
        ``model.layers.3.self_attn.q_proj.weight`` belongs to?"""
        return any(
            full_name == l or full_name.startswith(l + ".") for l in self.layers
        )

    def layer_indices(self, prefix: str = "model.layers.") -> list[int]:
        """Sorted numeric indices of this node's decoder layers."""
        idx = []
        for l in self.layers:
            if l.startswith(prefix):
                tail = l[len(prefix):]
                if tail.isdigit():
                    idx.append(int(tail))
        return sorted(idx)


class Topology:
    """Ordered worker-name -> Node mapping with layer lookups."""

    def __init__(self, nodes: dict[str, Node]):
        self.nodes = nodes

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        nodes = {}
        for name, spec in (d or {}).items():
            spec = spec or {}
            nodes[name] = Node(
                name=name,
                host=spec.get("host", ""),
                description=spec.get("description", ""),
                layers=expand_layer_ranges(list(spec.get("layers", []))),
                device=spec.get("device"),
            )
        return cls(nodes)

    @classmethod
    def from_path(cls, path: str | Path) -> "Topology":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    def to_dict(self) -> dict:
        out = {}
        for name, n in self.nodes.items():
            # round-trip the replica list when there is one; a single
            # address stays the scalar form every pre-replica tool reads
            host = (list(n.hosts) if n.hosts and len(n.hosts) > 1
                    else n.host)
            spec: dict = {"host": host, "description": n.description,
                          "layers": list(n.layers)}
            if n.device is not None:
                spec["device"] = n.device
            out[name] = spec
        return out

    def save(self, path: str | Path) -> None:
        Path(path).write_text(yaml.safe_dump(self.to_dict(), sort_keys=False))

    def get_node_for_layer(self, layer_name: str) -> Node | None:
        """First node listing ``layer_name`` (topology.rs:75-84)."""
        for node in self.nodes.values():
            if layer_name in node.layers:
                return node
        return None

    # -- dict-like surface (topology.rs:87-98 Deref) ------------------------
    def __getitem__(self, name: str) -> Node:
        return self.nodes[name]

    def __contains__(self, name: str) -> bool:
        return name in self.nodes

    def __iter__(self):
        return iter(self.nodes.values())

    def __len__(self) -> int:
        return len(self.nodes)

    # -- planning helpers (TPU build) ---------------------------------------
    def segments(self, num_layers: int, prefix: str = "model.layers.") -> list["Segment"]:
        """Partition ``0..num_layers`` into maximal contiguous runs with a
        single owner each — the coalescing the reference does per decode step
        (llama.rs:88-119: contiguous blocks with equal ``ident()`` batch into
        one RPC), computed once here because the assignment is static."""
        segs: list[Segment] = []
        for i in range(num_layers):
            owner = self.get_node_for_layer(f"{prefix}{i}")
            owner_name = owner.name if owner else None
            if segs and segs[-1].owner == owner_name and segs[-1].stop == i:
                segs[-1] = dataclasses.replace(segs[-1], stop=i + 1)
            else:
                segs.append(Segment(start=i, stop=i + 1, owner=owner_name))
        return segs


@dataclasses.dataclass(frozen=True)
class Segment:
    """A maximal contiguous layer run ``[start, stop)`` owned by one node
    (``owner None`` = local to the master)."""

    start: int
    stop: int
    owner: str | None

    @property
    def length(self) -> int:
        return self.stop - self.start
