"""Per-token flight recorder: a bounded ring of per-token records.

Every token the runtime produces can leave one record behind — kind
(prefill/decode), total and per-segment milliseconds, wire bytes in/out,
serialize/deserialize time, sample time, whether a recovery replay happened
— the black-box view of *where the token's millisecond went* that a
tokens/sec number (master.rs:36-65) cannot answer. Records are plain dicts
in a ``deque(maxlen=capacity)`` ring (old tokens age out, memory stays
bounded) and are optionally streamed to a JSONL file as they land
(``--flight-log PATH``), one JSON object per line.

Disabled by default: ``record()`` is one attribute check when off. The
master/generator hot paths call it per token; enabling costs a dict build +
deque append (+ a file write with a path set).
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

log = logging.getLogger("cake_tpu.obs.flight")


class FlightRecorder:
    """Bounded per-token record ring, optionally teed to a JSONL file."""

    FLUSH_EVERY = 32  # records between file flushes (close() always flushes)

    def __init__(self, capacity: int = 4096):
        self.enabled = False
        self._lock = threading.RLock()
        self._ring: deque = deque(maxlen=capacity)
        self._fh = None
        self._unflushed = 0
        self.path: str | None = None

    def enable(self, path: str | None = None,
               capacity: int | None = None) -> None:
        with self._lock:
            if capacity is not None:
                self._ring = deque(self._ring, maxlen=capacity)
            if path is not None:
                if self._fh is not None:
                    self._fh.close()
                self._fh = open(path, "a")
                self.path = path
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def flush(self) -> None:
        """Drain the batched JSONL tail to disk (idempotent, safe from a
        signal handler): a SIGTERM'd run must not lose its last
        FLUSH_EVERY-1 records to the write batching."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    self._unflushed = 0
                except (OSError, RuntimeError) as e:
                    # RuntimeError: CPython forbids re-entering a buffered
                    # writer — a signal can land while record() is inside
                    # _fh.write() on this same thread. The tail stays
                    # unflushed, but the handler must keep running (chain
                    # to the previous handler, dump metrics).
                    log.error("flight log flush to %s failed: %s",
                              self.path, e)

    def close(self) -> None:
        with self._lock:
            self.enabled = False
            if self._fh is not None:
                try:
                    self._fh.close()  # flushes the batched tail
                except OSError as e:
                    log.error("flight log close failed for %s: %s",
                              self.path, e)
                self._fh = None
                self.path = None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def record(self, **fields) -> None:
        """Append one per-token record (no-op when disabled). Callers pass
        whatever they measured; ``t`` (unix seconds) is stamped here."""
        if not self.enabled:
            return
        rec = dict(fields)
        rec["t"] = round(time.time(), 6)
        with self._lock:
            self._ring.append(rec)
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                    # flush in batches: a per-token syscall under the lock
                    # would put file I/O on the decode hot path
                    self._unflushed += 1
                    if self._unflushed >= self.FLUSH_EVERY:
                        self._fh.flush()
                        self._unflushed = 0
                except OSError as e:
                    # an observability tee must never kill the workload it
                    # observes: drop the file, keep the in-memory ring
                    log.error("flight log write to %s failed (%s); "
                              "disabling the file tee", self.path, e)
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
                    self.path = None

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def totals(self) -> dict:
        """Aggregate view over the ring: record count by kind plus sums of
        every numeric field (wire_bytes_out, sample_ms, ...)."""
        out: dict = {"records": 0, "by_kind": {}}
        for rec in self.records():
            out["records"] += 1
            kind = rec.get("kind", "?")
            out["by_kind"][kind] = out["by_kind"].get(kind, 0) + 1
            for k, v in rec.items():
                if k in ("t", "index", "kind"):
                    continue
                if isinstance(v, bool):
                    out[k] = out.get(k, 0) + int(v)
                elif isinstance(v, (int, float)):
                    out[k] = out.get(k, 0) + v
                elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, float)) for x in v
                ):
                    acc = out.setdefault(k, [])
                    for i, x in enumerate(v):
                        if i < len(acc):
                            acc[i] += x
                        else:
                            acc.append(x)
        return out


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(**fields) -> None:
    _RECORDER.record(**fields)
