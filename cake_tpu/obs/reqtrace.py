"""Request-scoped fleet tracing + SLO accounting (Dapper-style).

The aggregate planes (:mod:`cake_tpu.obs.metrics` histograms, the
process-local :mod:`cake_tpu.obs.trace` spans) answer "how is the fleet
doing"; this module answers "where did THIS request spend its 900 ms".
A :class:`ReqTrace` context is minted (or honored from the client's
``traceparent`` header) at the first tier a request touches, rides the
HTTP hop gateway → serve as a W3C ``traceparent`` header and the
prefill → decode hop as a ``trace`` field inside the snapshot frame's
JSON metadata, and collects per-request spans (``gateway.route``,
``serve.queue``, ``engine.prefill``, ``disagg.transfer`` …) stamped on
the unix-epoch timebase so any tier can rebase and merge them.

Three consumers sit on top:

- the process-global :class:`~cake_tpu.obs.trace.Tracer` — every span is
  mirrored into it live (and remote tiers' spans are stitched in via
  :func:`stitch_timeline`), so ``--trace`` on any tier exports ONE
  Perfetto-valid multi-process timeline of the whole fleet;
- the bounded :class:`RequestLog` behind ``GET /v1/requests/<id>`` — the
  per-request JSON timeline plus SLO verdict, queryable after the fact;
- :class:`SloTracker` — per-class TTFT/TPOT targets
  (``--slo-ttft-ms``/``--slo-tpot-ms``) turned into ``slo.good``/
  ``slo.bad`` counters and multi-window burn-rate gauges
  (Aurora/Borg-style: burn = bad-fraction ÷ error budget; 1.0 means
  exactly spending budget, >1 means burning it faster than allowed).

Everything here is thread-safe and near-zero cost when unused: a request
with no inbound header and no started tracer still gets a context (the
span records double as the flight-record timeline), but span bodies do
no I/O and the log is a bounded ring.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import trace as obs_trace

HEADER = "traceparent"  # W3C: 00-<32hex trace>-<16hex span>-<2hex flags>

MAX_SPANS = 256          # per-request span cap (a runaway stream can't OOM)
LOG_CAP = 512            # RequestLog entries retained

REQUESTS = obs_metrics.counter("reqtrace.requests")
STITCHED = obs_metrics.counter("reqtrace.stitched")
HEADER_ERRORS = obs_metrics.counter("reqtrace.header_errors")


def _unix_to_perf(t_unix: float) -> float:
    """Rebase a unix-epoch timestamp onto this process's perf_counter
    timebase (what Tracer.record/record_remote expect)."""
    return time.perf_counter() - (time.time() - t_unix)


class ReqTrace:
    """One request's trace context: id, span records, propagation helpers.

    Span records live on the unix-epoch timebase (``t`` seconds, ``ms``
    duration) with 16-hex span ids and explicit parent ids, so records
    from different processes merge into one causal tree. A per-instance
    per-thread stack parents nested spans; root spans parent to the
    inbound remote span (``parent_id``), which is what connects tiers.
    """

    _THREAD_DOMAIN = "any"

    def __init__(self, trace_id: str, parent_id: str | None = None):
        self.trace_id = trace_id
        self.parent_id = parent_id  # inbound remote span (hex) or None
        self.pid = os.getpid()
        self.request_id: str | None = None
        self.slo: dict | None = None  # verdict set once, at finish
        self._lock = threading.Lock()
        self._spans: list[dict] = []
        self._locals = threading.local()
        self._last_span_id: str | None = None

    # -- construction -----------------------------------------------------

    @classmethod
    def mint(cls) -> "ReqTrace":
        return cls(os.urandom(16).hex())

    @classmethod
    def from_header(cls, value: str | None) -> "ReqTrace":
        """Parse a ``traceparent`` header; malformed values count an
        error and fall back to a fresh mint (never reject the request)."""
        if not value:
            return cls.mint()
        parts = value.strip().split("-")
        if (len(parts) >= 4 and len(parts[1]) == 32 and len(parts[2]) == 16
                and parts[1] != "0" * 32 and parts[2] != "0" * 16):
            try:
                int(parts[1], 16), int(parts[2], 16)
            except ValueError:
                pass
            else:
                return cls(parts[1], parent_id=parts[2])
        HEADER_ERRORS.inc()
        return cls.mint()

    @classmethod
    def from_wire(cls, d: dict | None) -> "ReqTrace | None":
        """Rebuild a context from a snapshot frame's ``trace`` metadata
        (the prefill → decode hop). None in, None out."""
        if not d or not d.get("id"):
            return None
        ctx = cls(str(d["id"]), parent_id=d.get("parent") or None)
        ctx.request_id = d.get("request") or None
        return ctx

    # -- propagation ------------------------------------------------------

    def _current(self) -> str | None:
        st = getattr(self._locals, "stack", None)
        return st[-1] if st else None

    def _fallback_parent(self) -> str | None:
        return self._current() or self._last_span_id or self.parent_id

    def header(self) -> str:
        """Outbound ``traceparent`` value: the current (or most recent)
        span becomes the next tier's parent."""
        sid = self._fallback_parent() or "0" * 16
        return f"00-{self.trace_id}-{sid}-01"

    def wire(self) -> dict:
        """``trace`` metadata for the snapshot frame header."""
        d = {"id": self.trace_id}
        sid = self._fallback_parent()
        if sid:
            d["parent"] = sid
        if self.request_id:
            d["request"] = self.request_id
        return d

    # -- span recording ---------------------------------------------------

    def _record(self, name: str, span_id: str, parent: str | None,
                t_unix: float, dur_ms: float, args: dict) -> None:
        rec = {"name": name, "span": span_id, "t": t_unix,
               "ms": round(dur_ms, 3), "pid": self.pid}
        if parent:
            rec["parent"] = parent
        if args:
            rec["args"] = args
        with self._lock:
            if len(self._spans) < MAX_SPANS:
                self._spans.append(rec)
            self._last_span_id = span_id
        tr = obs_trace.tracer()
        if tr.enabled:
            targs = dict(args, trace=self.trace_id, span=span_id)
            if parent:
                targs["parent_span"] = parent
            tr.record(name, _unix_to_perf(t_unix), dur_ms / 1000.0, targs)

    def add_span(self, name: str, t_start: float, dur_ms: float,
                 parent: str | None = None, **args) -> str:
        """Record an after-the-fact span (``t_start`` unix-epoch seconds).
        Parent defaults to the thread's live span, else the last recorded
        span, else the inbound remote parent."""
        sid = os.urandom(8).hex()
        self._record(name, sid, parent or self._fallback_parent(),
                     t_start, dur_ms, args)
        return sid

    def event(self, name: str, **args) -> str:
        """A zero-duration instant (e.g. ``decode.first_token``)."""
        return self.add_span(name, time.time(), 0.0, **args)

    def span(self, name: str, **args) -> "_ReqSpan":
        """Context manager: times the body, parents to the enclosing
        reqtrace span on this thread (else the inbound remote span)."""
        return _ReqSpan(self, name, args)

    # -- output -----------------------------------------------------------

    def spans(self) -> list[dict]:
        with self._lock:
            return [dict(s) for s in self._spans]

    def timeline(self) -> dict:
        """The ``/v1/requests/<id>`` / flight-record JSON shape."""
        out = {"trace_id": self.trace_id, "spans": self.spans()}
        if self.request_id:
            out["request_id"] = self.request_id
        if self.slo is not None:
            out["slo"] = dict(self.slo)
        return out


class _ReqSpan:
    __slots__ = ("_ctx", "_name", "_args", "_id", "_parent", "_t_unix",
                 "_t_perf")

    def __init__(self, ctx: ReqTrace, name: str, args: dict):
        self._ctx = ctx
        self._name = name
        self._args = args

    def __enter__(self):
        ctx = self._ctx
        st = getattr(ctx._locals, "stack", None)
        if st is None:
            st = ctx._locals.stack = []
        self._parent = st[-1] if st else (ctx._last_span_id
                                          or ctx.parent_id)
        self._id = os.urandom(8).hex()
        st.append(self._id)
        self._t_unix = time.time()
        self._t_perf = time.perf_counter()
        return self

    def __exit__(self, *exc):
        ctx = self._ctx
        dur_ms = (time.perf_counter() - self._t_perf) * 1e3
        st = getattr(ctx._locals, "stack", None)
        if st and st[-1] == self._id:
            st.pop()
        args = self._args
        if exc and exc[0] is not None:
            # a span that died records WHY — retries under chaos read as
            # failed-attempt spans next to the one that landed
            args = dict(args, error=exc[0].__name__)
        ctx._record(self._name, self._id, self._parent, self._t_unix,
                    dur_ms, args)
        return False


# -- per-process request log (behind GET /v1/requests/<id>) ---------------


class RequestLog:
    """Bounded ring of finished-request timelines, keyed by trace id with
    request-id aliases. ``put`` MERGES same-trace entries, so a tiered
    request whose prefill and decode halves land separately still reads
    back as one timeline."""

    _THREAD_DOMAIN = "any"
    _GUARDED_BY = {"_entries": "_lock", "_alias": "_lock"}

    def __init__(self, cap: int = LOG_CAP):
        self._cap = cap
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, dict] = OrderedDict()
        self._alias: OrderedDict[str, str] = OrderedDict()

    def put(self, ctx: ReqTrace) -> None:
        tl = ctx.timeline()
        with self._lock:
            entry = self._entries.get(ctx.trace_id)
            if entry is None:
                entry = {"trace_id": ctx.trace_id, "spans": [],
                         "_ids": set()}
                self._entries[ctx.trace_id] = entry
                REQUESTS.inc()
            for s in tl["spans"]:
                if s["span"] not in entry["_ids"]:
                    entry["_ids"].add(s["span"])
                    entry["spans"].append(s)
            if tl.get("request_id"):
                entry["request_id"] = tl["request_id"]
                self._alias[tl["request_id"]] = ctx.trace_id
            if tl.get("slo") is not None:
                entry["slo"] = tl["slo"]
            self._entries.move_to_end(ctx.trace_id)
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)
            while len(self._alias) > 2 * self._cap:
                self._alias.popitem(last=False)

    def get(self, key: str) -> dict | None:
        """Timeline by request id or trace id (spans sorted by start)."""
        with self._lock:
            tid = self._alias.get(key, key)
            entry = self._entries.get(tid)
            if entry is None:
                return None
            out = {k: v for k, v in entry.items() if k != "_ids"}
            out["spans"] = sorted((dict(s) for s in entry["spans"]),
                                  key=lambda s: s["t"])
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_LOG = RequestLog()


def request_log() -> RequestLog:
    return _LOG


# -- cross-tier stitching --------------------------------------------------


def stitch_timeline(tl: dict, source: str) -> int:
    """Land a remote tier's span timeline (the ``/v1/requests/<id>``
    shape) on the local Tracer under a per-source track, skipping spans
    this process recorded itself (in-process fleets share a pid).
    Returns the number of spans stitched."""
    tr = obs_trace.tracer()
    if not tr.enabled:
        return 0
    me = os.getpid()
    n = 0
    for s in tl.get("spans") or []:
        if s.get("pid") == me:
            continue
        args = dict(s.get("args") or {}, trace=tl.get("trace_id"),
                    span=s.get("span"))
        if s.get("parent"):
            args["parent_span"] = s["parent"]
        tr.record_remote(source, s["name"], _unix_to_perf(s["t"]),
                         s["ms"] / 1000.0, args)
        n += 1
    if n:
        STITCHED.inc()
    return n


# -- SLO accounting --------------------------------------------------------


class SloPolicy:
    """Per-class latency targets. ``objective`` is the good-fraction goal
    (0.99 → a 1% error budget)."""

    def __init__(self, ttft_ms: float | None = None,
                 tpot_ms: float | None = None, objective: float = 0.99):
        self.ttft_ms = ttft_ms
        self.tpot_ms = tpot_ms
        self.objective = objective

    @property
    def enabled(self) -> bool:
        return self.ttft_ms is not None or self.tpot_ms is not None

    def verdict(self, ttft_ms: float | None,
                tpot_ms: float | None) -> dict:
        """Judge one request. A missing measurement passes its half (a
        zero-token reply has no TPOT to miss)."""
        ttft_ok = (self.ttft_ms is None or ttft_ms is None
                   or ttft_ms <= self.ttft_ms)
        tpot_ok = (self.tpot_ms is None or tpot_ms is None
                   or tpot_ms <= self.tpot_ms)
        out = {"good": bool(ttft_ok and tpot_ok)}
        if self.ttft_ms is not None:
            out["ttft_ms"] = None if ttft_ms is None else round(ttft_ms, 3)
            out["ttft_target_ms"] = self.ttft_ms
            out["ttft_ok"] = bool(ttft_ok)
        if self.tpot_ms is not None:
            out["tpot_ms"] = None if tpot_ms is None else round(tpot_ms, 3)
            out["tpot_target_ms"] = self.tpot_ms
            out["tpot_ok"] = bool(tpot_ok)
        return out


class SloTracker:
    """Burn-rate accounting over a ring of recent verdicts.

    burn(window) = bad-fraction(window) / (1 - objective): 1.0 means the
    error budget is being spent exactly at the allowed rate, >1 means an
    alertable burn (the classic short/long multi-window pattern: page on
    short AND long both hot)."""

    _THREAD_DOMAIN = "any"
    _GUARDED_BY = {"_ring": "_lock"}

    SHORT_S = 60.0
    LONG_S = 600.0

    def __init__(self, policy: SloPolicy):
        self.policy = policy
        self._lock = threading.Lock()
        self._ring: deque[tuple[float, bool]] = deque()
        self._good = obs_metrics.counter("slo.good")
        self._bad = obs_metrics.counter("slo.bad")
        self._burn_short = obs_metrics.gauge("slo.burn_short")
        self._burn_long = obs_metrics.gauge("slo.burn_long")

    def observe(self, ttft_ms: float | None,
                tpot_ms: float | None) -> dict:
        v = self.policy.verdict(ttft_ms, tpot_ms)
        (self._good if v["good"] else self._bad).inc()
        now = time.time()
        with self._lock:
            self._ring.append((now, v["good"]))
            self._refresh_locked(now)
        return v

    def _refresh_locked(self, now: float) -> None:
        ring = self._ring
        while ring and now - ring[0][0] > self.LONG_S:
            ring.popleft()
        budget = max(1e-9, 1.0 - self.policy.objective)
        n_long = len(ring)
        bad_long = sum(1 for t, good in ring if not good)
        short = [(t, good) for t, good in ring if now - t <= self.SHORT_S]
        n_short = len(short)
        bad_short = sum(1 for t, good in short if not good)
        self._burn_short.set(
            (bad_short / n_short / budget) if n_short else 0.0)
        self._burn_long.set(
            (bad_long / n_long / budget) if n_long else 0.0)

    def snapshot(self) -> dict:
        """The ``/healthz`` ``slo`` block."""
        now = time.time()
        with self._lock:
            self._refresh_locked(now)
            n = len(self._ring)
            bad = sum(1 for t, good in self._ring if not good)
            burn_short = self._burn_short.value
            burn_long = self._burn_long.value
        out = {"objective": self.policy.objective,
               "window_n": n, "window_bad": bad,
               "burn_short": round(burn_short, 4),
               "burn_long": round(burn_long, 4)}
        if self.policy.ttft_ms is not None:
            out["ttft_target_ms"] = self.policy.ttft_ms
        if self.policy.tpot_ms is not None:
            out["tpot_target_ms"] = self.policy.tpot_ms
        return out
