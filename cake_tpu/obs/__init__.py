"""Unified observability layer: metrics, spans, per-token flight records.

Three composable planes, all stdlib-only at import and near-zero overhead
when off, threaded through every layer of the runtime:

- :mod:`cake_tpu.obs.metrics` — process-global registry of thread-safe
  counters / gauges / fixed-bucket histograms; JSON and Prometheus dumps.
- :mod:`cake_tpu.obs.trace` — context-manager spans with Chrome
  trace-event export (Perfetto / ``chrome://tracing``) and optional
  ``jax.profiler.TraceAnnotation`` pass-through.
- :mod:`cake_tpu.obs.flight` — bounded ring of per-token records
  (per-segment ms, wire bytes, serialize/sample ms, recoveries),
  appendable to JSONL.

CLI surface: ``--trace PATH``, ``--metrics-out PATH``, ``--flight-log
PATH``, ``--log-level``.
"""

from __future__ import annotations

import logging

from cake_tpu.obs import flight, metrics, trace  # noqa: F401
from cake_tpu.obs.metrics import (  # noqa: F401
    counter,
    gauge,
    histogram,
    registry,
)
from cake_tpu.obs.trace import span, tracer  # noqa: F401

LOG_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def setup_logging(level: str | int = "info") -> None:
    """Configure root logging once, identically in master and worker
    processes (CLI ``--log-level``; ``-v`` maps to debug). Reconfigures on
    repeat calls so a library user can override an earlier basicConfig."""
    if isinstance(level, str):
        level = _LEVELS.get(level.lower(), logging.INFO)
    logging.basicConfig(level=level, format=LOG_FORMAT, force=True)
