"""Unified observability layer: metrics, spans, per-token flight records.

Three composable planes, all stdlib-only at import and near-zero overhead
when off, threaded through every layer of the runtime:

- :mod:`cake_tpu.obs.metrics` — process-global registry of thread-safe
  counters / gauges / fixed-bucket histograms; JSON and Prometheus dumps.
- :mod:`cake_tpu.obs.trace` — context-manager spans with Chrome
  trace-event export (Perfetto / ``chrome://tracing``) and optional
  ``jax.profiler.TraceAnnotation`` pass-through.
- :mod:`cake_tpu.obs.flight` — bounded ring of per-token records
  (per-segment ms, wire bytes, serialize/sample ms, recoveries),
  appendable to JSONL.
- :mod:`cake_tpu.obs.prof` — sampled engine-step phase breakdown,
  runtime retrace sentinel (steady-state decode recompiles), and
  device/host/kvpool memory watermarks (``GET /debug/prof``).

Cluster scope (the cross-process tier on top of the three planes):

- :mod:`cake_tpu.obs.clock` — per-connection clock-offset/RTT estimation
  (ping exchange) behind cross-process trace stitching.
- :mod:`cake_tpu.obs.cluster` — worker snapshot scraper, ``cluster.*``
  metric merge, straggler detection (``--cluster-report``).
- :mod:`cake_tpu.obs.top` — live ANSI cluster panel (``--top``).
- :mod:`cake_tpu.obs.statusd` — shared ``/`` JSON + ``/metrics``
  Prometheus HTTP surface (worker and master ``--status-port``).

CLI surface: ``--trace PATH``, ``--metrics-out PATH``, ``--flight-log
PATH``, ``--log-level``, ``--cluster-report PATH``, ``--top``,
``--status-port``/``--status-bind``.
"""

from __future__ import annotations

import logging

from cake_tpu.obs import clock, flight, metrics, prof, reqtrace, trace  # noqa: F401
from cake_tpu.obs.metrics import (  # noqa: F401
    counter,
    gauge,
    histogram,
    registry,
)
from cake_tpu.obs.trace import span, tracer  # noqa: F401

LOG_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def setup_logging(level: str | int = "info") -> None:
    """Configure root logging once, identically in master and worker
    processes (CLI ``--log-level``; ``-v`` maps to debug). Reconfigures on
    repeat calls so a library user can override an earlier basicConfig."""
    if isinstance(level, str):
        level = _LEVELS.get(level.lower(), logging.INFO)
    logging.basicConfig(level=level, format=LOG_FORMAT, force=True)


# -- artifact durability ------------------------------------------------------
#
# The CLI writes its observability artifacts on the clean exit path; a
# SIGTERM'd or SIGINT'd run used to lose the batched flight-log tail and the
# whole --metrics-out dump. These hooks make the artifacts crash-durable:
# flush on SIGTERM/SIGINT (then chain to the previous handler so exit
# semantics — KeyboardInterrupt, exit code 143 — are unchanged) and via
# atexit as the backstop for sys.exit paths.

_flush_state = {"metrics_out": None, "installed": False, "prev": {}}


def flush_artifacts() -> None:
    """Flush every enabled observability sink now (idempotent; safe from a
    signal handler — the flight/metrics locks it takes are reentrant, so a
    handler landing on a thread interrupted mid-record cannot deadlock)."""
    flight.recorder().flush()
    path = _flush_state["metrics_out"]
    if path:
        try:
            registry().dump_json(path)
        except OSError as e:
            logging.getLogger("cake_tpu.obs").error(
                "metrics flush to %s failed: %s", path, e)


def _flush_handler(signum, frame):
    try:
        flush_artifacts()
    except Exception:  # noqa: BLE001 — never block the signal chain
        logging.getLogger("cake_tpu.obs").exception("artifact flush failed")
    import os
    import signal as _signal

    prev = _flush_state["prev"].get(signum, _signal.SIG_DFL)
    if callable(prev):
        prev(signum, frame)
    elif prev != _signal.SIG_IGN:
        # re-deliver under the default disposition: the process still dies
        # of the signal (exit code 128+n), just with its artifacts on disk
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def install_flush_handlers(metrics_out: str | None = None) -> None:
    """Arm SIGTERM/SIGINT + atexit artifact flushing (CLI entry; safe to
    call again — e.g. in-process tests — to re-point ``metrics_out``)."""
    import atexit
    import signal as _signal

    _flush_state["metrics_out"] = metrics_out
    if _flush_state["installed"]:
        return
    _flush_state["installed"] = True
    atexit.register(flush_artifacts)
    for signum in (_signal.SIGTERM, _signal.SIGINT):
        try:
            prev = _signal.getsignal(signum)
            _signal.signal(signum, _flush_handler)
            _flush_state["prev"][signum] = prev
        except ValueError:  # not the main thread: atexit still covers exit
            pass
