"""Shared HTTP status surface: ``/`` JSON + ``/metrics`` Prometheus.

One handler shape for every process that exposes itself over HTTP — the
worker's ``--status-port`` page (the headless stand-in for the reference's
worker GUI), the master's own ``--status-port`` (whose registry additionally
carries the merged ``cluster.*`` series), and the serving plane's API port
(``cake_tpu.serve.api`` mounts these two routes next to its traffic
endpoints, so one port serves both requests and observability).
``status_fn`` supplies the JSON body; ``/metrics`` always serves the
process-global registry in Prometheus text exposition.

Binding defaults to loopback: a status page leaks identity, layer
assignments, and traffic counters, so exposing it beyond the host is an
explicit ``--status-bind`` decision, not a side effect of starting it.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading

from cake_tpu.obs import metrics as _metrics

log = logging.getLogger("cake_tpu.obs.statusd")


def status_response(status_fn, path: str) -> tuple[bytes, str]:
    """Body + content type for one status-surface GET: ``/metrics`` is the
    process-global registry in Prometheus text exposition, anything else is
    ``status_fn()`` as JSON (which embeds the same registry snapshot under
    ``metrics``). The ONE place the bytes are built — every server that
    exposes the surface (``start_status_server`` here, ``serve.api``'s
    mounted routes) calls this, so their output stays byte-identical."""
    path = path.rstrip("/")
    if path == "/metrics":
        return (_metrics.registry().to_prometheus().encode(),
                "text/plain; version=0.0.4")
    if path == "/debug/prof":
        # engine profiling plane (obs/prof): phase percentiles, compile/
        # retrace counts, memory watermarks — same body on every surface
        # that mounts this handler (worker statusd, serve API port)
        from cake_tpu.obs import prof as _prof

        return (json.dumps(_prof.report(), indent=1).encode(),
                "application/json")
    return json.dumps(status_fn(), indent=1).encode(), "application/json"


def start_status_server(status_fn, bind: str = "127.0.0.1", port: int = 0):
    """Serve ``status_fn()`` as JSON on ``/`` and the metrics registry as
    Prometheus text on ``/metrics``. Returns ``(httpd, bound_port)``;
    daemon-threaded, stopped with ``httpd.shutdown()`` +
    ``httpd.server_close()``."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib casing)
            body, ctype = status_response(status_fn, self.path)
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            log.debug("status: " + fmt, *args)

    httpd = http.server.ThreadingHTTPServer((bind, port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]
