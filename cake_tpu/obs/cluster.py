"""Cluster metrics aggregation, health, and straggler detection.

Per-process registries (:mod:`cake_tpu.obs.metrics`) stop at the process
boundary; this module is the master-side view across them. A
:class:`ClusterScraper` pulls each worker's status/registry snapshot —
over the wire via the ``STATS`` message on the op connection (workers
without a status port) or over HTTP from a ``--status-port`` page — and

- merges them into ``cluster.<worker>.*`` gauges in the master's own
  registry (so ``--metrics-out`` and the master's ``/metrics`` page carry
  the whole cluster in one scrape),
- computes per-worker segment forward p50/p99 and flags **stragglers**:
  a worker whose forward p99 exceeds the median of its peers' p99s
  (leave-one-out, so a slow worker cannot drag the baseline toward
  itself) by a configurable factor — in a pipeline, the worker that sets
  decode latency,
- carries the per-connection RTT and clock offset estimated by
  :mod:`cake_tpu.obs.clock`.

``scrape()`` returns (and ``--cluster-report`` persists) one JSON-ready
report; :mod:`cake_tpu.obs.top` renders the same report live.
"""

from __future__ import annotations

import logging
import statistics
import time

from cake_tpu.obs import metrics as _metrics

log = logging.getLogger("cake_tpu.obs.cluster")

DEFAULT_STRAGGLER_FACTOR = 2.0


def runner_link(runner) -> dict:
    """Connection-level health the master measured itself: min-of-N
    ping RTT + clock offset (clock.ClockSync), falling back to the
    handshake RTT for peers without the ping capability. For a runner
    with a replica set, also WHICH replica is live (``"2/3"``) — after a
    failover the cluster view must show where the segment actually
    runs."""
    clock = getattr(runner, "clock", None)
    if clock is not None and clock.synced:
        snap = clock.snapshot()
        link = {"rtt_ms": snap["rtt_ms"],
                "clock_offset_ms": snap["offset_ms"]}
    else:
        info = getattr(runner, "info", None)
        rtt = getattr(info, "latency_ms", None) if info else None
        link = {"rtt_ms": round(rtt, 4) if rtt else None,
                "clock_offset_ms": None}
    addrs = getattr(runner, "addrs", None)
    if addrs and len(addrs) > 1:
        link["replica"] = f"{runner._addr_idx + 1}/{len(addrs)}"
    return link


class WireSource:
    """Worker snapshots over the existing op connection (MsgType.STATS) —
    the path for workers that never opened a status port. Serialized
    against the runner's forward loop by the runner's own lock."""

    def __init__(self, runner):
        self.runner = runner

    @property
    def name(self) -> str:
        return self.runner.info.name

    @property
    def addr(self) -> str:
        return self.runner.ident()

    def fetch(self) -> dict | None:
        try:
            return self.runner.fetch_stats()
        except Exception as e:
            log.debug("stats fetch from %s failed: %s", self.addr, e)
            return None

    def link(self) -> dict:
        return runner_link(self.runner)


class HttpSource:
    """Worker snapshots over the status HTTP surface (``--status-port``) —
    the fallback scrape path for a peer without CAP_STATS that advertised
    a ``status_port`` in its handshake (or any status URL handed in
    directly). ``runner`` optionally supplies the connection-level
    RTT/offset view the page itself cannot know."""

    def __init__(self, url: str, name: str | None = None,
                 timeout_s: float = 5.0, runner=None):
        if not url.startswith("http"):
            url = f"http://{url}/"
        self.url = url
        self._name = name
        self.addr = url
        self.timeout_s = timeout_s
        self.runner = runner

    @property
    def name(self) -> str:
        return self._name or self.url

    def fetch(self) -> dict | None:
        import json
        import urllib.request

        try:
            with urllib.request.urlopen(self.url, timeout=self.timeout_s) as r:
                st = json.loads(r.read())
            if self._name is None:
                self._name = st.get("name")
            return st
        except Exception as e:
            log.debug("status fetch from %s failed: %s", self.url, e)
            return None

    def link(self) -> dict:
        if self.runner is not None:
            return runner_link(self.runner)
        return {"rtt_ms": None, "clock_offset_ms": None}


def _forward_pcts(status: dict) -> tuple[float | None, float | None]:
    """(p50, p99) of the worker's segment forward time: the instance-owned
    ``forward_ms`` snapshot when the status page carries one (always
    per-worker correct), else the ``worker.forward_ms`` registry series."""
    hist = status.get("forward_ms") or (
        status.get("metrics") or {}).get("worker.forward_ms") or {}
    return hist.get("p50"), hist.get("p99")


class ClusterScraper:
    """Pull + merge worker snapshots; flag stragglers.

    ``sources`` are objects with ``name``/``addr`` and ``fetch()`` /
    ``link()`` (WireSource, HttpSource, or anything test-shaped alike).
    """

    def __init__(self, sources, straggler_factor: float =
                 DEFAULT_STRAGGLER_FACTOR, registry=None):
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler factor must exceed 1.0 (got {straggler_factor})"
            )
        self.sources = list(sources)
        self.straggler_factor = straggler_factor
        self._registry = registry or _metrics.registry()
        self.last_report: dict | None = None
        self._flagged: set[str] = set()  # warn on transitions, not repeats

    def _gauge(self, worker: str, key: str, value) -> None:
        if value is not None:
            self._registry.gauge(f"cluster.{worker}.{key}").set(value)

    def scrape(self) -> dict:
        """One aggregation pass: fetch every source, update ``cluster.*``
        gauges, recompute straggler flags, return the report dict."""
        workers: dict[str, dict] = {}
        for src in self.sources:
            # one bad source must not kill the pass: the sources' own
            # fetch() already swallows transport errors into None, but a
            # third-party source (or a link() racing a failover) may
            # still raise — report that worker down and keep scraping
            try:
                st = src.fetch()
                link = src.link()
            except Exception as e:
                log.warning("scrape of %s failed: %s", src.addr, e)
                st, link = None, {"rtt_ms": None, "clock_offset_ms": None}
            name = src.name
            if st is None:
                workers[name] = {"addr": src.addr, "up": False, **link}
                self._gauge(name, "up", 0)
                continue
            p50, p99 = _forward_pcts(st)
            row = {
                "addr": src.addr,
                "up": True,
                "layer_runs": st.get("layer_runs"),
                "ops_total": st.get("ops_total"),
                "bytes_in": st.get("bytes_in"),
                "bytes_out": st.get("bytes_out"),
                "connections_live": st.get("connections_live"),
                "uptime_s": st.get("uptime_s"),
                "forward_p50_ms": p50,
                "forward_p99_ms": p99,
                "warmup_ms": st.get("warmup_ms"),
                **link,
            }
            workers[name] = row
            self._gauge(name, "up", 1)
            for key in ("ops_total", "bytes_in", "bytes_out",
                        "connections_live", "forward_p50_ms",
                        "forward_p99_ms", "rtt_ms", "clock_offset_ms"):
                self._gauge(name, key, row.get(key))

        # straggler flagging: each worker's p99 against the median of its
        # PEERS' p99s (leave-one-out), scaled by the operator's tolerance
        # factor. Against the global median a slow worker drags the
        # baseline toward itself — with 2 workers the global median IS the
        # mean, so a factor >= 2 could mathematically never flag, however
        # slow the slow one. Needs >= 2 measurable workers to mean
        # anything (a cluster of one has no peers).
        p99s = {n: w["forward_p99_ms"] for n, w in workers.items()
                if w.get("forward_p99_ms")}
        median_p99 = statistics.median(p99s.values()) if p99s else None
        stragglers = []
        for name, w in workers.items():
            peers = [v for n, v in p99s.items() if n != name]
            flagged = bool(
                peers
                and w.get("forward_p99_ms")
                and w["forward_p99_ms"]
                > statistics.median(peers) * self.straggler_factor
            )
            w["straggler"] = flagged
            self._gauge(name, "straggler", int(flagged))
            if flagged:
                stragglers.append(name)
                # warn once per transition: --top rescrapes every second,
                # and a repeated warning for an unchanged condition floods
                # stderr (where the panel repaints in place)
                log.log(
                    logging.DEBUG if name in self._flagged
                    else logging.WARNING,
                    "straggler: %s forward p99 %.2f ms > %.1fx peer "
                    "median %.2f ms", name, w["forward_p99_ms"],
                    self.straggler_factor, statistics.median(peers),
                )
        for name in self._flagged - set(stragglers):
            if name in workers:
                log.info("straggler recovered: %s", name)
        self._flagged = set(stragglers)
        if median_p99 is not None:
            self._registry.gauge("cluster.forward_p99_median_ms").set(
                median_p99)
        self._registry.gauge("cluster.workers_up").set(
            sum(1 for w in workers.values() if w["up"]))
        self._registry.gauge("cluster.stragglers").set(len(stragglers))

        report = {
            "t": round(time.time(), 3),
            "straggler_factor": self.straggler_factor,
            "median_forward_p99_ms": median_p99,
            "stragglers": stragglers,
            "workers": workers,
        }
        self.last_report = report
        return report
