"""Span tracer with Chrome trace-event JSON export and cluster merge.

Context-manager spans (``with span("decode.segment", seg=i):``) record
complete ``"ph": "X"`` events — name, start, duration, pid/tid, args — into
a bounded in-memory buffer, exported as Chrome trace-event JSON that
Perfetto / ``chrome://tracing`` load directly (the Dapper-style timeline
view of a decode step: local scan vs wire serialize vs remote round-trip vs
sampling). Per-thread span stacks give each event its enclosing span's name
as ``args.parent``, so nested timelines stay legible even when events from
many threads interleave.

Cluster stitching (Dapper-style, Sigelman et al. 2010): every started
tracer owns a ``trace_id`` and every live span an id
(:func:`current_span_id`), which the master propagates to workers on the
wire so their spans join the same causal timeline. Worker span digests come
back in replies; :meth:`Tracer.record_remote` lands them — already rebased
onto the master clock via :mod:`cake_tpu.obs.clock` — under a per-source
synthetic pid, so ``to_chrome_trace`` emits ONE multi-process trace with a
named track per worker next to the master's own.

Disabled (the default), ``span()`` returns a shared no-op context manager —
one attribute check per call site, nothing recorded. Enable with
``tracer().start()`` (the CLI's ``--trace PATH`` does this and writes the
file on exit). ``start(xla_annotations=True)`` additionally wraps every span
in ``jax.profiler.TraceAnnotation`` so the same names appear inside XLA
profiles captured with ``--profile``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

_local = threading.local()


def _stack() -> list:
    """Per-thread stack of live (name, span_id) pairs."""
    s = getattr(_local, "stack", None)
    if s is None:
        s = _local.stack = []
    return s


def current_span_id() -> int:
    """Id of this thread's innermost live span (0 = no span / disabled) —
    what the master sends as ``parent_span_id`` on a remote hop."""
    s = getattr(_local, "stack", None)
    return s[-1][1] if s else 0


class Tracer:
    """Process-global span recorder (thread-safe; bounded)."""

    def __init__(self):
        self.enabled = False
        self.xla_annotations = False
        self.dropped = 0
        self.trace_id = ""
        self._max_events = 1_000_000
        # (name, ts_us, dur_us, tid, args, source); source None = this
        # process, else the remote identity the event was stitched in from
        self._events: list[tuple] = []
        self._sources: list[str] = []  # remote sources in arrival order
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def start(self, max_events: int = 1_000_000,
              xla_annotations: bool = False) -> None:
        with self._lock:
            self._events = []
            self._sources = []
            self.dropped = 0
            self._max_events = max_events
            self._t0 = time.perf_counter()
            self.xla_annotations = xla_annotations
            self.trace_id = os.urandom(8).hex()
            self._ids = itertools.count(1)
            self.enabled = True

    def stop(self) -> None:
        self.enabled = False
        self.xla_annotations = False

    def next_span_id(self) -> int:
        return next(self._ids)

    def record(self, name: str, t_start: float, dur: float, args: dict) -> None:
        ev = (
            name,
            (t_start - self._t0) * 1e6,
            dur * 1e6,
            threading.get_ident(),
            args,
            None,
        )
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def record_remote(self, source: str, name: str, t_start: float,
                      dur: float, args: dict, tid: int = 1) -> None:
        """Land one remote span on the merged timeline. ``t_start`` must
        already be rebased onto THIS process's ``perf_counter`` timebase
        (clock.ClockSync.to_master); ``source`` names the remote process
        ('w1@host:port') and becomes its own pid/track in the export."""
        ev = (
            name,
            (t_start - self._t0) * 1e6,
            dur * 1e6,
            tid,
            args,
            source,
        )
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            if source not in self._sources:
                self._sources.append(source)
            self._events.append(ev)

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self._sources = []
            self.dropped = 0

    def to_chrome_trace(self) -> dict:
        """Trace-event JSON object: complete ``X`` events sorted by ``ts``
        plus process/thread-name metadata, loadable in Perfetto. Remote
        events (``record_remote``) are emitted under a distinct synthetic
        pid per source with a ``process_name`` row, so a stitched cluster
        run renders as one multi-process timeline."""
        pid = os.getpid()
        with self._lock:
            events = sorted(self._events, key=lambda e: e[1])
            sources = list(self._sources)
        # synthetic pids must collide with neither the real pid nor each
        # other; the trace file is self-contained so any distinct ints do
        src_pid = {s: pid + 1 + i for i, s in enumerate(sources)}
        names = {t.ident: t.name for t in threading.enumerate()}
        tids = sorted({e[3] for e in events if e[5] is None})
        out = [
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"master/{os.uname().nodename}"
                         if hasattr(os, "uname") else "master"},
            }
        ]
        out += [
            {
                "name": "process_name", "ph": "M", "pid": src_pid[s],
                "tid": 0, "args": {"name": s},
            }
            for s in sources
        ]
        out += [
            {
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": names.get(tid, f"thread-{tid}")},
            }
            for tid in tids
        ]
        for name, ts, dur, tid, args, source in events:
            ev = {
                "name": name, "cat": "cake", "ph": "X",
                "ts": round(ts, 3), "dur": round(dur, 3),
                "pid": pid if source is None else src_pid[source],
                "tid": tid,
            }
            if args:
                ev["args"] = args
            out.append(ev)
        doc = {"traceEvents": out, "displayTimeUnit": "ms"}
        if self.dropped:
            # surfaced in the file itself so a truncated timeline can
            # never be read as complete (Perfetto ignores extra keys)
            doc["otherData"] = {"dropped_events": self.dropped}
        return doc

    def write_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
            f.write("\n")


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_name", "_args", "_t0", "_ann", "_id")

    def __init__(self, name: str, args: dict):
        self._name = name
        self._args = args
        self._ann = None

    def __enter__(self):
        stack = _stack()
        if stack:
            self._args = dict(self._args, parent=stack[-1][0])
        self._id = _TRACER.next_span_id()
        stack.append((self._name, self._id))
        if _TRACER.xla_annotations:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self._name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        stack = _stack()
        if stack and stack[-1][1] == self._id:
            stack.pop()
        _TRACER.record(self._name, self._t0, dur, self._args)
        return False


def span(name: str, **args):
    """A timed span; no-op unless the tracer is started."""
    if not _TRACER.enabled:
        return _NULL_SPAN
    return _Span(name, args)
