"""Span tracer with Chrome trace-event JSON export.

Context-manager spans (``with span("decode.segment", seg=i):``) record
complete ``"ph": "X"`` events — name, start, duration, pid/tid, args — into
a bounded in-memory buffer, exported as Chrome trace-event JSON that
Perfetto / ``chrome://tracing`` load directly (the Dapper-style timeline
view of a decode step: local scan vs wire serialize vs remote round-trip vs
sampling). Per-thread span stacks give each event its enclosing span's name
as ``args.parent``, so nested timelines stay legible even when events from
many threads interleave.

Disabled (the default), ``span()`` returns a shared no-op context manager —
one attribute check per call site, nothing recorded. Enable with
``tracer().start()`` (the CLI's ``--trace PATH`` does this and writes the
file on exit). ``start(xla_annotations=True)`` additionally wraps every span
in ``jax.profiler.TraceAnnotation`` so the same names appear inside XLA
profiles captured with ``--profile``.
"""

from __future__ import annotations

import json
import os
import threading
import time

_local = threading.local()


def _stack() -> list:
    s = getattr(_local, "stack", None)
    if s is None:
        s = _local.stack = []
    return s


class Tracer:
    """Process-global span recorder (thread-safe; bounded)."""

    def __init__(self):
        self.enabled = False
        self.xla_annotations = False
        self.dropped = 0
        self._max_events = 1_000_000
        self._events: list[tuple] = []  # (name, ts_us, dur_us, tid, args)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def start(self, max_events: int = 1_000_000,
              xla_annotations: bool = False) -> None:
        with self._lock:
            self._events = []
            self.dropped = 0
            self._max_events = max_events
            self._t0 = time.perf_counter()
            self.xla_annotations = xla_annotations
            self.enabled = True

    def stop(self) -> None:
        self.enabled = False
        self.xla_annotations = False

    def record(self, name: str, t_start: float, dur: float, args: dict) -> None:
        ev = (
            name,
            (t_start - self._t0) * 1e6,
            dur * 1e6,
            threading.get_ident(),
            args,
        )
        with self._lock:
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self.dropped = 0

    def to_chrome_trace(self) -> dict:
        """Trace-event JSON object: complete ``X`` events sorted by ``ts``
        plus thread-name metadata, loadable in Perfetto."""
        pid = os.getpid()
        with self._lock:
            events = sorted(self._events, key=lambda e: e[1])
        names = {t.ident: t.name for t in threading.enumerate()}
        tids = sorted({e[3] for e in events})
        out = [
            {
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": names.get(tid, f"thread-{tid}")},
            }
            for tid in tids
        ]
        for name, ts, dur, tid, args in events:
            ev = {
                "name": name, "cat": "cake", "ph": "X",
                "ts": round(ts, 3), "dur": round(dur, 3),
                "pid": pid, "tid": tid,
            }
            if args:
                ev["args"] = args
            out.append(ev)
        doc = {"traceEvents": out, "displayTimeUnit": "ms"}
        if self.dropped:
            # surfaced in the file itself so a truncated timeline can
            # never be read as complete (Perfetto ignores extra keys)
            doc["otherData"] = {"dropped_events": self.dropped}
        return doc

    def write_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
            f.write("\n")


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_name", "_args", "_t0", "_ann")

    def __init__(self, name: str, args: dict):
        self._name = name
        self._args = args
        self._ann = None

    def __enter__(self):
        stack = _stack()
        if stack:
            self._args = dict(self._args, parent=stack[-1])
        stack.append(self._name)
        if _TRACER.xla_annotations:
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self._name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(*exc)
        stack = _stack()
        if stack and stack[-1] is self._name:
            stack.pop()
        _TRACER.record(self._name, self._t0, dur, self._args)
        return False


def span(name: str, **args):
    """A timed span; no-op unless the tracer is started."""
    if not _TRACER.enabled:
        return _NULL_SPAN
    return _Span(name, args)
