"""Engine profiling plane: step phases, retrace sentinel, memory marks.

The obs plane can trace a request across the fleet (reqtrace) and scrape
a cluster (cluster), but neither answers *where inside one engine step
the time goes* — the question every perf item (speculation that must
pay, churn vs steady, SLO scheduling) hinges on. Three arms:

- :class:`StepProfiler` — the ``BatchGenerator`` / ``SingleStreamEngine``
  step loops stamp each pass into named phases (``admit``, ``pages``,
  ``guide``, ``dispatch``, ``sync``, ``emit``, and the speculative
  ``spec_propose`` / ``spec_verify`` / ``spec_accept``; the scheduler
  adds ``idle_park`` between passes). Each sampled step feeds the
  per-phase ``prof.phase_ms.*`` histograms and a bounded ring of recent
  step records. Sampling every Nth step (``--prof-sample``, default
  coarse) keeps the steady-state cost inside the existing <= 3% obs
  budget: an unsampled step pays one integer increment at ``step_begin``
  and one attribute check per ``phase()`` call site. Phase stamping is
  host-side driver code only — never inside a jitted body (cakelint
  CK-JIT), and the step/phase calls run on the engine-owner thread
  (CK-THREAD); the ring and report path are lock-guarded for handler
  readers. ``dispatch`` prices the async dispatch call itself; the
  device compute lands in ``sync`` (the host fetch). ``pages`` nests
  inside ``dispatch`` and ``guide`` inside ``emit`` — sub-phases
  attribute their parents' time, they don't extend the step total.

- :class:`RetraceSentinel` — the runtime twin of cakelint CK-JIT, the
  way ``runtime/threadcheck`` twins CK-THREAD: a ``jax.monitoring``
  duration listener counts XLA backend compiles (``prof.compiles``).
  Engines wrap their decode dispatches in :meth:`RetraceSentinel.
  decode_phase`; once :meth:`RetraceSentinel.mark_steady` has been
  called (the serve scheduler marks it after a warmup step budget), any
  compile landing inside a decode dispatch is a *retrace finding* —
  ``prof.retraces`` plus a bounded findings list — warned by default,
  raised as :class:`RetraceError` under ``CAKE_PROF_STRICT=1``. The
  compile-count pins the test suites assert offline (constrain/kvpool
  no-retrace tests) become a live production invariant.

- :func:`memory_watermarks` — device live/peak bytes where the backend
  exposes ``memory_stats()`` (graceful no-op otherwise — CPU returns
  nothing), host RSS/peak from ``/proc/self/status``, and the kvpool
  page gauges stitched in so one report carries the whole memory story.

:func:`report` assembles all three arms into the JSON served at
``GET /debug/prof`` (serve replicas, statusd pages, and the gateway's
fleet-merged view) and rendered by ``obs/top.py``. When the tracer is
started (``--trace``), sampled phases additionally record ``prof.*``
spans, so one Perfetto file shows request spans with the engine phases
nested under them.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import trace as obs_trace

log = logging.getLogger("cake_tpu.obs.prof")

# Default step-sampling stride: coarse enough that the steady-state cost
# is one counter increment per step, fine enough that a minute of serving
# banks hundreds of phase breakdowns.
SAMPLE_DEFAULT = 64

# The declared phase vocabulary (catalog: prof.phase_ms.*). Call sites
# may only stamp these names — a typo'd phase would silently fork a
# series exactly the way the metric catalog exists to prevent.
PHASES = (
    "admit",         # admission / arrival-drain tick (prefill chunk)
    "pages",         # kvpool gather/scatter host prep (page-map upload)
    "guide",         # constrain guide/mask advance (host DFA cursor)
    "dispatch",      # device dispatch call (async: enqueue cost only)
    "sync",          # device sync + host fetch (where compute lands)
    "emit",          # detok / Token fan-out / bookkeeping
    "idle_park",     # scheduler parked waiting for work
    "spec_propose",  # speculative draft proposal (host n-gram walk)
    "spec_verify",   # speculative verify dispatch
    "spec_accept",   # accept/rollback: accept program + bank fetch
)


class RetraceError(RuntimeError):
    """A steady-state decode dispatch recompiled under CAKE_PROF_STRICT=1."""


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


class _Phase:
    """One stamped phase inside a sampled step: accumulates wall ms into
    the step record + the phase histogram, and (tracer started) records
    a ``prof.<name>`` span so the phase lands on the Perfetto timeline
    under whatever request span encloses it."""

    __slots__ = ("_prof", "_name", "_t0", "_span")

    def __init__(self, prof: "StepProfiler", name: str):
        self._prof = prof
        self._name = name

    def __enter__(self):
        self._span = obs_trace.span("prof." + self._name)
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1e3
        self._span.__exit__(*exc)
        self._prof._record_phase(self._name, dt_ms)
        return False


class StepProfiler:
    """Sampled per-step phase breakdown for the engine step loops.

    ``step_begin``/``phase``/``step_end`` run on the engine-owner thread
    (the current-step record is thread-local, so loopback fleets with
    several in-process engines don't race each other); the ring and the
    histograms behind :meth:`phases` are safe for handler threads.
    """

    _GUARDED_BY = {"_ring": "_lock"}

    def __init__(self, sample_every: int | None = None, ring: int = 64):
        if sample_every is None:
            try:
                sample_every = int(
                    os.environ.get("CAKE_PROF_SAMPLE", str(SAMPLE_DEFAULT)))
            except ValueError:
                sample_every = SAMPLE_DEFAULT
        self.sample_every = max(0, sample_every)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, ring))
        self._tl = threading.local()  # .count, .cur, .t0
        self._sampled = obs_metrics.counter("prof.sampled_steps")
        # phase histograms are created lazily per name; cached so the
        # sampled-step cost is a dict hit, not a registry lock
        self._hists: dict[str, object] = {}

    # -- knobs ----------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.sample_every > 0

    def set_sample(self, every: int) -> None:
        """Re-point the sampling stride (``--prof-sample``; 0 disables)."""
        self.sample_every = max(0, int(every))

    # -- engine-thread stamping ----------------------------------------------
    def step_begin(self, engine: str = "batch") -> None:
        """Open one engine step; every ``sample_every``-th call (per
        engine thread) opens a sampled record the inner ``phase()``
        stamps land in. MUST be paired with ``step_end`` (try/finally)."""
        tl = self._tl
        n = getattr(tl, "count", 0)
        tl.count = n + 1
        if not self.sample_every or n % self.sample_every:
            return
        tl.cur = {"engine": engine, "step": n, "phases": {}}
        tl.t0 = time.perf_counter()

    def phase(self, name: str):
        """Context manager stamping one phase of the current step; the
        shared no-op outside a sampled step (one attribute check)."""
        if getattr(self._tl, "cur", None) is None:
            return _NULL_PHASE
        return _Phase(self, name)

    def _hist(self, name: str):
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = obs_metrics.histogram(
                f"prof.phase_ms.{name}")
        return h

    def _record_phase(self, name: str, dt_ms: float) -> None:
        cur = getattr(self._tl, "cur", None)
        if cur is not None:
            cur["phases"][name] = round(
                cur["phases"].get(name, 0.0) + dt_ms, 4)
        self._hist(name).observe(dt_ms)

    def step_end(self) -> None:
        tl = self._tl
        cur = getattr(tl, "cur", None)
        if cur is None:
            return
        tl.cur = None
        cur["total_ms"] = round((time.perf_counter() - tl.t0) * 1e3, 4)
        self._sampled.inc()
        with self._lock:
            self._ring.append(cur)

    def observe_ms(self, name: str, dt_ms: float) -> None:
        """Record one out-of-step phase sample (the scheduler's
        ``idle_park`` waits happen between steps, not inside one)."""
        if self.enabled:
            self._hist(name).observe(dt_ms)

    # -- report ---------------------------------------------------------------
    def recent_steps(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def phases(self) -> dict:
        """Per-phase histogram snapshots (count/mean/p50/p99), keyed by
        the bare phase name."""
        out = {}
        for name, h in sorted(self._hists.items()):
            snap = h.snapshot()
            if snap.get("count"):
                out[name] = snap
        return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
        for h in self._hists.values():
            h.reset()
        self._sampled.reset()


class RetraceSentinel:
    """Runtime CK-JIT twin: count XLA compiles, flag steady-state
    decode-phase compiles as retrace findings."""

    _GUARDED_BY = {"_findings": "_lock"}

    def __init__(self):
        self.compiles = obs_metrics.counter("prof.compiles")
        self.retraces = obs_metrics.counter("prof.retraces")
        self._lock = threading.Lock()
        self._findings: deque = deque(maxlen=32)
        self._steady = False
        self._installed = False
        self._tl = threading.local()  # .depth: inside a decode dispatch

    def install(self) -> None:
        """Register the ``jax.monitoring`` duration listener (idempotent;
        a jax without the API leaves the sentinel a no-op). The listener
        is process-permanent — jax has no per-listener removal — so it
        consults this singleton's live state on every event."""
        if self._installed:
            return
        try:
            from jax import monitoring
        except Exception:  # pragma: no cover - jax always present here
            return
        monitoring.register_event_duration_secs_listener(self._on_duration)
        self._installed = True

    # -- engine-side markers --------------------------------------------------
    def decode_phase(self):
        """Context manager marking 'this thread is inside a decode
        dispatch' — compiles observed in here after ``mark_steady`` are
        retraces. (Compiles are synchronous on the dispatching thread,
        so a thread-local depth is the correct scope.)"""
        return _DecodeRegion(self._tl)

    def mark_steady(self) -> None:
        """Warmup is over: from now on a decode-phase compile is a
        finding. The serve scheduler calls this after its warmup step
        budget (``CAKE_PROF_WARM_STEPS``); tests call it directly."""
        self._steady = True

    @property
    def steady(self) -> bool:
        return self._steady

    def reset(self) -> None:
        """Back to warmup (tests): clears steady, findings, counters."""
        self._steady = False
        with self._lock:
            self._findings.clear()
        self.compiles.reset()
        self.retraces.reset()

    def findings(self) -> list[dict]:
        with self._lock:
            return list(self._findings)

    # -- listener -------------------------------------------------------------
    def _on_duration(self, event: str, dur: float, **kw) -> None:
        if not event.endswith("backend_compile_duration"):
            return
        self.compiles.inc()
        if not self._steady or not getattr(self._tl, "depth", 0):
            return
        self.retraces.inc()
        finding = {
            "event": event,
            "compile_ms": round(dur * 1e3, 3),
            "ts": time.time(),
        }
        with self._lock:
            self._findings.append(finding)
        msg = ("steady-state decode dispatch recompiled "
               f"({dur * 1e3:.1f} ms): a shape/dtype/static-arg varied "
               "after warmup — the no-retrace invariant the offline "
               "compile-count pins assert is broken live")
        if os.environ.get("CAKE_PROF_STRICT", "0") == "1":
            raise RetraceError(msg)
        log.warning("prof.retraces: %s", msg)


class _DecodeRegion:
    __slots__ = ("_tl",)

    def __init__(self, tl):
        self._tl = tl

    def __enter__(self):
        self._tl.depth = getattr(self._tl, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        self._tl.depth -= 1
        return False


# -- memory watermarks --------------------------------------------------------

def _host_rss() -> tuple[int | None, int | None]:
    """(rss_bytes, peak_bytes) from /proc/self/status; (None, None) when
    unavailable (non-Linux)."""
    try:
        with open("/proc/self/status") as f:
            txt = f.read()
    except OSError:
        return None, None
    out = {}
    for key in ("VmRSS", "VmHWM"):
        i = txt.find(key + ":")
        if i >= 0:
            try:
                out[key] = int(txt[i:].split(None, 2)[1]) * 1024
            except (ValueError, IndexError):
                pass
    return out.get("VmRSS"), out.get("VmHWM")


def memory_watermarks() -> dict:
    """Device peak/live bytes (backends exposing ``memory_stats``), host
    RSS/peak, and the kvpool page gauges — refreshed into the ``prof.mem_*``
    gauges so /metrics scrapes carry the same numbers as /debug/prof."""
    out: dict = {}
    reg = obs_metrics.registry()
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if stats:
        live = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        dev = {k: v for k, v in (("bytes_in_use", live),
                                 ("peak_bytes_in_use", peak))
               if v is not None}
        if "bytes_limit" in stats:
            dev["bytes_limit"] = stats["bytes_limit"]
        if dev:
            out["device"] = dev
        if live is not None:
            reg.gauge("prof.mem_device_bytes").set(live)
        if peak is not None:
            reg.gauge("prof.mem_device_peak_bytes").set(peak)
    rss, peak = _host_rss()
    if rss is not None:
        out["host"] = {"rss_bytes": rss, "peak_bytes": peak}
        reg.gauge("prof.mem_host_rss_bytes").set(rss)
        if peak is not None:
            reg.gauge("prof.mem_host_peak_bytes").set(peak)
    kv = reg.snapshot(prefix="kvpool.")
    if kv:
        out["kvpool"] = {k.split(".", 1)[1]: v.get("value")
                         for k, v in kv.items() if v.get("type") == "gauge"}
    return out


# -- process singletons + report ----------------------------------------------

_PROFILER = StepProfiler()
_SENTINEL = RetraceSentinel()


def profiler() -> StepProfiler:
    return _PROFILER


def sentinel() -> RetraceSentinel:
    return _SENTINEL


def report() -> dict:
    """The /debug/prof body: all three arms in one JSON document."""
    p, s = _PROFILER, _SENTINEL
    return {
        "sample_every": p.sample_every,
        "sampled_steps": p._sampled.value,
        "phases": p.phases(),
        "recent_steps": p.recent_steps(),
        "compiles": s.compiles.value,
        "retraces": s.retraces.value,
        "steady": s.steady,
        "findings": s.findings(),
        "memory": memory_watermarks(),
    }
