"""Declared catalog of every metrics-registry series this tree emits.

The registry (:mod:`cake_tpu.obs.metrics`) is string-keyed and
get-or-create by design — independent modules share series without
import-order coupling. The cost of that convenience is that a typo'd
name silently forks a series: ``wire.bytes_out`` and ``wire.byte_out``
would both exist, each half-populated, and every dashboard built on the
real name goes quietly wrong. This catalog is the fix: one declaration
per series (name, kind, meaning), enforced two ways —

- statically, by the ``metrics-catalog`` checker in
  :mod:`cake_tpu.analysis` (``make lint``): every series-name literal at
  a ``counter()``/``gauge()``/``histogram()``/instrument-constructor
  call site must appear here;
- optionally at runtime: ``CAKE_OBS_STRICT=1`` (or
  ``registry().strict = True``) makes the registry refuse to create an
  undeclared series, for test rigs that want the invariant hot.

Dynamic families (per-segment, per-worker) are declared as patterns with
``*`` standing for exactly the formatted field an f-string interpolates;
the checker derives the same pattern from the f-string AST and requires
an exact match, so even dynamic names can't drift.

Adding a series is a two-line change: the call site and one entry here.
The entry is the review surface — a reviewer sees the new name, its
kind, and what it means, in one place.
"""

from __future__ import annotations

from fnmatch import fnmatchcase

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# name -> (kind, meaning). Grouped by owning subsystem; keep each group
# sorted so diffs stay reviewable.
SERIES: dict[str, tuple[str, str]] = {
    # -- constrained decoding (cake_tpu/constrain) -----------------------
    "constrain.dead_ends": (
        COUNTER, "constrained streams retired at a grammar dead end"),
    "constrain.fsm_cache_hits": (
        COUNTER, "token-DFA compiles served from memo/disk cache"),
    "constrain.fsm_cache_misses": (
        COUNTER, "token-DFA compiles that ran the vocab walk"),
    "constrain.fsm_compile_ms": (
        HISTOGRAM, "grammar -> token-DFA compile wall time"),
    # -- disaggregated prefill/decode (cake_tpu/disagg) ------------------
    "disagg.exports": (
        COUNTER, "stream snapshots exported (prefill handoffs + session "
                 "suspends)"),
    "disagg.handoffs": (
        COUNTER, "gateway two-stage routes completed (prefill -> "
                 "transfer -> decode resume)"),
    "disagg.import_aborts": (
        COUNTER, "imports dropped unresumed (TTL expiry, cancelled "
                 "resume, pool rebuild)"),
    "disagg.imports": (
        COUNTER, "snapshots whose pages landed in the local pool"),
    "disagg.inflight": (
        GAUGE, "KV transfers in flight on this replica (outgoing sends "
               "+ imports awaiting resume) — the /healthz "
               "kv_transfers_inflight field"),
    "disagg.reprefills": (
        COUNTER, "gateway fallbacks that re-prefilled a request after a "
                 "tiered-path failure"),
    "disagg.resumes": (
        COUNTER, "imported streams attached to a slot and decoding"),
    "disagg.transfer_bytes": (
        HISTOGRAM, "snapshot payload size per completed transfer"),
    "disagg.transfer_failures": (
        COUNTER, "transfers that exhausted their retry budget or were "
                 "rejected"),
    "disagg.transfer_ms": (
        HISTOGRAM, "export-to-ACK wall time per completed transfer"),
    # -- gateway (multi-replica routing front door) ----------------------
    "gateway.added_ms": (
        HISTOGRAM, "gateway-added latency ahead of the backend "
                   "(route + connect + request send, failed attempts "
                   "included)"),
    "gateway.backends_up": (GAUGE, "backends currently routable (UP)"),
    "gateway.breaker_open": (
        GAUGE, "DOWN backends whose circuit breaker is holding probes"),
    "gateway.deregistrations": (
        COUNTER, "explicit fleet leaves (the SIGTERM drain path's "
                 "goodbye; pins the member DRAINING)"),
    "gateway.lease_expired": (
        COUNTER, "registration leases that missed their renewal window "
                 "(demotes through the probe hysteresis, never an "
                 "instant delete)"),
    "gateway.queued_admissions": (
        COUNTER, "saturated-fleet requests held in the bounded admission "
                 "queue instead of being shed"),
    "gateway.registrations": (
        COUNTER, "fleet registration/renewal POSTs accepted (dynamic "
                 "membership leases)"),
    "gateway.rejected": (
        COUNTER, "requests refused at the gateway (draining / no backend "
                 "up)"),
    "gateway.requests": (COUNTER, "completions requests accepted"),
    "gateway.retries": (
        COUNTER, "transparent re-routes after a backend failure or 429"),
    "gateway.route_prefix_fallback": (
        COUNTER, "prefix-affinity routes that fell back to p2c"),
    "gateway.route_prefix_hits": (
        COUNTER, "requests landed on their prefix-preferred replica"),
    "gateway.saturated": (
        COUNTER, "429s propagated because every UP backend was saturated"),
    "gateway.shed": (
        COUNTER, "requests shed at the front door under fleet saturation "
                 "(429 with a fleet-derived Retry-After)"),
    # -- engine profiling plane (cake_tpu/obs/prof) ----------------------
    "prof.compiles": (
        COUNTER, "XLA backend compiles observed process-wide "
                 "(jax.monitoring duration events)"),
    "prof.mem_device_bytes": (
        GAUGE, "device memory live bytes (backends exposing "
               "memory_stats; absent elsewhere)"),
    "prof.mem_device_peak_bytes": (
        GAUGE, "device memory high-water mark in bytes"),
    "prof.mem_host_peak_bytes": (
        GAUGE, "host process peak RSS (VmHWM)"),
    "prof.mem_host_rss_bytes": (
        GAUGE, "host process resident set size (VmRSS)"),
    "prof.retraces": (
        COUNTER, "steady-state decode-phase compiles — retrace findings "
                 "(warn; raise under CAKE_PROF_STRICT=1)"),
    "prof.sampled_steps": (
        COUNTER, "engine steps that recorded a sampled phase breakdown"),
    # -- speculative decoding acceptance (runtime/speculative) -----------
    "spec.accept_rate_ema": (
        GAUGE, "EMA of accepted-proposal fraction per round — the "
               "adaptive-spec_k control signal"),
    "spec.accepted": (
        COUNTER, "draft proposals accepted by verification rounds"),
    "spec.proposed": (
        COUNTER, "draft tokens proposed to verification rounds"),
    # -- paged KV pool (cake_tpu/kvpool) ---------------------------------
    "kvpool.admit_defers": (
        COUNTER, "admissions deferred waiting for free pages"),
    "kvpool.cow_copies": (
        COUNTER, "private copy-on-write materializations of partially "
                 "shared prefix pages"),
    "kvpool.evictions": (
        COUNTER, "prefix-tree page claims evicted to refill the free "
                 "list"),
    "kvpool.pages_free": (GAUGE, "pool pages on the free list"),
    "kvpool.pages_pinned": (
        GAUGE, "pages held by in-flight KV-transfer pins (claims outside "
               "stream tables and the prefix tree)"),
    "kvpool.pages_shared": (
        GAUGE, "physical pages referenced more than once (streams and/or "
               "the prefix tree)"),
    "kvpool.prefix_nodes": (
        GAUGE, "prefix-tree nodes (cached shared-prefix pages)"),
    # -- generator (local single-stream decode) --------------------------
    "generator.decode_ms": (HISTOGRAM, "per-token decode latency"),
    "generator.prefill_ms": (HISTOGRAM, "prompt prefill latency"),
    # -- master (distributed decode walk) --------------------------------
    "master.failovers": (COUNTER, "recoveries that landed on a replica"),
    "master.recoveries": (COUNTER, "successful mid-stream reconnect+replay"),
    "master.tokens_generated": (COUNTER, "tokens emitted by the master"),
    # -- recovery/backoff plane ------------------------------------------
    "recover.backoff_ms": (COUNTER, "total backoff sleep during recovery"),
    # -- request-scoped tracing (cake_tpu/obs/reqtrace) ------------------
    "reqtrace.header_errors": (
        COUNTER, "malformed inbound traceparent headers (fell back to a "
                 "fresh mint)"),
    "reqtrace.requests": (
        COUNTER, "distinct trace ids landed in the per-process request "
                 "log"),
    "reqtrace.stitched": (
        COUNTER, "remote tier timelines merged into the local tracer"),
    # -- SLO accounting (per-class TTFT/TPOT targets) --------------------
    "slo.bad": (COUNTER, "requests that missed their TTFT/TPOT targets"),
    "slo.burn_long": (
        GAUGE, "long-window (600 s) error-budget burn rate (bad-fraction "
               "/ budget; >1 = burning faster than the objective allows)"),
    "slo.burn_short": (
        GAUGE, "short-window (60 s) error-budget burn rate"),
    "slo.good": (COUNTER, "requests that met their TTFT/TPOT targets"),
    # -- serving plane (HTTP API + scheduler) ----------------------------
    "serve.admit_chunk_ms": (HISTOGRAM, "admission prefill chunk dispatch"),
    "serve.cancelled": (COUNTER, "requests cancelled (client went away)"),
    "serve.completed": (COUNTER, "requests that got their tokens"),
    "serve.decode_dispatch_ms": (HISTOGRAM, "batched decode dispatch"),
    "serve.migrated_sessions": (
        COUNTER, "live sessions re-homed to a sibling replica by a "
                 "drain-migration (rolling restart)"),
    "serve.preemptions": (
        COUNTER, "batch streams spilled to host RAM so a higher-class "
                 "arrival could take the slot (SLO scheduling)"),
    "serve.queue_depth": (GAUGE, "requests waiting for admission"),
    "serve.rejected": (COUNTER, "submissions refused at the queue bound"),
    "serve.resume_ms": (
        HISTOGRAM, "preempted-stream resume time (spill take through "
                   "replay + attach queued)"),
    "serve.spill_bytes": (
        GAUGE, "host-RAM bytes held by spilled stream snapshots"),
    "serve.spill_pages": (
        GAUGE, "KV pages represented by spilled stream snapshots"),
    "serve.stop_matches": (COUNTER, "streams ended by a stop-string match"),
    "serve.tenant_throttled": (
        COUNTER, "admissions where an over-budget tenant's arrival was "
                 "queued behind in-budget traffic of its class"),
    "serve.timeouts": (COUNTER, "requests expired (queued or mid-stream)"),
    "serve.tokens_emitted": (COUNTER, "tokens emitted by the batch engine"),
    "serve.tpot_ms": (HISTOGRAM, "inter-token gap per serving request"),
    "serve.ttft_ms": (HISTOGRAM, "submit-to-first-token per request"),
    # -- wire transport ---------------------------------------------------
    "wire.bytes_in": (COUNTER, "frame payload bytes received"),
    "wire.bytes_out": (COUNTER, "frame payload bytes sent"),
    "wire.codec_bytes_encoded": (COUNTER, "activation bytes after codec"),
    "wire.codec_bytes_raw": (COUNTER, "activation bytes before codec"),
    "wire.crc_failures": (COUNTER, "frames dropped on CRC mismatch"),
    "wire.deserialize_ms": (HISTOGRAM, "reply tensor decode time"),
    "wire.frame_bytes": (HISTOGRAM, "payload size distribution"),
    "wire.frames_in": (COUNTER, "frames received"),
    "wire.frames_out": (COUNTER, "frames sent"),
    "wire.serialize_ms": (HISTOGRAM, "request tensor encode time"),
    "wire.timeouts": (COUNTER, "recv/send deadlines expired"),
    # -- worker (remote segment server) ----------------------------------
    "worker.bytes_in": (COUNTER, "op payload bytes received"),
    "worker.bytes_out": (COUNTER, "op payload bytes sent"),
    "worker.forward_ms": (HISTOGRAM, "steady-state decode forward time"),
    "worker.ops": (COUNTER, "ops handled"),
    "worker.prefill_ms": (HISTOGRAM, "prefill/replay forward time"),
    "worker.warmup_ms": (GAUGE, "per-shape XLA compile warmup"),
    # -- cluster aggregation (master-side merged view) -------------------
    "cluster.forward_p99_median_ms": (GAUGE, "median of worker p99s"),
    "cluster.stragglers": (GAUGE, "workers currently flagged"),
    "cluster.workers_up": (GAUGE, "workers answering scrapes"),
}

# Dynamic families: ``*`` stands for exactly one interpolated field. The
# static checker requires an f-string series name to reduce to one of
# these patterns verbatim; fnmatch covers literal names that happen to
# land inside a family.
DYNAMIC: dict[str, tuple[str, str]] = {
    "gateway.*.errors": (
        COUNTER, "per-backend proxy failures (connect / 5xx / stream)"),
    "gateway.*.requests": (COUNTER, "per-backend routed requests"),
    "gateway.*.retries": (
        COUNTER, "per-backend requests re-routed away after a failure"),
    "gateway.*.state": (
        GAUGE, "per-backend health state (2 UP / 1 DRAINING / 0 DOWN)"),
    "master.segment*.decode_ms": (
        HISTOGRAM, "per-segment steady-state forward time"),
    "master.segment*.warmup_ms": (
        GAUGE, "per-segment first-call compile+prefill"),
    "cluster.*.*": (
        GAUGE, "per-worker merged health/traffic fields (ClusterScraper)"),
    "prof.phase_ms.*": (
        HISTOGRAM, "per-phase wall ms inside sampled engine steps "
                   "(admit/pages/guide/dispatch/sync/emit/idle_park and "
                   "the spec_* phases — obs/prof.PHASES)"),
    "serve.ttft_ms.*": (
        HISTOGRAM, "per-class submit-to-first-token (serve.session "
                   "CLASSES — the SLO rows split interactive from "
                   "batch)"),
    "serve.tpot_ms.*": (
        HISTOGRAM, "per-class inter-token gap"),
}


def is_declared(name: str) -> bool:
    """True if ``name`` — a concrete series name OR a ``*`` pattern
    derived from an f-string — is covered by the catalog."""
    if name in SERIES or name in DYNAMIC:
        return True
    return any(fnmatchcase(name, pat) for pat in DYNAMIC)


def kind_of(name: str) -> str | None:
    """Declared kind for a concrete name (None if undeclared)."""
    if name in SERIES:
        return SERIES[name][0]
    for pat, (kind, _) in DYNAMIC.items():
        if fnmatchcase(name, pat):
            return kind
    return None


def all_names() -> list[str]:
    """Every declared name and pattern (sorted) — the docs/table view."""
    return sorted(SERIES) + sorted(DYNAMIC)
