"""Per-connection clock alignment for cross-process trace stitching.

Worker span timestamps ride back to the master in ``time.perf_counter()``
seconds — a per-process monotonic clock with an arbitrary epoch, so they
mean nothing on the master's timeline until the offset between the two
clocks is known. A ping exchange estimates it NTP-style: the master stamps
``t0``, the worker echoes with its own clock reading ``tw``, the master
stamps ``t1`` on receipt. Assuming symmetric network delay,

    offset = tw - (t0 + t1) / 2        rtt = t1 - t0

and the error of a single sample is bounded by half its RTT asymmetry.
:class:`ClockSync` keeps the last N samples and answers from the
minimum-RTT one (the Cristian/NTP trick: the tightest round trip is the
least-delayed, hence least-skewed, observation). The master runs the
exchange at handshake and refreshes periodically; the estimate rebases
worker span timestamps onto the master timebase for the merged trace and
feeds the ``cluster.*`` RTT/offset gauges.
"""

from __future__ import annotations

import threading
from collections import deque


class ClockSync:
    """Offset/RTT estimator over a bounded window of ping samples.

    All times are seconds. ``t0``/``t1`` are master ``perf_counter``
    readings around the exchange; ``tw`` is the worker's ``perf_counter``
    reading in between. Thread-safe: the scraper reads while the runner's
    forward loop refreshes.
    """

    def __init__(self, max_samples: int = 64):
        self._lock = threading.Lock()
        self._samples: deque = deque(maxlen=max_samples)

    def add(self, t0: float, tw: float, t1: float) -> None:
        if t1 < t0:
            raise ValueError(f"non-causal ping sample: t1 {t1} < t0 {t0}")
        with self._lock:
            self._samples.append((t1 - t0, tw - (t0 + t1) / 2.0))

    def _best(self) -> tuple | None:
        """Min-RTT sample of the current WINDOW (caller holds the lock).
        Computed over the bounded deque, not an all-time minimum: the
        periodic refresh must keep correcting the estimate as the two
        crystals drift apart (tens of ppm adds up over a long run) —
        a frozen historical best would never move again."""
        return min(self._samples, default=None)

    @property
    def synced(self) -> bool:
        with self._lock:
            return bool(self._samples)

    @property
    def rtt_s(self) -> float:
        """RTT of the best (minimum-RTT) windowed sample; 0.0 before any."""
        with self._lock:
            best = self._best()
        return best[0] if best else 0.0

    @property
    def offset_s(self) -> float:
        """Estimated (worker clock - master clock); 0.0 before any sample."""
        with self._lock:
            best = self._best()
        return best[1] if best else 0.0

    def to_master(self, tw: float) -> float:
        """Rebase a worker ``perf_counter`` reading onto the master's."""
        return tw - self.offset_s

    def snapshot(self) -> dict:
        with self._lock:
            best = self._best()
            n = len(self._samples)
        return {
            "samples": n,
            "rtt_ms": round(best[0] * 1e3, 4) if best else None,
            "offset_ms": round(best[1] * 1e3, 4) if best else None,
        }
