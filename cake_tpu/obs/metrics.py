"""Metrics registry: thread-safe counters, gauges, fixed-bucket histograms.

The reference's only metric is a tokens/sec print (master.rs:36-65); this is
the unified replacement for the hand-rolled counter patches that grew around
it here (master's ``_runner_time`` arrays, the worker's ad-hoc ``_total_*``
fields). One process-global :class:`Registry` holds every instrument; hot
paths hold direct instrument references so a recorded sample costs one lock
acquire + a few float ops. The registry dumps as JSON (``--metrics-out``) and
Prometheus-style text (the worker status page serves the JSON snapshot).

Instruments are get-or-create by name, so independent modules (wire, worker,
master) share series without import-order coupling. Instrument and registry
locks are reentrant: the SIGTERM/SIGINT artifact flush
(``obs.install_flush_handlers``) runs its dump on whatever thread the
signal lands on — possibly one interrupted mid-``observe`` with the same
lock held — and must not deadlock the dying process. A disabled registry
(``registry().enabled = False``, or env ``CAKE_OBS_METRICS=0`` at import)
hands out shared null instruments whose methods are no-ops — near-zero
overhead for code that cached the handle before a sample ever lands.

Histograms use fixed upper-bound buckets (Prometheus semantics): percentiles
are estimated by linear interpolation inside the bucket where the rank
falls, clamped to the observed min/max, so p50/p99 are meaningful without
storing raw samples.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import threading

# Default buckets for millisecond latencies: ~exponential from 50 µs to 10 s.
LATENCY_MS_BUCKETS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)
# Frame/payload sizes in bytes: 64 B .. 256 MiB.
BYTES_BUCKETS = tuple(float(64 * 4 ** i) for i in range(12))


class Counter:
    """Monotonic counter. ``inc`` is thread-safe."""

    __slots__ = ("name", "_lock", "_value")
    _GUARDED_BY = {"_value": "_lock"}

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.RLock()
        self._value = 0

    def inc(self, n: int | float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "counter", "value": self._value}


class Gauge:
    """Last-value gauge."""

    __slots__ = ("name", "_lock", "_value")
    _GUARDED_BY = {"_value": "_lock"}

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.RLock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0

    def snapshot(self) -> dict:
        with self._lock:
            return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket histogram (Prometheus ``le`` semantics, +inf implicit).

    Tracks count/sum/min/max alongside the bucket counts; ``percentile``
    interpolates inside the bucket where the rank falls, clamped to the
    observed range (a one-sample histogram reports that sample exactly).
    """

    __slots__ = ("name", "_lock", "buckets", "_counts", "count", "sum",
                 "min", "max")
    # count/sum/min/max are tolerated-atomic reads (mean, tests); the
    # bucket array is the torn-read hazard and stays lock-only.
    _GUARDED_BY = {"_counts": "_lock"}

    def __init__(self, name: str = "", buckets=LATENCY_MS_BUCKETS):
        self.name = name
        self._lock = threading.RLock()
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +inf
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) from the bucket counts."""
        with self._lock:
            counts = list(self._counts)
            total, mn, mx = self.count, self.min, self.max
        return self._percentile(q, counts, total, mn, mx)

    def _percentile(self, q, counts, total, mn, mx) -> float:
        """Pure quantile estimate over a captured state (no lock — lets
        snapshot() compute every statistic from ONE consistent capture)."""
        if not total:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            lo = self.buckets[i - 1] if i else max(0.0, mn)
            hi = self.buckets[i] if i < len(self.buckets) else mx
            if cum + c >= rank:
                frac = (rank - cum) / c
                est = lo + frac * (hi - lo)
                return min(max(est, mn), mx)
            cum += c
        return mx

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self.count = 0
            self.sum = 0.0
            self.min = math.inf
            self.max = -math.inf

    def snapshot(self) -> dict:
        # one locked capture; every derived statistic (mean, percentiles,
        # min/max) is computed from it, so a snapshot taken mid-traffic is
        # internally consistent
        with self._lock:
            counts = list(self._counts)
            count, total, mn, mx = self.count, self.sum, self.min, self.max
        snap = {
            "type": "histogram",
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6) if count else 0.0,
            "buckets": {
                ("+inf" if i == len(self.buckets) else repr(self.buckets[i])):
                c for i, c in enumerate(counts) if c
            },
        }
        if count:
            snap["min"] = round(mn, 6)
            snap["max"] = round(mx, 6)
            snap["p50"] = round(
                self._percentile(0.5, counts, count, mn, mx), 6)
            snap["p99"] = round(
                self._percentile(0.99, counts, count, mn, mx), 6)
        return snap


class _Null:
    """Shared no-op instrument handed out by a disabled registry."""

    name = ""
    buckets = LATENCY_MS_BUCKETS
    count = 0
    sum = 0.0
    mean = 0.0
    min = math.inf
    max = -math.inf
    value = 0

    def inc(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def percentile(self, q):
        return 0.0

    def reset(self):
        pass

    def snapshot(self):
        return {"type": "null"}


_NULL = _Null()


class Registry:
    """Thread-safe name -> instrument map."""

    _GUARDED_BY = {"_instruments": "_lock"}

    def __init__(self, enabled: bool | None = None,
                 strict: bool | None = None):
        self._lock = threading.RLock()
        self._instruments: dict[str, object] = {}
        if enabled is None:
            enabled = os.environ.get("CAKE_OBS_METRICS", "1") != "0"
        self.enabled = enabled
        # strict mode: refuse to create a series the catalog
        # (cake_tpu/obs/catalog.py) does not declare — the runtime twin
        # of the CK-METRIC lint check, for test rigs that want the
        # can't-fork-a-series invariant enforced hot.
        if strict is None:
            strict = os.environ.get("CAKE_OBS_STRICT", "0") == "1"
        self.strict = strict

    def _check_declared(self, name: str) -> None:
        from cake_tpu.obs import catalog  # lazy: catalog is pure data

        if not catalog.is_declared(name):
            raise ValueError(
                f"metric series '{name}' is not declared in "
                "cake_tpu/obs/catalog.py (strict registry); declare it "
                "or fix the typo"
            )

    def _get_or_create(self, name: str, cls, *args):
        if not self.enabled:
            return _NULL
        if self.strict:
            self._check_declared(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, *args)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str, buckets=LATENCY_MS_BUCKETS) -> Histogram:
        return self._get_or_create(name, Histogram, buckets)

    def register(self, name: str, instrument, replace: bool = False) -> None:
        """Publish an externally owned instrument under ``name``. With
        ``replace``, the name is rebound (how per-instance histograms — a
        new DistributedGenerator's segment timings — take over a stable
        series name from a closed predecessor). A disabled registry drops
        the registration, keeping its exports consistently empty (the owner
        still holds the live instrument for its own reporting)."""
        if not self.enabled:
            return
        if self.strict:
            self._check_declared(name)
        with self._lock:
            if not replace and name in self._instruments:
                raise ValueError(f"metric '{name}' already registered")
            self._instruments[name] = instrument

    def publish(self, *instruments) -> None:
        """Bind owner-held instruments under their own names, replacing any
        predecessor — the per-instance-series pattern: a component
        constructs its instruments (so its own reporting is never polluted
        by a prior instance's samples) and publishes them under stable
        names, latest instance winning in the dumps."""
        for inst in instruments:
            self.register(inst.name, inst, replace=True)

    def unregister(self, name: str, instrument=None) -> None:
        """Remove ``name`` from the registry. With ``instrument``, remove
        only if the name still binds that exact object — a closed owner
        must not tear down a successor that already replaced the series."""
        with self._lock:
            if instrument is None or self._instruments.get(name) is instrument:
                self._instruments.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._instruments)

    def snapshot(self, prefix: str = "") -> dict:
        """All instruments (optionally name-filtered) as plain JSON data."""
        with self._lock:
            items = sorted(self._instruments.items())
        return {n: i.snapshot() for n, i in items if n.startswith(prefix)}

    def to_json(self, prefix: str = "") -> str:
        return json.dumps(self.snapshot(prefix), indent=1, sort_keys=True)

    def dump_json(self, path: str, prefix: str = "") -> None:
        with open(path, "w") as f:
            f.write(self.to_json(prefix) + "\n")

    def to_prometheus(self, namespace: str = "cake") -> str:
        """Prometheus text exposition (counters/gauges as-is, histograms as
        ``_bucket``/``_sum``/``_count`` series)."""

        def clean(name: str) -> str:
            return "".join(
                c if c.isalnum() or c == "_" else "_" for c in name
            )

        lines: list[str] = []
        for name, inst in sorted(self.snapshot().items()):
            m = f"{namespace}_{clean(name)}"
            kind = inst.get("type")
            if kind in ("counter", "gauge"):
                lines.append(f"# TYPE {m} {kind}")
                lines.append(f"{m} {inst['value']}")
            elif kind == "histogram":
                lines.append(f"# TYPE {m} histogram")
                cum = 0
                for le, c in inst.get("buckets", {}).items():
                    cum += c
                    le = "+Inf" if le == "+inf" else le
                    lines.append(f'{m}_bucket{{le="{le}"}} {cum}')
                if "+inf" not in inst.get("buckets", {}):
                    lines.append(f'{m}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{m}_sum {inst['sum']}")
                lines.append(f"{m}_count {inst['count']}")
        return "\n".join(lines) + "\n"

    def reset(self, prefix: str = "") -> None:
        with self._lock:
            items = list(self._instruments.items())
        for n, i in items:
            if n.startswith(prefix):
                i.reset()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, buckets=LATENCY_MS_BUCKETS) -> Histogram:
    return _REGISTRY.histogram(name, buckets)
