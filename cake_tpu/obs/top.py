"""Live cluster terminal view (``--top``): plain ANSI refresh, no curses.

Renders the :class:`~cake_tpu.obs.cluster.ClusterScraper` report as a
compact fixed-width table — one row per worker with up/straggler state,
segment forward p50/p99, RTT, clock offset, and op/byte counters — and
repaints it in place with cursor-up escapes. Runs as a daemon thread next
to a master generation (the panel goes to stderr so the token stream on
stdout stays clean and pipeable), or one-shot via :func:`render` for
tests and snapshots.
"""

from __future__ import annotations

import sys
import threading
import time

_HDR = (f"{'WORKER':<14} {'ST':<4} {'LAYERS':<10} {'repl':<5} {'p50ms':>8} "
        f"{'p99ms':>8} {'rtt':>7} {'offset':>8} {'ops':>8} {'MB in':>8} "
        f"{'MB out':>8}")


def _fmt(v, nd=2, scale=1.0) -> str:
    if v is None:
        return "-"
    return f"{v / scale:.{nd}f}"


def _runs(layer_runs) -> str:
    if not layer_runs:
        return "-"
    return ",".join(f"{lo}-{hi - 1}" for lo, hi in layer_runs)


def render(report: dict) -> str:
    """Report dict -> multi-line panel (no trailing newline)."""
    lines = [
        f"cake-tpu cluster — {len(report.get('workers', {}))} worker(s), "
        f"median fwd p99 {_fmt(report.get('median_forward_p99_ms'))} ms, "
        f"straggler factor {report.get('straggler_factor')}",
        _HDR,
    ]
    for name, w in sorted(report.get("workers", {}).items()):
        if not w.get("up"):
            lines.append(f"{name:<14} DOWN")
            continue
        state = "SLOW" if w.get("straggler") else "ok"
        lines.append(
            f"{name:<14} {state:<4} {_runs(w.get('layer_runs')):<10} "
            # which address of the segment's failover set is live ("2/3");
            # single-address segments show "-"
            f"{w.get('replica') or '-':<5} "
            f"{_fmt(w.get('forward_p50_ms')):>8} "
            f"{_fmt(w.get('forward_p99_ms')):>8} "
            f"{_fmt(w.get('rtt_ms')):>7} "
            f"{_fmt(w.get('clock_offset_ms')):>8} "
            f"{w.get('ops_total') if w.get('ops_total') is not None else '-':>8} "
            f"{_fmt(w.get('bytes_in'), 1, 1e6):>8} "
            f"{_fmt(w.get('bytes_out'), 1, 1e6):>8}"
        )
    if report.get("stragglers"):
        lines.append("stragglers: " + ", ".join(report["stragglers"]))
    # a report carrying serving-plane data (loopback fleets, co-located
    # replicas) gets the replica panel appended under the cluster table
    if report.get("replica"):
        lines.append("")
        lines.append(render_replica(report["replica"], report.get("prof")))
    return "\n".join(lines)


_PHASE_HDR = (f"{'PHASE':<14} {'count':>8} {'mean ms':>9} {'p50 ms':>9} "
              f"{'p99 ms':>9}")


def render_replica(status: dict, prof: dict | None = None) -> str:
    """One serve replica's panel: queue depth / SLO burn header, kvpool
    page line, and the engine phase breakdown (``/debug/prof`` body) —
    the step-loop time budget at a glance."""
    slo = status.get("slo") or {}
    lines = [
        "serve — "
        f"queued {status.get('queued', 0)}"
        f"/{status.get('queue_depth', '-')} "
        f"running {status.get('running', 0)} "
        f"tok/s {status.get('observed_tok_s') or '-'} "
        f"slo burn {_fmt(slo.get('burn_short'))}"
        f"/{_fmt(slo.get('burn_long'))}"
    ]
    prof = prof or {}
    kv = (prof.get("memory") or {}).get("kvpool") or {}
    if kv:
        lines.append(
            f"kvpool — free {kv.get('pages_free', '-')} "
            f"shared {kv.get('pages_shared', '-')} "
            f"pinned {kv.get('pages_pinned', '-')}")
    phases = prof.get("phases") or {}
    if phases:
        lines.append(_PHASE_HDR)
        for name, h in phases.items():
            lines.append(
                f"{name:<14} {h.get('count', 0):>8} "
                f"{_fmt(h.get('mean')):>9} {_fmt(h.get('p50')):>9} "
                f"{_fmt(h.get('p99')):>9}")
    if prof.get("retraces"):
        lines.append(f"RETRACES: {prof['retraces']} "
                     f"(compiles {prof.get('compiles')}) — steady-state "
                     "decode recompiled; see /debug/prof findings")
    return "\n".join(lines)


class Top:
    """Background refresher: scrape -> render -> repaint every interval."""

    def __init__(self, scraper, out=None, interval_s: float = 1.0):
        self.scraper = scraper
        self.out = out if out is not None else sys.stderr
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_lines = 0

    def _paint(self) -> None:
        frame = render(self.scraper.scrape())
        if self._last_lines:
            # cursor up over the previous frame, clear to end of screen —
            # the whole "UI"; survives any ANSI terminal, needs no curses
            self.out.write(f"\x1b[{self._last_lines}F\x1b[J")
        self.out.write(frame + "\n")
        self.out.flush()
        self._last_lines = frame.count("\n") + 1

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._paint()
            except Exception:  # an obs view must never kill the run
                pass
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, final_paint: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_paint:
            try:
                self._paint()
            except Exception:
                pass
