"""Static-shape KV cache for autoregressive decode.

TPU-native redesign of the reference cache (`cake-core/src/model/cache.rs`).
The reference appends K/V per token with `Tensor::cat` along the sequence axis
(cache.rs:106-135) — a realloc-per-step pattern that would force an XLA retrace
on every decode step. Here the cache is a preallocated
``[num_layers, batch, num_kv_heads, max_seq, head_dim]`` pytree updated in
place with ``lax.dynamic_update_slice`` and donated across steps, so every
decode step compiles once and reuses the same HBM buffers.

The reference's other two cache jobs are relocated where XLA wants them:
RoPE tables (cache.rs:31-50) live in :mod:`cake_tpu.ops.rope`; causal masks
(cache.rs:81-103) are folded into attention via iota comparison (no
memoization needed — the mask is fused by XLA, or folded into the Pallas
flash kernel).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from cake_tpu.models.config import LlamaConfig


@partial(jax.tree_util.register_dataclass, data_fields=["q", "scale"],
         meta_fields=[])
@dataclasses.dataclass
class QuantizedKV:
    """Int8 KV buffer half: ``q [..., KH, S, D] int8`` + per-token-per-head
    f32 ``scale [..., KH, S]`` (symmetric absmax over the head_dim channel,
    written alongside each token's KV slot). Halves cache HBM — the lever
    that lets multi-stream serving and long windows coexist on 16 GiB chips
    (the reference's f16 cache has no quantized tier, cache.rs:106-135)."""

    q: jax.Array
    scale: jax.Array


def _kv_data(x) -> jax.Array:
    return x.q if isinstance(x, QuantizedKV) else x


def dequant_kv(x, dtype) -> jax.Array:
    """Materialize (trace-level — XLA fuses the convert+mul into the
    attention dot's operand read) a full-precision view of a KV buffer."""
    if isinstance(x, QuantizedKV):
        return (x.q.astype(jnp.float32) * x.scale[..., None]).astype(dtype)
    return x


def quant_kv(x: jax.Array) -> QuantizedKV:
    """Per-token-per-head symmetric int8 over the head_dim channel."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return QuantizedKV(q=q, scale=scale)


@partial(jax.tree_util.register_dataclass, data_fields=["k", "v"], meta_fields=[])
@dataclasses.dataclass
class KVCache:
    """Preallocated per-layer key/value buffers.

    Shapes: ``k, v: [num_layers, batch, num_kv_heads, max_seq, head_dim]``.
    The leading layer axis makes the cache scannable alongside stacked layer
    weights, and shardable along a pipeline-stage mesh axis.

    ``k``/``v`` may each be a plain array or a :class:`QuantizedKV` (int8
    storage + per-slot scales); every consumer goes through
    :func:`dequant_kv` / :func:`update_layer`, which handle both.
    """

    k: jax.Array | QuantizedKV
    v: jax.Array | QuantizedKV

    @property
    def num_layers(self) -> int:
        return _kv_data(self.k).shape[0]

    @property
    def batch(self) -> int:
        return _kv_data(self.k).shape[1]

    @property
    def max_seq(self) -> int:
        return _kv_data(self.k).shape[3]

    def as_new(self) -> "KVCache":
        """Fresh zeroed cache with identical shapes.

        Mirrors the reference's per-connection isolation clone
        (`cache.rs:138-146`): same geometry, reset contents.
        """
        return jax.tree.map(jnp.zeros_like, self)


def init_cache(
    config: LlamaConfig,
    batch: int = 1,
    max_seq: int | None = None,
    dtype=None,
    num_layers: int | None = None,
    quant: str | None = None,
) -> KVCache:
    """Allocate a zeroed cache. ``num_layers`` overrides the config count so a
    pipeline stage / worker can hold buffers for only its own layers
    (the reference worker keeps a cache indexed by *global* block_idx,
    cache.rs:17,58 — here each stage's cache is dense over its local layers).

    ``quant="int8"`` allocates int8 storage + per-slot f32 scales
    (:class:`QuantizedKV`): ~half the cache HBM, quantize-on-write."""
    if quant not in (None, "int8"):
        raise ValueError(f"unsupported kv quant={quant!r}")
    L = config.num_hidden_layers if num_layers is None else num_layers
    S = max_seq or config.max_seq_len
    dt = dtype or config.jax_dtype
    shape = (L, batch, config.num_key_value_heads, S, config.head_dim)
    if quant == "int8":
        def half():
            return QuantizedKV(q=jnp.zeros(shape, jnp.int8),
                               scale=jnp.zeros(shape[:-1], jnp.float32))

        return KVCache(k=half(), v=half())
    return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))


def update_layer(
    k_cache: jax.Array,
    v_cache: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
    pos: jax.Array,
    gate: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Write ``k_new/v_new [batch, kv_heads, T, head_dim]`` into one layer's
    buffers ``[batch, kv_heads, max_seq, head_dim]`` at sequence offset ``pos``.

    Replaces the reference's `process_kv` concat (cache.rs:106-135) — including
    *not* reproducing its axis-confused trimming bug (length checks on the
    heads axis, narrow on head_dim; see SURVEY.md §2).

    ``gate`` (scalar bool): predicated write for SPMD-uniform pipelines — when
    false the current slot contents are rewritten unchanged, so every device
    executes the identical program (collectives stay uniform) and only the
    active pipeline stage commits. Gated off, the touched region is just the
    ``T`` slots, not the whole buffer.

    ``pos`` may be a scalar (all batch rows write at the same offset — the
    single-stream paths) or ``[batch]`` (each row at its own offset — the
    multi-stream serving path, where right-padded prompts of different
    lengths decode concurrently).
    """
    t = k_new.shape[2]
    pos = jnp.asarray(pos, jnp.int32)

    def write_buf(cache, new, has_d):
        """``has_d``: buffer carries a trailing head_dim axis (the int8
        ``q``/plain arrays); scales are the same layout minus that axis."""
        if pos.ndim == 0:
            if gate is not None:
                cur = jax.lax.dynamic_slice_in_dim(cache, pos, t, axis=2)
                new = jnp.where(gate, new, cur)
            zero = jnp.zeros((), jnp.int32)
            idx = (zero, zero, pos, zero) if has_d else (zero, zero, pos)
            return jax.lax.dynamic_update_slice(cache, new, idx)

        def one(c, n, p):  # c [KH, S(, D)], n [KH, T(, D)]
            if gate is not None:
                cur = jax.lax.dynamic_slice_in_dim(c, p, t, axis=1)
                n = jnp.where(gate, n, cur)
            zero = jnp.zeros((), jnp.int32)
            idx = (zero, p, zero) if has_d else (zero, p)
            return jax.lax.dynamic_update_slice(c, n, idx)

        return jax.vmap(one)(cache, new, pos)

    def write(cache, new):
        if isinstance(cache, QuantizedKV):
            qn = quant_kv(new)  # quantize-on-write
            return QuantizedKV(
                q=write_buf(cache.q, qn.q, True),
                scale=write_buf(cache.scale, qn.scale, False),
            )
        return write_buf(cache, new.astype(cache.dtype), True)

    return write(k_cache, k_new), write(v_cache, v_new)
