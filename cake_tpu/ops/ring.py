"""Ring attention + sequence-parallel decode (context parallelism).

The reference has **no** long-context story: a hard ``MAX_SEQ_LEN = 4096``
cap baked into its RoPE tables and masks (`config.rs:6`, `cache.rs:40-43`),
and no sequence/context parallelism of any kind (SURVEY.md §5). This module
is the TPU-native capability the reference lacks, built the way the hardware
wants it:

- **Prefill — ring attention.** The sequence is sharded over an ``sp`` mesh
  axis; each device holds one query block and one KV block. KV blocks rotate
  around the ring with ``lax.ppermute`` (compiler-scheduled ICI DMA between
  neighbors) while each device folds the visiting block into a blockwise
  online softmax (running max / sum / accumulator, all f32). Attention over
  a sequence of length S costs each chip O(S/n · S) FLOPs and only
  neighbor-to-neighbor transfers — no all-gather of KV, no O(S²) score
  materialization.
- **Decode — distributed flash decoding.** The KV cache's sequence axis is
  sharded over ``sp``; the single query token is replicated. Each device
  attends over its local KV slice producing *partial* softmax stats
  ``(o, m, l)``; the exact global softmax is reconstructed with one
  ``pmax`` + two ``psum`` over the axis. Per step this moves only
  ``[B, H, D]``-sized partials — independent of sequence length.

Both paths share :func:`attend_stats`, whose masked-softmax numerics match
:func:`cake_tpu.ops.attention._attend_xla` (f32 scores regardless of model
dtype — the reference's attention.rs:62-77 convention) so sharded output is
bit-comparable to the single-device oracle up to reduction order.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attend_stats(
    q: jax.Array,  # [B, H, T, D]
    k: jax.Array,  # [B, KH, S, D]
    v: jax.Array,  # [B, KH, S, D]
    q_off,  # scalar: global position of q[..., 0, :]
    k_off,  # scalar: global position of k[..., 0, :]
    window: int | None = None,  # sliding-window width (Mistral); None=full
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Partial causal GQA attention over one KV block.

    Returns unnormalized ``(o [B,H,T,D] f32, m [B,H,T] f32, l [B,H,T] f32)``
    — the blockwise online-softmax triple: row max, row sum of
    ``exp(score - m)``, and the exp-weighted value accumulator. Partials from
    different KV blocks combine exactly via :func:`merge_stats` /
    :func:`combine_axis`.

    Causality: key position ``k_off + s`` attends iff ``<= q_off + t``. With
    ``window`` the lower bound ``> q_off + t - window`` is ANDed in (the
    sliding-window mask of :func:`cake_tpu.ops.attention._attend_xla`,
    applied blockwise — a block wholly outside some row's window simply
    yields ``m = NEG_INF, l = 0`` for that row and drops out of the merge).
    Rows with no valid key yield ``m = NEG_INF, l = 0, o = 0`` and drop out
    of any merge. ``q_off`` may be scalar or ``[B]`` (per-batch-row causal
    frontiers — the multi-stream sp serving path).
    """
    b, n_heads, t, d = q.shape
    kv_heads, s = k.shape[1], k.shape[2]
    group = n_heads // kv_heads

    qg = q.reshape(b, kv_heads, group, t, d)
    scores = jnp.einsum(
        "bkgtd,bksd->bkgts", qg, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(d))

    kpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1) + jnp.asarray(k_off, jnp.int32)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    q_off = jnp.asarray(q_off, jnp.int32)
    if q_off.ndim == 0:
        mask = kpos <= qpos + q_off  # [T, S]
        if window is not None:
            mask &= kpos > qpos + q_off - window
        mask = mask[None, None, None]  # [1,1,1,T,S]
    else:
        mask = kpos[None] <= qpos[None] + q_off[:, None, None]  # [B,T,S]
        if window is not None:
            mask &= kpos[None] > qpos[None] + q_off[:, None, None] - window
        mask = mask[:, None, None]  # [B,1,1,T,S]
    scores = jnp.where(mask, scores, NEG_INF)

    m = jnp.max(scores, axis=-1)  # [B, KH, G, T]
    # Guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1, so re-mask.
    p = jnp.where(mask, jnp.exp(scores - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum(
        "bkgts,bksd->bkgtd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return (
        o.reshape(b, n_heads, t, d),
        m.reshape(b, n_heads, t),
        l.reshape(b, n_heads, t),
    )


def merge_stats(o1, m1, l1, o2, m2, l2):
    """Fold two online-softmax partials into one (associative)."""
    m = jnp.maximum(m1, m2)
    s1 = jnp.exp(m1 - m)
    s2 = jnp.exp(m2 - m)
    return (
        o1 * s1[..., None] + o2 * s2[..., None],
        m,
        l1 * s1 + l2 * s2,
    )


def finalize_stats(o, m, l, dtype) -> jax.Array:
    """Normalize the accumulator into attention output ``[B, H, T, D]``."""
    del m
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(dtype)


def combine_axis(o, m, l, axis_name: str):
    """Exactly reduce partial stats held across a mesh axis.

    One ``pmax`` (global row max) + two ``psum`` (rescaled accumulator and
    denominator). Fully-masked shards carry ``m = NEG_INF`` and contribute 0.
    """
    m_g = jax.lax.pmax(m, axis_name)
    scale = jnp.exp(m - m_g)
    o_g = jax.lax.psum(o * scale[..., None], axis_name)
    l_g = jax.lax.psum(l * scale, axis_name)
    return o_g, m_g, l_g


def ring_attention(
    q: jax.Array,  # [B, H, T_l, D] local query block (already roped)
    k: jax.Array,  # [B, KH, T_l, D] local key block
    v: jax.Array,  # [B, KH, T_l, D] local value block
    axis_name: str,
    axis_size: int,
    q_off,  # scalar: global position of this shard's q[..., 0, :]
    chunk_starts: jax.Array | None = None,  # [axis_size] global start per shard
    window: int | None = None,  # sliding-window width (Mistral); None=full
) -> jax.Array:
    """Causal ring attention inside ``shard_map`` over ``axis_name``.

    Each of the ``axis_size`` devices holds contiguous blocks of Q and KV.
    KV (with its block origin) rotates around the ring ``axis_size`` times via
    ``ppermute``; each visit folds into the online softmax. Returns
    ``[B, H, T_l, D]`` in ``q.dtype``.

    ``chunk_starts[i]`` is the global position of shard *i*'s ``k[..., 0, :]``;
    defaults to the uniform layout ``i * T_l``.

    ``window``: sliding-window attention. The mask's lower bound folds into
    every blockwise visit, and a visiting block that is WHOLLY outside this
    shard's window — every key at or below ``q_off - window`` — skips the
    score/merge math entirely (``lax.cond`` around pure compute; the
    ppermute rotation stays SPMD-uniform). Long-window Mistral over sp
    therefore pays window-proportional FLOPs, not prompt-proportional —
    the sp twin of the windowed flash kernel's bounded block sweep.
    """
    b, n_heads, t, d = q.shape
    if axis_size == 1:
        o, m, l = attend_stats(
            q, k, v, q_off, 0 if chunk_starts is None else chunk_starts[0],
            window=window,
        )
        return finalize_stats(o, m, l, q.dtype)

    my = jax.lax.axis_index(axis_name)
    if chunk_starts is None:
        chunk_starts = jnp.arange(axis_size, dtype=jnp.int32) * k.shape[2]
    # Send our KV block to the next rank each step; after `step` rotations we
    # hold the block that originated at rank (my - step) mod n.
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    o = jnp.zeros((b, n_heads, t, d), jnp.float32)
    m = jnp.full((b, n_heads, t), NEG_INF, jnp.float32)
    l = jnp.zeros((b, n_heads, t), jnp.float32)

    def body(step, carry):
        k, v, o, m, l = carry
        src = (my - step) % axis_size
        k_start = chunk_starts[src]

        def visit(args):
            k, v, o, m, l = args
            o_p, m_p, l_p = attend_stats(q, k, v, q_off, k_start,
                                         window=window)
            return merge_stats(o, m, l, o_p, m_p, l_p)

        if window is None:
            o, m, l = visit((k, v, o, m, l))
        else:
            # Block visibility for this shard's queries (rows q_off ..
            # q_off+t-1): any key in [k_start, k_start + s) inside
            # (q_off - window, q_off + t - 1]?  Causality's upper bound and
            # the window's lower bound, evaluated blockwise.
            s = k.shape[2]
            visible = (k_start <= jnp.asarray(q_off) + t - 1) & (
                k_start + s - 1 > jnp.asarray(q_off) - window
            )
            o, m, l = jax.lax.cond(
                visible, visit, lambda args: args[2:], (k, v, o, m, l)
            )
        # Rotate the KV block to the neighbor (the final rotation restores
        # the original layout, so the cache leaves this function unmoved).
        k, v = jax.lax.ppermute((k, v), axis_name, perm)
        return k, v, o, m, l

    k, v, o, m, l = jax.lax.fori_loop(0, axis_size, body, (k, v, o, m, l))
    return finalize_stats(o, m, l, q.dtype)


def sp_decode_attend(
    q: jax.Array,  # [B, H, T, D] (replicated across sp, already roped)
    k_local: jax.Array,  # [B, KH, S_l, D] this shard's KV slice
    v_local: jax.Array,
    pos,  # scalar or [B]: global position(s) of the query token(s)
    axis_name: str,
    shard_start,  # scalar: global position of k_local[..., 0, :]
    window: int | None = None,  # sliding-window width (Mistral); None=full
) -> jax.Array:
    """Distributed flash decoding over a sequence-sharded KV cache.

    Each shard computes partial stats over its slice (keys beyond the causal
    frontier ``pos`` masked — scalar, or ``[B]`` for multi-stream serving
    with per-row frontiers; a sliding ``window``'s lower bound masks the
    same way, so an out-of-window shard contributes ``m = NEG_INF, l = 0``
    and drops out), then the exact softmax is reassembled with one pmax +
    two psum. Traffic per step is O(B·H·T·D), independent of S.

    ``T > 1`` is the chunked-admission mode (sp serving): the chunk's T
    queries run replicated on every shard, each row's causal frontier is
    ``pos + t`` — the same math :func:`attend_stats` already does blockwise.
    """
    o, m, l = attend_stats(q, k_local, v_local, pos, shard_start,
                           window=window)
    o, m, l = combine_axis(o, m, l, axis_name)
    return finalize_stats(o, m, l, q.dtype)


def _leaf_pairs(cache, new):
    """Pair a cache half with its (pre-quantized-if-needed) new values,
    leaf by leaf: a plain array yields one ``(cache, new)`` pair; a
    :class:`cake_tpu.ops.kvcache.QuantizedKV` yields ``(q, q)`` and
    ``(scale, scale)`` pairs plus a rebuild function. The sequence axis is
    axis 2 in every leaf layout (``[B, KH, S, D]`` and ``[B, KH, S]``), so
    one write routine serves both."""
    from cake_tpu.ops import kvcache as kvc

    if isinstance(cache, kvc.QuantizedKV):
        qn = kvc.quant_kv(new)
        return ([(cache.q, qn.q), (cache.scale, qn.scale)],
                lambda leaves: kvc.QuantizedKV(q=leaves[0], scale=leaves[1]))
    return [(cache, new)], lambda leaves: leaves[0]


def sp_chunked_cache_write(
    k_cache,  # [B, KH, S_l, D] local slice of the range-sharded cache
    v_cache,
    k_new: jax.Array,  # [B, KH, T_l, D] this shard's prefill chunk (roped)
    v_new: jax.Array,
    axis_name: str,
    axis_size: int,
    gate: jax.Array | None = None,
):
    """Write chunk-sharded prefill KV into the range-sharded cache layout.

    Chunked sp prefill shards the *prompt* (shard ``i`` computes KV for
    global positions ``[i*T_l, (i+1)*T_l)``, ``T_pad = T_l * sp`` ≪ max_seq),
    but the decode cache layout owns *ranges* of the full window (shard ``i``
    holds ``[i*S_l, (i+1)*S_l)``). The two only coincide when the prompt is
    padded to the full window (``T_l == S_l`` — the round-1 contract). Here
    the roped KV is all-gathered over sp — prompt-proportional traffic, NOT
    window-proportional — and each shard slices the window it owns; positions
    past the prompt stay zero and are overwritten slot-by-slot by decode
    before they ever become attendable (same invariant as the local bucketed
    prefill path).

    ``k_cache``/``v_cache`` may be plain buffers or int8 ``QuantizedKV``
    halves (quantize-on-write; the int8 bytes + tiny scales ride the
    all-gather, not the bf16 chunk). ``gate``: pipeline-stage activity
    predicate; inactive stages keep their cache unchanged.
    """
    from cake_tpu.ops.kvcache import _kv_data

    s_l = _kv_data(k_cache).shape[2]
    shard_start = jax.lax.axis_index(axis_name) * s_l

    def write_leaf(cache, new):
        allkv = jax.lax.all_gather(new, axis_name, axis=2, tiled=True)
        # Pad the gathered tensor along the sequence axis so the window
        # slice below is always in-bounds: dynamic_slice clamps start to
        # [0, T_pad], and a shard whose range begins past the prompt reads
        # only zeros.
        pad = [(0, 0)] * allkv.ndim
        pad[2] = (0, s_l)
        padded = jnp.pad(allkv, pad)
        win = jax.lax.dynamic_slice_in_dim(
            padded, shard_start, s_l, axis=2
        ).astype(cache.dtype)
        if gate is not None:
            win = jnp.where(gate, win, cache)
        return win

    def write(cache, new):
        pairs, rebuild = _leaf_pairs(cache, new)
        return rebuild([write_leaf(c, n) for c, n in pairs])

    return write(k_cache, k_new), write(v_cache, v_new)


def sp_range_cache_write(
    k_cache,  # [B, KH, S_l, D] local slice of the range-sharded cache
    v_cache,
    k_new: jax.Array,  # [B, KH, C, D] chunk KV, computed REPLICATED per shard
    v_new: jax.Array,
    pos0,  # scalar: global position of the chunk's first token
    shard_start,  # scalar: global position of this shard's slot 0
    gate: jax.Array | None = None,
):
    """Owner-masked RANGE write into a sequence-sharded cache.

    The chunked-admission twin of :func:`sp_cache_write`: a C-token chunk
    occupies global positions ``[pos0, pos0 + C)`` which may span shard
    boundaries, and every shard already holds the full chunk KV (the
    admission row's activations are replicated over sp), so there is no
    gather — each shard selects the in-range slots of its own window slice
    via a positional gather + select, exactly the per-slot pattern
    :func:`sp_chunked_cache_write` uses after its all-gather. Quantized
    halves quantize-on-write per slot like every other sp write path.

    ``pos0`` may be scalar (one staged row — admission / shared-prefix
    remainders) or ``[B]`` (per-row chunk frontiers — the sp serving
    SPECULATION plane: each row's K+1 verification slots start at its own
    position, possibly on different shards).
    """
    from cake_tpu.ops.kvcache import _kv_data

    s_l = _kv_data(k_cache).shape[2]
    c = k_new.shape[2]
    gpos = (jnp.asarray(shard_start, jnp.int32)
            + jnp.arange(s_l, dtype=jnp.int32))
    pos0 = jnp.asarray(pos0, jnp.int32)

    if pos0.ndim == 0:
        idx = gpos - pos0  # in-chunk index per local slot
        valid = (idx >= 0) & (idx < c)
        if gate is not None:
            valid = valid & gate

        def write_leaf(cache, new):
            # gather the chunk value owned by each local slot (clamped for
            # out-of-range slots, which the select below discards)
            vals = jnp.take(new, jnp.clip(idx, 0, c - 1), axis=2)
            sel = valid.reshape((1, 1, s_l) + (1,) * (cache.ndim - 3))
            return jnp.where(sel, vals.astype(cache.dtype), cache)
    else:
        idx = gpos[None, :] - pos0[:, None]  # [B, S_l]
        valid = (idx >= 0) & (idx < c)
        if gate is not None:
            valid = valid & gate

        def write_leaf(cache, new):
            def one(c_, n_, idx_r, ok_r):  # c_ [KH, S_l(, D)], n_ [KH, C(, D)]
                vals = jnp.take(n_, jnp.clip(idx_r, 0, c - 1), axis=1)
                sel = ok_r.reshape((1, s_l) + (1,) * (c_.ndim - 2))
                return jnp.where(sel, vals, c_)

            return jax.vmap(one)(cache, new.astype(cache.dtype), idx, valid)

    def write(cache, new):
        pairs, rebuild = _leaf_pairs(cache, new)
        return rebuild([write_leaf(c_, n) for c_, n in pairs])

    return write(k_cache, k_new), write(v_cache, v_new)


def sp_cache_write(
    k_cache,  # [B, KH, S_l, D] local slice (plain or QuantizedKV)
    v_cache,
    k_new: jax.Array,  # [B, KH, 1, D]
    v_new: jax.Array,
    pos,  # scalar or [B]: global write position(s)
    shard_start,  # scalar global position of this shard's slot 0
    gate: jax.Array | None = None,
):
    """Owner-masked single-slot write into a sequence-sharded cache.

    Every shard executes the same program (SPMD); only the shard whose range
    contains ``pos`` commits the new KV — the rest rewrite their current slot
    value, which XLA lowers to an in-place dynamic-update on donated buffers.
    ``pos`` may be scalar (single-stream) or ``[B]`` (multi-stream serving:
    each row writes at its own frontier, possibly on different shards).
    ``gate``: additional scalar predicate (pipeline-stage activity) ANDed in.
    Quantized halves write their int8 bytes and per-slot scale the same way.
    """
    from cake_tpu.ops.kvcache import _kv_data

    s_l = _kv_data(k_cache).shape[2]
    pos = jnp.asarray(pos, jnp.int32)
    local = pos - jnp.asarray(shard_start, jnp.int32)
    owner = (local >= 0) & (local < s_l)
    if gate is not None:
        owner = owner & gate
    off = jnp.clip(local, 0, s_l - 1)

    if pos.ndim == 0:
        def write_leaf(cache, new):
            cur = jax.lax.dynamic_slice_in_dim(cache, off, 1, axis=2)
            val = jnp.where(owner, new.astype(cache.dtype), cur)
            return jax.lax.dynamic_update_slice_in_dim(cache, val, off,
                                                       axis=2)
    else:
        def write_leaf(cache, new):
            def one(c, n, ok, o):  # c [KH, S_l(, D)], n [KH, 1(, D)]
                cur = jax.lax.dynamic_slice_in_dim(c, o, 1, axis=1)
                val = jnp.where(ok, n.astype(c.dtype), cur)
                return jax.lax.dynamic_update_slice_in_dim(c, val, o, axis=1)

            return jax.vmap(one)(cache, new.astype(cache.dtype), owner, off)

    def write(cache, new):
        pairs, rebuild = _leaf_pairs(cache, new)
        return rebuild([write_leaf(c, n) for c, n in pairs])

    return write(k_cache, k_new), write(v_cache, v_new)
