"""Mixture-of-Experts SwiGLU with expert parallelism.

The reference has no MoE at all (SURVEY.md §2 "expert parallelism (no MoE)"
under *Not present*) — this is a capability extension that completes the
mesh's parallelism alphabet (dp / stage / sp / tp / **ep**) and serves the
Mixtral model family (HF ``model_type: "mixtral"``: 8 experts, top-2
routing, softmax over the selected gate logits).

TPU-first design:

- **Static shapes only.** Routing never gathers a data-dependent *number* of
  tokens. Two fixed-shape strategies, picked at trace time:

  * ``dense`` — every (local) expert runs over every token via batched
    einsums (``[E, N, F]`` activations) and the per-token combine weights
    zero out the non-selected experts. FLOPs are E/top_k× the routed
    minimum, but every op is a large MXU matmul with no dynamic shapes; at
    prefill the block is compute-bound and XLA keeps the expert axis as a
    clean batch dimension.
  * ``gather`` — decode-shaped inputs (tiny N): gather the top-k experts'
    weight rows with ``jnp.take`` (static output shape ``[N, k, H, F]``)
    and run only those. At N=1/k=2 this reads 2 experts' bytes instead of
    E — the decode path is weights-bandwidth-bound, so the gather is the
    difference between top-k and all-E HBM traffic per token.

- **Expert parallelism** shards the expert axis over the mesh's ``ep`` axis
  (:mod:`cake_tpu.parallel.mesh`): each rank holds ``E/ep`` experts' weights,
  computes the dense path restricted to its local experts (tokens are
  replicated over ep — at inference scale activations are tiny next to
  expert weights), and the combine is a single ``psum`` over ``ep``. This
  composes with tensor parallelism: the expert intermediate axis shards over
  ``tp`` exactly like the dense MLP, and the down-projection partial sums
  reduce over ``(ep, tp)`` in one fused psum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cake_tpu.ops.quant import QuantizedLinear, dequantize_linear

# Decode/prefill strategy crossover: gather materializes [N*k, H, F] weight
# rows, so it only pays off while N*k is well under E (single-digit serving
# batches at decode). Above it the dense path's E-batched einsum wins.
GATHER_MAX_ROWS = 8


def _deq(w, dt):
    """Trace-level dequant of an int8 expert stack ``[E, in, out]``
    (scale ``[E, out]``): XLA fuses the convert+mul into the downstream
    einsum's operand read, so HBM streams the int8 bytes — the same
    contract as the int8 KV cache's XLA path (ops/attention.py)."""
    if isinstance(w, QuantizedLinear):
        return dequantize_linear(w, dt)
    return w


def _take(w, flat):
    """Expert-row gather that works for plain and int8 stacks (gathering
    q and scale separately keeps the gathered bytes int8-sized)."""
    if isinstance(w, QuantizedLinear):
        return QuantizedLinear(q=jnp.take(w.q, flat, axis=0),
                               scale=jnp.take(w.scale, flat, axis=0))
    return jnp.take(w, flat, axis=0)


def router_topk(
    x2d: jax.Array,  # [N, H]
    router_w: jax.Array,  # [H, E] (global expert count)
    top_k: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing (Mixtral convention): softmax over the *selected*
    logits, in f32. Returns ``(combine [N, E] f32, weights [N, k] f32,
    idx [N, k] int32)`` where ``combine`` is zero off the top-k."""
    logits = jnp.einsum(
        "nh,he->ne", x2d, router_w, preferred_element_type=jnp.float32
    )
    vals, idx = jax.lax.top_k(logits, top_k)  # [N, k]
    w = jax.nn.softmax(vals, axis=-1)
    onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=w.dtype)  # [N,k,E]
    combine = jnp.einsum("nk,nke->ne", w, onehot)
    return combine, w, idx


def _moe_dense(
    x2d: jax.Array,  # [N, H]
    combine: jax.Array,  # [N, E_local] f32 combine weights (zeros off top-k)
    w_gate,  # [E_local, H, F] array or int8 QuantizedLinear
    w_up,
    w_down,  # [E_local, F, H]
) -> jax.Array:
    dt = x2d.dtype
    g = jnp.einsum("nh,ehf->enf", x2d, _deq(w_gate, dt))
    u = jnp.einsum("nh,ehf->enf", x2d, _deq(w_up, dt))
    y = jnp.einsum("enf,efh->enh", jax.nn.silu(g) * u, _deq(w_down, dt))
    return jnp.einsum("ne,enh->nh", combine.astype(y.dtype), y)


def _moe_gather(
    x2d: jax.Array,  # [N, H]
    w_topk: jax.Array,  # [N, k] f32
    idx: jax.Array,  # [N, k] int32 (global expert ids)
    w_gate,  # [E, H, F] array or int8 QuantizedLinear
    w_up,
    w_down,  # [E, F, H]
) -> jax.Array:
    n, k = idx.shape
    dt = x2d.dtype
    flat = idx.reshape(-1)
    gg = _deq(_take(w_gate, flat), dt)  # [N*k, H, F]
    gu = _deq(_take(w_up, flat), dt)
    gd = _deq(_take(w_down, flat), dt)  # [N*k, F, H]
    xr = jnp.repeat(x2d, k, axis=0)  # [N*k, H]
    g = jnp.einsum("nh,nhf->nf", xr, gg)
    u = jnp.einsum("nh,nhf->nf", xr, gu)
    y = jnp.einsum("nf,nfh->nh", jax.nn.silu(g) * u, gd)  # [N*k, H]
    y = y.reshape(n, k, -1)
    return jnp.einsum("nk,nkh->nh", w_topk.astype(y.dtype), y)


def moe_swiglu(
    x: jax.Array,  # [B, T, H]
    router_w: jax.Array,  # [H, E_global]
    w_gate: jax.Array,  # [E_local, H, F]
    w_up: jax.Array,
    w_down: jax.Array,  # [E_local, F, H]
    top_k: int,
    ep_axis: str | None = None,
    ep_size: int | None = None,
    tp_axis: str | None = None,
) -> jax.Array:
    """Routed SwiGLU MLP. Returns ``[B, T, H]`` (residual NOT added).

    The router always scores the **global** expert set; under ep the weight
    arrays hold this rank's contiguous expert slice (global experts
    ``[ep_idx*E_local, (ep_idx+1)*E_local)``) and the combine is psum'd over
    ``ep_axis`` (plus ``tp_axis`` for the row-parallel down projection — one
    fused reduction when both are given). ``ep_size`` defaults to the mesh
    axis size (callers inside shard_map just pass the axis name; a size-1
    ep axis degrades to the unsharded strategies).
    """
    b, t, h = x.shape
    x2d = x.reshape(b * t, h)
    combine, w_topk, idx = router_topk(x2d, router_w, top_k)

    e_local = (w_gate.q if isinstance(w_gate, QuantizedLinear)
               else w_gate).shape[0]
    if ep_axis is not None and ep_size is None:
        # Static ep width from the shapes already in hand: the router
        # scores the GLOBAL expert set ([H, E_global]) while the weight
        # arrays hold this rank's local slice ([E_local, ...]), so the
        # shard count is their ratio. Shape-derived rather than
        # jax.lax.axis_size so it works on jax versions without that API
        # (and it must be a Python int — it gates the strategy below).
        ep_size = combine.shape[1] // e_local
    axes: tuple[str, ...] = ()
    if ep_axis is not None and ep_size > 1:
        lo = jax.lax.axis_index(ep_axis) * e_local
        combine_local = jax.lax.dynamic_slice_in_dim(combine, lo, e_local, 1)
        out = _moe_dense(x2d, combine_local, w_gate, w_up, w_down)
        axes += (ep_axis,)
    elif x2d.shape[0] * top_k <= GATHER_MAX_ROWS:
        out = _moe_gather(x2d, w_topk, idx, w_gate, w_up, w_down)
    else:
        out = _moe_dense(x2d, combine, w_gate, w_up, w_down)
    if tp_axis is not None:
        axes += (tp_axis,)
    if axes:
        out = jax.lax.psum(out, axes)
    return out.reshape(b, t, h)
