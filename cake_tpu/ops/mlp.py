"""SwiGLU feed-forward.

Equivalent of `cake-core/src/model/mlp.rs`: ``down(silu(gate(x)) * up(x))``
(mlp.rs:15-18) with no-bias linears gate/up/down sized hidden↔intermediate
(mlp.rs:21-32). Left as plain jnp — XLA fuses the silu and multiply into the
matmul epilogues on TPU, so a hand-written kernel buys nothing here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    return (jax.nn.silu(x @ w_gate) * (x @ w_up)) @ w_down
