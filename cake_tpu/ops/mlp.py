"""SwiGLU feed-forward.

Equivalent of `cake-core/src/model/mlp.rs`: ``down(silu(gate(x)) * up(x))``
(mlp.rs:15-18) with no-bias linears gate/up/down sized hidden↔intermediate
(mlp.rs:21-32). Left as plain jnp — XLA fuses the silu and multiply into the
matmul epilogues on TPU, so a hand-written kernel buys nothing here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cake_tpu.ops.quant import dense


def _gelu_tanh(x: jax.Array) -> jax.Array:
    """torch's ``gelu(approximate='tanh')`` — the GeGLU gate (Gemma)."""
    return jax.nn.gelu(x, approximate=True)


_ACTS = {"silu": jax.nn.silu, "gelu_tanh": _gelu_tanh}


def swiglu(
    x: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    tp_axis: str | None = None,
    act: str = "silu",
) -> jax.Array:
    """``tp_axis``: inside shard_map with the intermediate dim sharded over a
    tensor-parallel axis (column-parallel gate/up, row-parallel down), the
    down-proj partial sums are psum-reduced over that axis. ``act`` selects
    the gate activation (``config.hidden_act``): silu = SwiGLU (every
    Llama-family model), gelu_tanh = GeGLU (Gemma)."""
    out = dense(_ACTS[act](dense(x, w_gate)) * dense(x, w_up), w_down)
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out
