"""Fused elementwise Pallas kernels: RMSNorm.

The reference's RMSNorm comes from candle's fused CUDA/Metal kernel
(`transformer.rs:30-38`); this is the Pallas equivalent — one pass over each
row block in VMEM, f32 statistics, output cast back to the activation dtype.
XLA fuses the pure-JAX version well already; the kernel exists so the whole
decoder block can run kernel-resident on TPU and as the template for further
fusions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)  # [BR, hidden]
    var = jnp.mean(x * x, axis=1, keepdims=True)
    normed = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (normed * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def rms_norm_pallas(
    x: jax.Array,  # [..., hidden]
    weight: jax.Array,  # [hidden]
    eps: float,
    *,
    block_rows: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused ``x * rsqrt(mean(x^2) + eps) * weight`` over the last axis."""
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()
    orig_shape = x.shape
    hidden = orig_shape[-1]
    rows = x.size // hidden
    x2 = x.reshape(rows, hidden)
    w2 = weight.reshape(1, hidden)

    br = 1
    while br * 2 <= min(rows, block_rows) and rows % (br * 2) == 0:
        br *= 2

    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, hidden), x.dtype),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((br, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((br, hidden), lambda i: (i, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(x2, w2)
    return out.reshape(orig_shape)
