"""Pallas int8-weight matmul: ``y = (x @ q_int8) * scale`` fused.

The int8 weights stream HBM→VMEM at half the bf16 bytes (the decode
bottleneck), are converted to the activation dtype in VMEM, hit the MXU with
f32 accumulation, and the per-output-channel dequant scale is applied in the
epilogue — the dequantized weights never exist in HBM (the XLA fallback in
:func:`cake_tpu.ops.quant.quant_matmul_xla` relies on convert-into-dot
fusion instead).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_block(n: int, preferred: int) -> int:
    b = 1
    while b * 2 <= min(n, preferred) and n % (b * 2) == 0:
        b *= 2
    return b


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, num_k_blocks: int):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    x = x_ref[:]  # [BM, BK] activation dtype
    w = q_ref[:].astype(x.dtype)  # [BK, BN] int8 -> activation dtype in VMEM
    acc_ref[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(kb == num_k_blocks - 1)
    def _finish():
        o_ref[:] = (acc_ref[:] * s_ref[:]).astype(o_ref.dtype)


def quant_matmul_pallas(
    x: jax.Array,  # [M, K]
    q: jax.Array,  # [K, N] int8
    scale: jax.Array,  # [N] f32
    *,
    block_m: int = 256,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused int8-weight matmul with per-channel dequant epilogue."""
    m, k = x.shape
    n = q.shape[1]
    bm = _pick_block(m, block_m)
    bn = _pick_block(n, block_n)
    bk = _pick_block(k, block_k)
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()

    out = pl.pallas_call(
        functools.partial(_kernel, num_k_blocks=k // bk),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((bk, bn), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((1, bn), lambda i, j, kb: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kb: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize + k * n + m * n * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, q, scale.reshape(1, n).astype(jnp.float32))
    return out
