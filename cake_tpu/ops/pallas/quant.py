"""Pallas int8-weight matmul: ``y = (x @ q_int8) * scale`` fused.

The int8 weights stream HBM→VMEM at half the bf16 bytes (the decode
bottleneck), are converted to the activation dtype in VMEM, hit the MXU with
f32 accumulation, and the per-output-channel dequant scale is applied in the
epilogue — the dequantized weights never exist in HBM (the XLA fallback in
:func:`cake_tpu.ops.quant.quant_matmul_xla` relies on convert-into-dot
fusion instead).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the params class was renamed TPUCompilerParams -> CompilerParams;
# resolve once so the kernels build on either side of the rename
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _pick_block(n: int, preferred: int) -> int:
    b = 1
    while b * 2 <= min(n, preferred) and n % (b * 2) == 0:
        b *= 2
    return b


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, num_k_blocks: int):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    x = x_ref[:]  # [BM, BK] activation dtype
    w = q_ref[:].astype(x.dtype)  # [BK, BN] int8 -> activation dtype in VMEM
    acc_ref[:] += jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(kb == num_k_blocks - 1)
    def _finish():
        o_ref[:] = (acc_ref[:] * s_ref[:]).astype(o_ref.dtype)


def quant_matmul_pallas(
    x: jax.Array,  # [M, K]
    q: jax.Array,  # [K, N] int8
    scale: jax.Array,  # [N] f32
    *,
    block_m: int = 256,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused int8-weight matmul with per-channel dequant epilogue."""
    m, k = x.shape
    n = q.shape[1]
    bm = _pick_block(m, block_m)
    bn = _pick_block(n, block_n)
    bk = _pick_block(k, block_k)
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()

    out = pl.pallas_call(
        functools.partial(_kernel, num_k_blocks=k // bk),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=(m // bm, n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((bk, bn), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((1, bn), lambda i, j, kb: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kb: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize + k * n + m * n * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x, q, scale.reshape(1, n).astype(jnp.float32))
    return out


def _kernel4(
    xlo_ref, xhi_ref, qp_ref, s_ref, o_ref, acc_ref, *,
    num_k_blocks: int, grouped: bool, blocks_per_group: int,
    unpack: str = "int32",
):
    """Packed-int4 matmul kernel. ``grouped`` is a Python static: per-channel
    applies the scale once in the epilogue; grouped multiplies each K
    block's f32 partial by its group's scale before accumulating (every K
    block lies inside one group — bk2 divides group_size/2) — same math as
    the grouped XLA einsum path up to f32 summation order.

    Grouped ``s_ref`` holds the FULL ``[ngroups, BN]`` scale column: a
    per-K-block scale BlockSpec would need a (1, BN) block over the group
    axis, which Mosaic rejects whenever ngroups isn't the whole axis (the
    sublane-divisibility rule — caught on real v5e, r4). The kernel
    dynamically indexes its group's row instead; scales are tiny, so
    re-fetching the column per N block costs nothing."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    x_lo = xlo_ref[:]  # [BM, BK2] activation dtype (even K rows)
    x_hi = xhi_ref[:]  # [BM, BK2] (odd K rows)
    # Unpack both nibbles of the SAME packed block (adjacent-pair layout,
    # ops/quant.py:pack_int4). The shift width is a tunable (`unpack`):
    # int32 is the VPU's native lane width; int16 halves the unpacked
    # temporary's VMEM footprint at skinny M where the [BK2, BN] weight
    # temporaries dominate VMEM — tools/int4_sweep.py measures which wins
    # per shape. The int8 bytes are what streamed from HBM either way.
    if unpack == "int16":
        p = qp_ref[:].astype(jnp.int16)  # [BK2, BN]
        w_lo = ((p << 12) >> 12).astype(x_lo.dtype)
    else:
        p = qp_ref[:].astype(jnp.int32)  # [BK2, BN]
        w_lo = ((p << 28) >> 28).astype(x_lo.dtype)
    w_hi = (p >> 4).astype(x_lo.dtype)
    partial = jax.lax.dot_general(
        x_lo, w_lo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        x_hi, w_hi, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if grouped:
        s_row = s_ref[pl.ds(kb // blocks_per_group, 1), :]  # [1, BN]
        acc_ref[:] += partial * s_row
    else:
        acc_ref[:] += partial

    @pl.when(kb == num_k_blocks - 1)
    def _finish():
        if grouped:
            o_ref[:] = acc_ref[:].astype(o_ref.dtype)
        else:
            o_ref[:] = (acc_ref[:] * s_ref[:]).astype(o_ref.dtype)


def _sublane(dtype) -> int:
    """Minimum second-to-last tile dim for ``dtype`` on TPU."""
    return {2: 16, 4: 8}.get(jnp.dtype(dtype).itemsize, 32)


def quant4_matmul_pallas(
    x: jax.Array,  # [M, K]
    qp: jax.Array,  # [K/2, N] int8 packed (two int4 per byte)
    scale: jax.Array,  # [N] f32 per-channel, or [ngroups, N] grouped
    *,
    block_m: int = 256,
    block_n: int = 512,
    block_k: int = 512,
    unpack: str = "int32",
    skinny_widen: bool = True,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused packed-int4 matmul: quarter the bf16 weight bytes from HBM.

    ``skinny_widen=False`` disables the skinny-M block widening so an
    explicit ``block_n``/``block_k`` is honored verbatim (modulo divisor
    clamping) — tools/int4_sweep.py uses it to measure the sub-1024
    configs the default policy would silently override.

    ``y = (x[:, 0::2] @ lo(qp) + x[:, 1::2] @ hi(qp)) * scale`` with the
    even/odd activation slices materialized OUTSIDE the kernel (M x K/2
    each, activation-sized), so the K-axis grid walks packed weight rows
    directly and the weight side never strides or interleaves. A grouped
    ``scale [ngroups, N]`` caps the K block at half a group and applies
    each group's scale to its own f32 partial.

    Decode (skinny M): M below the dtype sublane is zero-padded up to it —
    a sub-sublane block would make Mosaic mask every weight tile, and the
    padded rows cost only activation-sized traffic. The weight stream (the
    bandwidth bound) is unchanged, so the kernel's win over the XLA
    fallback (which re-materializes bf16 weights every step, 4x the bytes)
    holds at M=1; blocks are widened in the skinny regime to amortize
    per-grid-step overhead over the ~0.5 byte/weight stream."""
    m, k = x.shape
    k2, n = qp.shape
    if k != 2 * k2:
        raise ValueError(f"x in-dim {k} != 2 * packed rows {k2}")
    if unpack not in ("int32", "int16"):
        raise ValueError(f"unpack must be 'int32' or 'int16', got {unpack!r}")
    grouped = scale.ndim == 2
    pad_m = 0
    sub = _sublane(x.dtype)
    if m < sub:
        pad_m = sub - m
        x = jnp.pad(x, ((0, pad_m), (0, 0)))
        m = sub
    if m <= 32 and skinny_widen:
        # skinny regime: fewer, larger grid steps (weights dominate VMEM
        # and HBM; the activation block is tiny either way)
        block_n = max(block_n, 1024)
        block_k = max(block_k, 1024)
    bm = _pick_block(m, block_m)
    bn = _pick_block(n, block_n)
    if grouped:
        g2 = k2 // scale.shape[0]  # packed rows per group
        bk2 = _pick_block(g2, block_k)
    else:
        g2 = k2
        bk2 = _pick_block(k2, block_k)
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()

    s_in = (
        scale.astype(jnp.float32)
        if grouped
        else scale.reshape(1, n).astype(jnp.float32)
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel4,
            num_k_blocks=k2 // bk2,
            grouped=grouped,
            blocks_per_group=g2 // bk2,
            unpack=unpack,
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=(m // bm, n // bn, k2 // bk2),
        in_specs=[
            pl.BlockSpec((bm, bk2), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((bm, bk2), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((bk2, bn), lambda i, j, kb: (kb, j)),
            # grouped: the whole group axis rides in the block (a (1, bn)
            # block over it fails Mosaic's sublane rule on real TPUs); the
            # kernel picks its row. Per-channel: scale is [1, n].
            pl.BlockSpec(
                (s_in.shape[0], bn), lambda i, j, kb: (0, j)
            ),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kb: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=m * k * x.dtype.itemsize
            + k2 * n
            + m * n * x.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(
        x[:, 0::2],
        x[:, 1::2],
        qp,
        s_in,
    )
    return out[: m - pad_m] if pad_m else out
