"""Blockwise (flash) causal GQA attention as Pallas TPU kernels.

Replaces the reference's materialized-scores attention for long sequences
(`cake-core/src/model/attention.rs:59-80`: repeat_kv + full [T, S] score
matrix + memoized masks, cache.rs:81-103). Here the causal mask is folded
into an online-softmax blockwise sweep over the KV buffer — scores never hit
HBM, the mask is an iota comparison computed in registers, and KV blocks
entirely beyond the causal frontier are never even DMA'd from HBM (their
block index is clamped so the pipeline re-uses the previous fetch, and the
compute is predicated off).

Two kernels share the math:

- :func:`flash_attention` — prefill: ``q [B, H, T, D]`` against the full
  ``[B, KVH, S, D]`` cache buffers, grid over (batch, head, q-block,
  kv-block) with f32 running max / sum / accumulator scratch.
- :func:`flash_decode` — decode (T == 1): the GQA head group is folded into
  the q-row axis (``[B, KVH, group, D]``) so the MXU sees a [group, D] x
  [D, BK] matmul per step; grid over (batch, kv-head, kv-block). Only KV
  blocks at or before the frontier ``pos`` are read.

Numerics match :func:`cake_tpu.ops.attention.attend`: f32 scores and
accumulation regardless of model dtype (attention.rs:62-77), probabilities
cast to the value dtype for the PV matmul.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the params class was renamed TPUCompilerParams -> CompilerParams;
# resolve once so the kernels build on either side of the rename
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30
_LANES = 128


def _pick_block(n: int, preferred: int) -> int:
    """Largest power-of-two block <= preferred that divides n."""
    b = 1
    while b * 2 <= min(n, preferred) and n % (b * 2) == 0:
        b *= 2
    return b


def _kv_block_bounds(pos, qb, block_q: int, block_k: int,
                     window: int | None):
    """(min_kb, max_kb) of the live KV-block range for q block ``qb`` at
    frontier ``pos`` — THE one definition of the causal upper bound and
    the sliding-window lower bound, shared by the kernels' live-range
    gates and the BlockSpec index maps so fetch clamp and compute mask
    can never desynchronize. ``qb``/``block_q`` of (0, 1) express the
    decode case (a single query row at ``pos``)."""
    max_kb = jax.lax.div(pos + (qb + 1) * block_q - 1, block_k)
    if window is None:
        return 0, max_kb
    lo = jnp.maximum(0, pos + qb * block_q - window + 1)
    return jax.lax.div(lo, block_k), max_kb


# ---------------------------------------------------------------------------
# Prefill kernel
# ---------------------------------------------------------------------------


def _prefill_kernel(
    pos_ref,  # scalar prefetch: [1] int32
    q_ref,  # [1, 1, BQ, D]
    k_ref,  # [1, 1, BK, D]
    v_ref,  # [1, 1, BK, D]
    o_ref,  # [1, 1, BQ, D]
    acc_ref,  # VMEM [BQ, D] f32
    m_ref,  # VMEM [BQ, LANES] f32  (running max, lanes replicated)
    l_ref,  # VMEM [BQ, LANES] f32  (running denom)
    *,
    block_q: int,
    block_k: int,
    scale: float,
    num_kv_blocks: int,
    window: int | None = None,
):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    pos = pos_ref[0]

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    # Sliding window (Mistral): blocks entirely below the q block's
    # lowest valid key position are skipped — the block sweep is
    # window-proportional, not history-proportional.
    min_kb, max_kb = _kv_block_bounds(pos, qb, block_q, block_k, window)
    live = (kb >= min_kb) & (kb <= max_kb)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # [BQ, BK] f32

        qpos = (
            pos
            + qb * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        )
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = kpos <= qpos
        if window is not None:
            mask &= kpos > qpos - window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]  # [BQ, LANES]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)  # [BQ, LANES]
        p = jnp.exp(s - m_new[:, :1])  # [BQ, BK] f32
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

    @pl.when(kb == num_kv_blocks - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,  # [B, H, T, D] (already roped)
    k_all: jax.Array,  # [B, KVH, S, D] full cache buffer
    v_all: jax.Array,
    pos,  # scalar int: absolute position of q[..., 0, :]
    *,
    block_q: int = 512,
    block_k: int | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal flash attention over a fixed KV buffer. Returns [B, H, T, D].

    Default blocks from a v5e sweep (8B geometry, D=128): bq=512
    throughout; bk=1024 once the KV buffer is long enough to amortize the
    bigger fetch (S >= 4096 — 1.5x faster there than bk=512), bk=512 below
    (where bk=1024 loses ~35%).

    ``window``: sliding-window attention (Mistral) — the lower mask bound
    is folded into the block sweep, so KV blocks entirely outside the
    window are neither fetched nor computed (the XLA fallback sweeps and
    masks the whole history instead).
    """
    b, h, t, d = q.shape
    kvh, s = k_all.shape[1], k_all.shape[2]
    group = h // kvh
    if block_k is None:
        block_k = 1024 if s >= 4096 else 512
    bq = _pick_block(t, block_q)
    bk = _pick_block(s, block_k)
    nq, nk = t // bq, s // bk
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()
    pos_arr = jnp.asarray(pos, jnp.int32).reshape(1)
    scale = 1.0 / math.sqrt(d)

    def q_map(bi, hi, qb, kb, pos_ref):
        return (bi, hi, qb, 0)

    def kv_map(bi, hi, qb, kb, pos_ref):
        # Clamp to the causal frontier (and, windowed, to the window's
        # lower bound): fully-masked blocks re-use a live block index, so
        # the pipeline skips their HBM fetch.
        min_kb, max_kb = _kv_block_bounds(pos_ref[0], qb, bq, bk, window)
        return (bi, hi // group, jnp.clip(kb, min_kb, max_kb), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), q_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, block_q=bq, block_k=bk, scale=scale,
        num_kv_blocks=nk, window=window,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * s * d,
            bytes_accessed=(q.size + 2 * k_all.size + q.size) * q.dtype.itemsize,
            transcendentals=b * h * t * s,
        ),
        interpret=interpret,
    )(pos_arr, q, k_all, v_all)


# ---------------------------------------------------------------------------
# Prefill kernel over an int8 KV cache (kvcache.QuantizedKV layout)
# ---------------------------------------------------------------------------


def _prefill_q8_kernel(
    pos_ref,  # scalar prefetch: [1] int32
    q_ref,  # [1, 1, BQ, D]
    kq_ref,  # [1, 1, BK, D] int8
    ks_ref,  # [1, KVH, BK] f32 (per-token-per-head scales, full head axis)
    vq_ref,  # [1, 1, BK, D] int8
    vs_ref,  # [1, KVH, BK] f32
    o_ref,  # [1, 1, BQ, D]
    acc_ref,  # VMEM [BQ, D] f32
    m_ref,  # VMEM [BQ, LANES] f32
    l_ref,  # VMEM [BQ, LANES] f32
    *,
    block_q: int,
    block_k: int,
    scale: float,
    num_kv_blocks: int,
    group: int,
    window: int | None = None,
):
    """Same online softmax as :func:`_prefill_kernel`, reading int8 KV. The
    per-token dequant scale is constant along D, so it factors OUT of both
    matmuls: ``q . (s_j * kq_j) = s_j * (q . kq_j)`` folds into the score
    column, and ``p @ diag(vs) @ vq = (p * vs) @ vq`` folds into the
    probabilities — the kernel never materializes dequantized KV, and HBM
    reads stay at the int8 bytes + one f32 scale per token.

    The scale blocks carry the FULL kv-head axis: a (1, 1, BK) block would
    put a size-1 block over that axis, which Mosaic's sublane rule rejects
    on real TPUs whenever KVH > 1 (caught on v5e, r4). The kernel selects
    its head's row dynamically — the stripe is a few KB."""
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    hk = pl.program_id(1) // group  # this grid cell's kv head
    pos = pos_ref[0]

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    min_kb, max_kb = _kv_block_bounds(pos, qb, block_q, block_k, window)
    live = (kb >= min_kb) & (kb <= max_kb)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]  # [BQ, D]
        kq = kq_ref[0, 0].astype(q.dtype)  # [BK, D] (VMEM convert)
        s = jax.lax.dot_general(
            q, kq, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ks_row = jax.lax.dynamic_slice_in_dim(ks_ref[0], hk, 1, 0)  # [1, BK]
        s = s * scale * ks_row  # fold key scales per column

        qpos = (
            pos
            + qb * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        )
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = kpos <= qpos
        if window is not None:
            mask &= kpos > qpos - window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])  # [BQ, BK] f32
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        vq = vq_ref[0, 0].astype(q.dtype)
        vs_row = jax.lax.dynamic_slice_in_dim(vs_ref[0], hk, 1, 0)  # [1, BK]
        pv = jax.lax.dot_general(
            (p * vs_row).astype(q.dtype), vq,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

    @pl.when(kb == num_kv_blocks - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def flash_attention_q8(
    q: jax.Array,  # [B, H, T, D] (already roped)
    k_q: jax.Array,  # [B, KVH, S, D] int8
    k_scale: jax.Array,  # [B, KVH, S] f32
    v_q: jax.Array,  # [B, KVH, S, D] int8
    v_scale: jax.Array,  # [B, KVH, S] f32
    pos,  # scalar int
    *,
    block_q: int = 512,
    block_k: int | None = None,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal flash attention over an int8 KV buffer (quantize-on-write
    layout of :class:`cake_tpu.ops.kvcache.QuantizedKV`). Returns
    ``[B, H, T, D]``. Keeps the long-context flash plane available to the
    int8 cache: the XLA fallback would materialize dequantized KV (or full
    scores) in HBM at exactly the window sizes the int8 cache exists for."""
    b, h, t, d = q.shape
    kvh, s = k_q.shape[1], k_q.shape[2]
    group = h // kvh
    if block_k is None:
        block_k = 1024 if s >= 4096 else 512
    bq = _pick_block(t, block_q)
    bk = _pick_block(s, block_k)
    nq, nk = t // bq, s // bk
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()
    pos_arr = jnp.asarray(pos, jnp.int32).reshape(1)
    scale = 1.0 / math.sqrt(d)

    def q_map(bi, hi, qb, kb, pos_ref):
        return (bi, hi, qb, 0)

    def _kb_idx(qb, kb, pos_ref):
        min_kb, max_kb = _kv_block_bounds(pos_ref[0], qb, bq, bk, window)
        return jnp.clip(kb, min_kb, max_kb)

    def kv_map(bi, hi, qb, kb, pos_ref):
        return (bi, hi // group, _kb_idx(qb, kb, pos_ref), 0)

    def scale_map(bi, hi, qb, kb, pos_ref):
        # full kv-head axis per block (see the kernel docstring); only
        # batch and the (clamped) S block vary
        return (bi, 0, _kb_idx(qb, kb, pos_ref))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), q_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
            pl.BlockSpec((1, kvh, bk), scale_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
            pl.BlockSpec((1, kvh, bk), scale_map),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_q8_kernel, block_q=bq, block_k=bk, scale=scale,
        num_kv_blocks=nk, group=group, window=window,
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * t * s * d,
            bytes_accessed=(
                2 * q.size * q.dtype.itemsize
                + 2 * k_q.size
                + 2 * k_scale.size * 4
            ),
            transcendentals=b * h * t * s,
        ),
        interpret=interpret,
    )(pos_arr, q, k_q, k_scale, v_q, v_scale)


# ---------------------------------------------------------------------------
# Decode kernel (T == 1)
# ---------------------------------------------------------------------------


def _decode_kernel(
    pos_ref,  # [B] int32 (per-row causal frontier; row b reads pos_ref[b])
    q_ref,  # [1, 1, G, D]
    k_ref,  # [1, 1, BK, D]
    v_ref,  # [1, 1, BK, D]
    o_ref,  # [1, 1, G, D]
    acc_ref,  # VMEM [G, D] f32
    m_ref,  # VMEM [G, LANES] f32
    l_ref,  # VMEM [G, LANES] f32
    *,
    group: int,
    block_k: int,
    scale: float,
    num_kv_blocks: int,
    window: int | None = None,
):
    kb = pl.program_id(2)
    pos = pos_ref[pl.program_id(0)]

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, -jnp.inf, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    # sliding window: this row attends keys in (pos-window, pos] only —
    # at long S the block sweep is window-proportional where the XLA
    # path sweeps and masks the whole buffer
    min_kb, max_kb = _kv_block_bounds(pos, 0, 1, block_k, window)
    live = (kb >= min_kb) & (kb <= max_kb)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]  # [G, D]
        k = k_ref[0, 0]  # [BK, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # [G, BK]
        kpos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (group, block_k), 1
        )
        mask = kpos <= pos
        if window is not None:
            mask &= kpos > pos - window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

    @pl.when(kb == num_kv_blocks - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def flash_decode(
    q: jax.Array,  # [B, H, 1, D] (already roped)
    k_all: jax.Array,  # [B, KVH, S, D]
    v_all: jax.Array,
    pos,  # scalar int
    *,
    block_k: int = 512,
    window: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Single-position flash attention. Returns [B, H, 1, D].

    The GQA group is folded into q rows so each (batch, kv-head) grid cell is
    one [group, D] x [D, BK] matmul; KV blocks past ``pos`` are neither read
    nor computed. ``pos`` may be scalar (shared frontier) or ``[B]``
    (per-row frontiers — multi-stream serving): it is broadcast to a [B]
    prefetch and each batch grid row clamps its own KV fetch window.

    ``window``: sliding-window attention — blocks below the window's lower
    bound are likewise neither fetched nor computed, so a W-window decode
    against a long buffer reads ~W of KV bytes instead of ~pos.
    """
    b, h, t, d = q.shape
    assert t == 1, "flash_decode requires T == 1"
    kvh, s = k_all.shape[1], k_all.shape[2]
    group = h // kvh
    bk = _pick_block(s, block_k)
    nk = s // bk
    if interpret is None:
        from cake_tpu.ops.pallas import interpret_default

        interpret = interpret_default()
    pos_arr = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    scale = 1.0 / math.sqrt(d)
    qg = q.reshape(b, kvh, group, d)

    def q_map(bi, khi, kb, pos_ref):
        return (bi, khi, 0, 0)

    def kv_map(bi, khi, kb, pos_ref):
        min_kb, max_kb = _kv_block_bounds(pos_ref[bi], 0, 1, bk, window)
        return (bi, khi, jnp.clip(kb, min_kb, max_kb), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, kvh, nk),
        in_specs=[
            pl.BlockSpec((1, 1, group, d), q_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
            pl.BlockSpec((1, 1, bk, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, group=group, block_k=bk, scale=scale,
        num_kv_blocks=nk, window=window,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * h * s * d,
            bytes_accessed=2 * k_all.size * k_all.dtype.itemsize,
            transcendentals=b * h * s,
        ),
        interpret=interpret,
    )(pos_arr, qg, k_all, v_all)
    return out.reshape(b, h, 1, d)
