"""Pallas TPU kernels (SURVEY.md §7 step 4).

The reference delegates all device kernels to candle's CUDA/Metal backends
(`cake-core/Cargo.toml:28-48`); the TPU-native equivalent is hand-written
Pallas (Mosaic) kernels for the hot ops, with the pure-JAX reference-math
implementations in :mod:`cake_tpu.ops` retained as the fallback / parity
oracle.

Dispatch policy (``CAKE_PALLAS`` env): ``auto`` (default — kernels on TPU,
XLA elsewhere), ``1`` (force kernels; interpreted off-TPU, used by tests),
``0`` (force XLA fallback everywhere).
"""

from __future__ import annotations

import os

import jax


def _mode() -> str:
    return os.environ.get("CAKE_PALLAS", "auto").lower()


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def kernels_enabled() -> bool:
    """Should hot ops route to Pallas kernels?"""
    mode = _mode()
    if mode in ("1", "true", "force"):
        return True
    if mode in ("0", "false", "off"):
        return False
    return on_tpu()


def force_kernels() -> bool:
    """CAKE_PALLAS=1: kernels unconditionally, overriding the measured
    crossover dispatch (ops.attention, ops.quant) that would otherwise pick
    XLA at shapes where it wins."""
    return _mode() in ("1", "true", "force")


def interpret_default() -> bool:
    """Pallas kernels run interpreted off-TPU (CPU tests), compiled on TPU."""
    return not on_tpu()


from cake_tpu.ops.pallas.flash import (  # noqa: E402
    flash_attention,
    flash_attention_q8,
    flash_decode,
)
from cake_tpu.ops.pallas.quant import (  # noqa: E402
    quant4_matmul_pallas,
    quant_matmul_pallas,
)

__all__ = [
    "kernels_enabled",
    "interpret_default",
    "on_tpu",
    "flash_attention",
    "flash_attention_q8",
    "flash_decode",
    "quant_matmul_pallas",
    "quant4_matmul_pallas",
]
