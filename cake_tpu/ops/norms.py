"""RMSNorm.

Equivalent of the reference's ``candle_nn::RmsNorm`` usage in the pre-norm
decoder block (`transformer.rs:30-38,48-64`). Computed in f32 regardless of
activation dtype (the candle kernel upcasts the same way), cast back on exit
so XLA keeps the surrounding matmuls in bf16 on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, eps: float,
             offset: bool = False) -> jax.Array:
    """``x * rsqrt(mean(x^2) + eps) * weight`` over the last axis.

    ``offset=True`` scales by ``(1 + weight)`` instead — the Gemma-family
    convention (its checkpoints store the scale centered at zero)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if offset:
        w = 1.0 + w
    return (normed * w).astype(x.dtype)
