"""GQA causal self-attention (reference math path).

Equivalent of `cake-core/src/model/attention.rs`: no-bias q/k/v/o projections
sized by head counts (attention.rs:92-109), RoPE from precomputed tables
(:17-27), KV append (:57), GQA key/value sharing (:59-60,84-89), **scores in
f32 regardless of model dtype** (:62-77), causal masking, softmax, weighted
sum, o_proj.

TPU-first redesign decisions:

- The cache is a fixed ``max_seq`` buffer; attention always reads the full
  buffer and masks out positions beyond the causal frontier. This keeps every
  decode step the same static shape (one compiled program) instead of the
  reference's growing-concat shapes.
- GQA is computed with a grouped einsum (``[B, kv_heads, group, T, D]``)
  instead of materializing ``repeat_kv`` copies (attention.rs:84-89) — XLA
  maps the group axis onto the MXU batch dimension for free, where a
  materialized repeat would burn HBM bandwidth.
- The memoized mask cache of the reference (cache.rs:81-103) is replaced by an
  iota comparison fused into the softmax by XLA.

On TPU, :func:`attend` dispatches to the fused Pallas flash kernels
(:mod:`cake_tpu.ops.pallas.flash`) — blockwise online softmax, causal mask in
registers, no HBM score materialization, KV blocks past the frontier never
fetched — at the shapes where the measured sweep says they win: prefill from
``PREFILL_FLASH_MIN_S`` context up (tools/flash_sweep.py). Below the
crossover, and for single-token decode, XLA's fused attention is faster and
``auto`` picks it. The XLA path also remains the parity oracle
(``CAKE_PALLAS=0`` forces it everywhere; ``CAKE_PALLAS=1`` forces the
kernels everywhere).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from cake_tpu.ops import kvcache as kv
from cake_tpu.ops import pallas as pk
from cake_tpu.ops import quant
from cake_tpu.ops.rope import apply_rope

log = logging.getLogger("cake_tpu.attention")

NEG_INF = -1e30


def _flash_ok(t: int, s: int, d: int) -> bool:
    """Shapes the compiled (non-interpret) kernels handle efficiently:
    lane-aligned head_dim and a KV buffer divisible into aligned blocks."""
    return d % 128 == 0 and s % 128 == 0


# Measured context-length crossover for ``impl="auto"`` (tools/flash_sweep.py
# on v5 lite, 8B geometry H=32/KVH=8/D=128 — same treatment quant_matmul's
# m>=16 gate got):
#
# - prefill: flash wins from S >= 2048 (1.5x at T=512/S=2048, 2.2-2.3x at
#   S=4096, 50x at S=8192 where XLA materializes the f32 score matrix) and
#   loses below it (0.77x at T=512/S=1024, 0.87x at T=256/S=512).
# - decode (T=1): XLA wins at every measured shape — 0.99x at S=512 falling
#   to 0.82x at S=8192, and 0.72-0.90x at serving batches 8/32 — the
#   [B, H, 1, S] score row is tiny, so XLA's fused masked gemv is already
#   bandwidth-optimal at the frontier-near-full worst case. The one regime
#   with a structural case for flash decode (it reads KV blocks only up to
#   the frontier; XLA sweeps the whole buffer) is an EARLY frontier in a
#   long window — tools/flash_sweep.py's (s, pos) decode rows measure it;
#   until a measured win lands in KERNELS_TPU.json, auto stays XLA and
#   CAKE_PALLAS=1 remains the only way to force the kernel.
PREFILL_FLASH_MIN_S = 2048
# T floor for the flash prefill: the sweep's smallest measured chunk is
# T=256; far below it the q-block degenerates (_pick_block of a tiny/odd T
# -> 1-row blocks) and the grid re-fetches the whole KV buffer per q-block
# — a speculative-verify dispatch (T ~ 9) would read S bytes T times.
# Real prefill buckets are powers of two >= 256 whenever S is in the flash
# regime, so the floor costs nothing on the prompt path.
PREFILL_FLASH_MIN_T = 256


def _flash_prefill_choice(t: int, s: int, d: int) -> str:
    """Measured-crossover dispatch for a prefill-shaped (T>1, scalar-pos)
    attention — shared by the plain and int8-KV paths so there is exactly
    one policy. Returns ``"flash"`` or ``"xla"``; warns when the kernels
    were wanted but the shape is not lane-aligned."""
    enabled = pk.kernels_enabled()
    want = enabled and (
        pk.force_kernels()
        or (t >= PREFILL_FLASH_MIN_T and s >= PREFILL_FLASH_MIN_S)
    )
    if not want:
        return "xla"
    if pk.interpret_default() or _flash_ok(t, s, d):
        return "flash"
    # Runs at trace time (once per compiled shape): a misaligned config
    # must not silently lose the kernels.
    log.warning(
        "flash kernels enabled but shape (T=%d, S=%d, D=%d) is not "
        "lane-aligned (need D%%128==0 and S%%128==0); falling back to the "
        "XLA attention path", t, s, d,
    )
    return "xla"


def attend(
    q: jax.Array,  # [B, n_heads, T, D] (already roped)
    k_all: jax.Array,  # [B, kv_heads, S, D] (full cache buffer)
    v_all: jax.Array,  # [B, kv_heads, S, D]
    pos,  # scalar: absolute position of q[..., 0, :]
    impl: str = "auto",  # auto | xla | flash
    window: int | None = None,  # sliding-window width (Mistral); None=full
) -> jax.Array:
    """Masked GQA attention over a fixed-size KV buffer. Returns [B,H,T,D].

    ``pos`` may be scalar or ``[B]`` (per-row causal frontiers — the
    multi-stream serving path; per-row is supported by the XLA path and the
    flash decode kernel, T>1 per-row routes to XLA).

    ``window``: sliding-window attention — key positions more than
    ``window`` behind the query are masked out. Both flash kernels fold
    the window lower bound into their block sweeps (out-of-window KV
    blocks are neither fetched nor computed). Prefill rides the kernel at
    the measured crossover; decode under ``impl="auto"`` stays XLA until
    a measured win lands (flash_sweep ``decode_win*`` rows), with
    ``impl="flash"``/``CAKE_PALLAS=1`` forcing the windowed kernel.
    Per-row prefill (T>1 with ``[B]`` pos) stays XLA — not a
    kernel-served shape.
    """
    t, d = q.shape[2], q.shape[3]
    s = k_all.shape[2]
    per_row = jnp.asarray(pos).ndim == 1
    if window is not None:
        # Windowed PREFILL rides the flash kernel at the measured
        # crossover (the lower bound is folded into its block sweep — KV
        # blocks outside the window are never fetched). Windowed DECODE
        # supports the kernel too (same lower-bound skip: ~W KV bytes vs
        # XLA's full-buffer sweep) but auto stays XLA until a measured
        # win lands (flash_sweep decode_win4096 rows); CAKE_PALLAS=1 or
        # impl='flash' forces it. Per-row prefill stays XLA (not a
        # kernel-served shape, windowed or not).
        if per_row and t > 1:
            impl = "xla"
        elif t == 1:
            if impl == "auto":
                force = pk.kernels_enabled() and pk.force_kernels()
                ok = pk.interpret_default() or _flash_ok(t, s, d)
                impl = "flash" if force and ok else "xla"
        elif impl == "auto":
            impl = _flash_prefill_choice(t, s, d)
        if impl == "flash":
            if t == 1:
                return pk.flash_decode(q, k_all, v_all, pos, window=window)
            return pk.flash_attention(q, k_all, v_all, pos, window=window)
        return _attend_xla(q, k_all, v_all, pos, window=window)
    if per_row and t > 1 and impl != "xla":
        impl = "xla"  # per-row prefill: XLA only (not a served path)
    if impl == "auto":
        if t > 1:
            impl = _flash_prefill_choice(t, s, d)
        elif pk.kernels_enabled() and pk.force_kernels():
            # decode: XLA wins at every measured shape (crossover notes
            # above); CAKE_PALLAS=1 still forces the kernel
            if pk.interpret_default() or _flash_ok(t, s, d):
                impl = "flash"
            else:
                impl = "xla"
                log.warning(
                    "flash kernels forced (CAKE_PALLAS=1) but decode shape "
                    "(T=%d, S=%d, D=%d) is not lane-aligned (need D%%128==0 "
                    "and S%%128==0); falling back to the XLA attention path",
                    t, s, d,
                )
        else:
            impl = "xla"
    if impl == "flash":
        if t == 1:
            return pk.flash_decode(q, k_all, v_all, pos)
        return pk.flash_attention(q, k_all, v_all, pos)
    return _attend_xla(q, k_all, v_all, pos, window=window)


def _attend_xla(
    q: jax.Array,
    k_all: jax.Array,
    v_all: jax.Array,
    pos,
    window: int | None = None,
) -> jax.Array:
    """Reference-math XLA path (full [T, S] scores, mask by iota compare).
    ``pos`` scalar or ``[B]`` (per-row causal frontier)."""
    b, n_heads, t, d = q.shape
    kv_heads, s = k_all.shape[1], k_all.shape[2]
    group = n_heads // kv_heads

    qg = q.reshape(b, kv_heads, group, t, d)
    # f32 scores regardless of model dtype (attention.rs:62-77).
    scores = jnp.einsum(
        "bkgtd,bksd->bkgts", qg, k_all, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.float32(d))

    # Causal frontier: key position valid iff kpos <= pos + t_idx.
    pos = jnp.asarray(pos, jnp.int32)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 1)
    qpos = jax.lax.broadcasted_iota(jnp.int32, (t, s), 0)
    if pos.ndim == 0:
        mask = (kpos <= qpos + pos)[None, None, None]  # [1,1,1,T,S]
        if window is not None:
            # sliding window: keys more than `window` behind the query are
            # out (key valid iff qpos+pos-window < kpos <= qpos+pos)
            mask &= (kpos > qpos + pos - window)[None, None, None]
    else:
        mask = (kpos[None] <= qpos[None] + pos[:, None, None])[
            :, None, None
        ]  # [B,1,1,T,S]
        if window is not None:
            mask &= (kpos[None] > qpos[None] + pos[:, None, None] - window)[
                :, None, None
            ]
    scores = jnp.where(mask, scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgts,bksd->bkgtd", probs.astype(v_all.dtype), v_all,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, n_heads, t, d).astype(q.dtype)


def self_attention_block(
    x: jax.Array,  # [B, T, hidden]
    wq: jax.Array,  # [hidden, n_heads * D]
    wk: jax.Array,  # [hidden, kv_heads * D]
    wv: jax.Array,  # [hidden, kv_heads * D]
    wo: jax.Array,  # [n_heads * D, hidden]
    k_cache: jax.Array,  # [B, kv_heads, S, D]
    v_cache: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    pos,
    num_heads: int,
    num_kv_heads: int,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    sp_size: int = 1,
    write_gate: jax.Array | None = None,
    sp_prefill: bool | None = None,
    sp_chunk: bool = False,
    bq: jax.Array | None = None,  # q/k/v projection biases (Qwen2 family)
    bk: jax.Array | None = None,
    bv: jax.Array | None = None,
    bo: jax.Array | None = None,  # o_proj bias (HF llama-arch attention_bias)
    window: int | None = None,  # sliding-window width (Mistral family)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One attention sublayer incl. cache update.

    Returns ``(attn_out [B,T,hidden], new_k_cache, new_v_cache)``.
    Mirrors `attention.rs:30-90` + `cache.process_kv` (:57).

    ``tp_axis``: when run inside shard_map with heads sharded over a tensor-
    parallel mesh axis (Megatron-style: column-parallel qkv, row-parallel
    o_proj), pass the axis name — the o_proj partial sums are psum-reduced
    over it. ``num_heads``/``num_kv_heads`` are then the *local* counts.

    ``sp_axis``: sequence/context parallelism (:mod:`cake_tpu.ops.ring`).
    The cache's sequence axis is sharded over this mesh axis; shard *i* owns
    global positions ``[i*S_l, (i+1)*S_l)``. Two modes:

    - prefill: ``x`` holds this shard's chunk of the (bucketed) prompt —
      ring attention over the sp ring, chunked cache write.
    - decode (``T == 1``): ``x`` is replicated; the owner shard commits the
      new KV slot and exact softmax is reassembled from per-shard partials
      (distributed flash decoding).

    ``sp_prefill`` selects the mode explicitly (the pipeline builders pass
    it); ``None`` falls back to the ``T > 1`` heuristic, which is WRONG for
    one-token-per-shard prefill chunks — callers that can produce
    ``T_local == 1`` prefill must pass the flag.

    ``sp_chunk`` selects a third sp mode (overriding both): chunked OFFSET
    prefill against committed history — ``x`` is the full chunk replicated
    on every sp shard, positioned at ``pos`` (scalar: the admission /
    shared-prefix serving path; ``[B]``: per-row chunk frontiers, the
    sp serving speculation-verification path).

    ``write_gate`` (scalar bool): when running inside an SPMD-uniform pipeline
    loop every stage executes this code every step (collectives must be
    uniform across devices — a conditional ppermute/psum deadlocks); the gate
    makes the KV commit predicated so only the active stage's write lands.
    """
    b, t, hidden = x.shape
    d = quant.out_features(wq) // num_heads

    q = quant.dense(x, wq)
    k = quant.dense(x, wk)
    v = quant.dense(x, wv)
    if bq is not None:
        q = q + bq
    if bk is not None:
        k = k + bk
    if bv is not None:
        v = v + bv
    q = q.reshape(b, t, num_heads, d).transpose(0, 2, 1, 3)
    k = k.reshape(b, t, num_kv_heads, d).transpose(0, 2, 1, 3)
    v = v.reshape(b, t, num_kv_heads, d).transpose(0, 2, 1, 3)

    if sp_axis is not None and sp_size > 1:
        from cake_tpu.ops import ring

        quantized = isinstance(k_cache, kv.QuantizedKV)
        s_l = kv._kv_data(k_cache).shape[2]
        sp_idx = jax.lax.axis_index(sp_axis)
        is_prefill = (not sp_chunk) and (
            sp_prefill if sp_prefill is not None else t > 1
        )
        # pos may be [B] (multi-stream sp serving: per-row frontiers) on
        # the decode path; the prefill path positions by chunk offset and
        # never reads it
        if is_prefill:
            # Sequence-parallel prefill: the prompt (bucketed to a multiple
            # of sp) is sharded over the ring; ring attention costs are
            # prompt-proportional, not window-proportional.
            if jnp.asarray(pos).ndim:
                # this branch positions by chunk offset and never reads
                # pos — a caller passing per-row positions here would get
                # silently wrong RoPE/causal offsets
                raise ValueError(
                    "per-row positions are not supported by sp prefill "
                    "(rows share the chunk-offset position layout)"
                )
            if t > s_l:
                raise ValueError(
                    f"sp prefill chunk (T_local {t}) exceeds the cache "
                    f"window per shard (S_local {s_l})"
                )
            my_off = sp_idx * t  # global position of this shard's token 0
            q = apply_rope(q, cos, sin, my_off)
            k = apply_rope(k, cos, sin, my_off)
            if quantized:
                # attention must see exactly what the cache will hold:
                # round-trip the chunk through the int8 quantization before
                # the ring (the same values the sp_*_write paths store), so
                # sp output matches the single-device int8-KV oracle
                k_att = kv.dequant_kv(kv.quant_kv(k), q.dtype)
                v_att = kv.dequant_kv(kv.quant_kv(v), q.dtype)
            else:
                k_att, v_att = k, v
            if t == s_l:
                # chunk layout == cache layout: write in place, no gather
                k_cache, v_cache = kv.update_layer(k_cache, v_cache, k, v, 0,
                                                   gate=write_gate)
            else:
                k_cache, v_cache = ring.sp_chunked_cache_write(
                    k_cache, v_cache, k, v, sp_axis, sp_size, gate=write_gate
                )
            out = ring.ring_attention(q, k_att, v_att, sp_axis, sp_size,
                                      q_off=my_off, window=window)
        elif sp_chunk:
            # Chunked offset prefill over the sp-sharded window (the
            # continuous-batching admission / shared-prefix remainder
            # path): the chunk's T tokens run REPLICATED on every sp
            # shard from global position ``pos`` against the committed
            # history already in the range-sharded cache — owner-masked
            # range write, then the exact softmax reassembled from
            # per-shard partials (the T>1 generalization of distributed
            # flash decode).
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
            shard_start = sp_idx * s_l
            k_cache, v_cache = ring.sp_range_cache_write(
                k_cache, v_cache, k, v, pos, shard_start, gate=write_gate
            )
            out = ring.sp_decode_attend(
                q, kv.dequant_kv(k_cache, q.dtype),
                kv.dequant_kv(v_cache, q.dtype), pos, sp_axis, shard_start,
                window=window,
            )
        else:
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
            shard_start = sp_idx * s_l
            k_cache, v_cache = ring.sp_cache_write(
                k_cache, v_cache, k, v, pos, shard_start, gate=write_gate
            )
            out = ring.sp_decode_attend(
                q, kv.dequant_kv(k_cache, q.dtype),
                kv.dequant_kv(v_cache, q.dtype), pos, sp_axis, shard_start,
                window=window,
            )
    else:
        q = apply_rope(q, cos, sin, pos)
        k = apply_rope(k, cos, sin, pos)
        k_cache, v_cache = kv.update_layer(k_cache, v_cache, k, v, pos,
                                           gate=write_gate)
        quantized = isinstance(k_cache, kv.QuantizedKV)
        if quantized:
            # int8 KV. Long-context prefill (the measured flash regime,
            # S >= PREFILL_FLASH_MIN_S) routes to the quantization-aware
            # flash kernel, which folds the per-token scales into the
            # score columns / probabilities and reads only int8 bytes.
            # Everything else — decode, short prefill — dequantizes at
            # trace level on the XLA path, where the convert+mul fuses
            # into the attention dot's operand read. (A plain-flash-kernel
            # operand would be a materialized bf16 KV buffer in HBM,
            # losing the bandwidth win, so plain flash is never used with
            # the quantized cache.)
            s_len = k_cache.q.shape[2]
            use_q8_flash = (
                t > 1
                and jnp.asarray(pos).ndim == 0
                and _flash_prefill_choice(t, s_len, d) == "flash"
            )
            if use_q8_flash:
                out = pk.flash_attention_q8(
                    q, k_cache.q, k_cache.scale, v_cache.q, v_cache.scale,
                    pos, window=window,
                )
            else:
                out = attend(q, kv.dequant_kv(k_cache, q.dtype),
                             kv.dequant_kv(v_cache, q.dtype), pos,
                             impl="xla", window=window)
        else:
            out = attend(q, k_cache, v_cache, pos, window=window)  # [B,H,T,D]

    out = out.transpose(0, 2, 1, 3).reshape(b, t, num_heads * d)
    out = quant.dense(out, wo)
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    if bo is not None:
        # after the tp reduction: the bias belongs to the full (summed)
        # projection, not to each rank's partial
        out = out + bo
    return out, k_cache, v_cache
