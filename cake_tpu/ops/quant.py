"""Int8 weight quantization (per-output-channel, symmetric).

The reference runs f16/bf16 weights only (dtype plane, `cake/mod.rs:56-62`);
int8 is a capability the TPU build adds because it is load-bearing for the
70B-on-v5e-16 target (SURVEY.md §7: ~8.75 GB f16 weights + KV per 16 GB chip
leaves no headroom — int8 halves the weight bytes and decode is
HBM-bandwidth-bound, so it is also a throughput lever).

Scheme: symmetric per-output-channel absmax. For a weight ``w [in, out]``
(or stacked ``[L, in, out]``): ``scale = absmax(w, axis=in) / 127``,
``q = round(w / scale)`` in int8. Matmul dequantizes in the epilogue:
``y = (x @ q) * scale`` — the int8 weights stream from HBM at half the bf16
bytes and the MXU accumulates in f32 (on TPU via the Pallas kernel in
:mod:`cake_tpu.ops.pallas.quant`; elsewhere XLA fuses the int8→bf16 convert
into the dot).

Every linear site in the model goes through :func:`dense`, which accepts
either a plain array or a :class:`QuantizedLinear` — quantization is a pure
params-pytree transform (:func:`quantize_params`), no model code changes.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["q", "scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class QuantizedLinear:
    """int8 weight + f32 per-output-channel scale.

    ``q: [..., in, out] int8``, ``scale: [..., out] f32`` (leading axes — the
    stacked layer axis — are shared)."""

    q: jax.Array
    scale: jax.Array


def quantize_linear(w: jax.Array) -> QuantizedLinear:
    """Symmetric per-output-channel int8 quantization of ``w [..., in, out]``."""
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2)  # [..., out]
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QuantizedLinear(q=q, scale=scale)


def quantize_linear_np(w) -> tuple:
    """Host-side (numpy) variant of :func:`quantize_linear` for quantize-
    during-load: the bf16 weight never reaches the device, so peak HBM is the
    int8 bytes, not bf16 + temporaries. Returns ``(q int8, scale f32)``."""
    import numpy as np

    wf = np.asarray(w, np.float32)
    absmax = np.max(np.abs(wf), axis=-2)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale[..., None, :]), -127, 127).astype(np.int8)
    # C-order outputs even when ``w`` is a transposed view (see the int4
    # twin below): raw-buffer serializers must never see F-ordered arrays
    return np.ascontiguousarray(q), np.ascontiguousarray(scale)


# Linear weight names eligible for quantization (norms/embed stay bf16; the
# embedding is a gather, not a matmul, and norm scales are tiny).
LAYER_LINEARS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def reject_int4_moe() -> None:
    """The ONE int4+MoE rejection, raised by every entry point (pytree
    quantize, random-init, both checkpoint loaders, the offline tool) so
    that wiring int4 expert packing later means deleting exactly one
    guard per site and this helper — no independently-worded copies to
    drift (the same single-source rule as tools' _LINEAR_SUFFIXES)."""
    raise NotImplementedError(
        "int4 MoE expert stacks are not wired (the nibble packing is 2D); "
        "use int8 for Mixtral-family quantization"
    )


def quantize_params(
    params: dict, bits: int = 8, group_size: int | None = None
) -> dict:
    """Quantize every linear in a params pytree (model or stage slice).

    Works on full params (embed/norm_f/lm_head + layers) and on bare stacked
    layer pytrees (a worker's slice). ``bits`` selects the tier: 8
    (:class:`QuantizedLinear`) or 4 (:class:`Quantized4Linear`, packed);
    ``group_size`` (int4 only) switches to group-wise scales along the in
    axis — the accuracy tier for real checkpoints."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    if group_size is not None and bits != 4:
        raise ValueError("group_size applies to bits=4 only")
    layer_tree = params.get("layers", params) if isinstance(params, dict) else {}
    if bits == 4 and isinstance(layer_tree, dict) and "router" in layer_tree:
        reject_int4_moe()
    if bits == 8:
        qfn = quantize_linear
    else:
        qfn = partial(quantize_linear4, group_size=group_size)
    out = dict(params)
    if "layers" in params:
        out["layers"] = {
            k: (qfn(v) if k in LAYER_LINEARS else v)
            for k, v in params["layers"].items()
        }
    elif all(k in params for k in ("wq", "wo")):  # bare layer-stack pytree
        return {
            k: (qfn(v) if k in LAYER_LINEARS else v)
            for k, v in params.items()
        }
    if "lm_head" in params:
        out["lm_head"] = qfn(params["lm_head"])
    return out


def dequantize_linear(w: QuantizedLinear, dtype=jnp.bfloat16) -> jax.Array:
    return (w.q.astype(jnp.float32) * w.scale[..., None, :]).astype(dtype)


# ---------------------------------------------------------------------------
# int4 (packed) — half the int8 bytes again on the decode-dominating weight
# stream. Same per-output-channel symmetric scheme at absmax/7, values in
# [-7, 7], two values packed per int8 byte along the *in* (K) axis.
#
# Packing convention — ADJACENT pairs: byte i of ``qp [K/2, N]`` holds
# q(2i, n) in its low nibble and q(2i+1, n) in its high nibble. This makes
# the packed array **sharding-transparent on the K axis**: packed rows
# [a, b) always correspond to the contiguous original rows [2a, 2b), so a
# row-parallel (in-axis) tp shard of the globally packed weight is exactly
# the pack of that shard's slice. (A halves layout — k paired with
# k + K/2 — would pair rows living in different tp shards and silently
# break under parallel/mesh.py's in-axis partitioning.)
#
# The matmul splits the ACTIVATION instead, where striding is cheap
# (activations are M x K, weights are K x N):
#
#     y = x[:, 0::2] @ lo(qp) + x[:, 1::2] @ hi(qp)
#
# — both the XLA fallback and the Pallas kernel
# (ops/pallas/quant.py:quant4_matmul_pallas) use this form. Sign extension
# is pure arithmetic shifts: ``hi = p >> 4``, ``lo = (p << 4) >> 4``.
# ---------------------------------------------------------------------------


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["qp", "scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class Quantized4Linear:
    """Packed int4 weight + f32 scales.

    ``qp: [..., in/2, out] int8`` (two nibbles per byte, adjacent-pair
    packing). ``scale`` is either ``[..., out]`` (per-output-channel) or
    ``[..., ngroups, out]`` (group-wise along the in axis, group size
    ``in / ngroups`` — the standard int4 accuracy fix; the tier is read
    off the scale's rank, no extra metadata)."""

    qp: jax.Array
    scale: jax.Array

    @property
    def group_size(self) -> int | None:
        """Group size along the in axis, or None for per-channel."""
        if self.scale.ndim == self.qp.ndim - 1:
            return None
        return 2 * self.qp.shape[-2] // self.scale.shape[-2]


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int4 values ``q [..., K, N]`` (in [-7, 7], any int dtype) into
    ``[..., K/2, N] int8`` with adjacent-pair nibble layout (byte i = rows
    2i low, 2i+1 high)."""
    k = q.shape[-2]
    if k % 2:
        raise ValueError(f"int4 packing needs an even in-dim, got {k}")
    q = q.astype(jnp.int8)
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    return (lo & 0xF) | (hi << 4)


def unpack_int4(qp: jax.Array) -> jax.Array:
    """Inverse of :func:`pack_int4`: ``[..., K/2, N] int8 -> [..., K, N]``
    int8 values in [-7, 7]."""
    lo = (qp << 4) >> 4
    hi = qp >> 4
    k2, n = qp.shape[-2], qp.shape[-1]
    return jnp.stack([lo, hi], axis=-2).reshape(*qp.shape[:-2], 2 * k2, n)


def quantize_linear4(
    w: jax.Array, group_size: int | None = None
) -> Quantized4Linear:
    """Symmetric int4 quantization of ``w [..., in, out]``.

    ``group_size=None``: one scale per output channel (absmax over the full
    in axis). ``group_size=G``: one scale per (G-row in-group, channel) —
    int4's dynamic range is 4 bits, so per-channel absmax wastes most of it
    on outlier rows; G of 64–128 recovers near-int8 fidelity (tested)."""
    wf = jnp.asarray(w, jnp.float32)
    k = wf.shape[-2]
    if group_size is None:
        absmax = jnp.max(jnp.abs(wf), axis=-2)  # [..., out]
        scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
        q = jnp.clip(jnp.round(wf / scale[..., None, :]), -7, 7)
        return Quantized4Linear(qp=pack_int4(q), scale=scale)
    if k % group_size or group_size % 2:
        raise ValueError(
            f"group_size {group_size} must be even and divide in-dim {k}"
        )
    g = k // group_size
    wg = wf.reshape(*wf.shape[:-2], g, group_size, wf.shape[-1])
    absmax = jnp.max(jnp.abs(wg), axis=-2)  # [..., g, out]
    scale = jnp.where(absmax > 0, absmax / 7.0, 1.0)
    q = jnp.clip(jnp.round(wg / scale[..., None, :]), -7, 7)
    q = q.reshape(*wf.shape[:-2], k, wf.shape[-1])
    return Quantized4Linear(qp=pack_int4(q), scale=scale)


def pack_int4_np(q) -> "np.ndarray":  # noqa: F821 — numpy is lazy here
    """Numpy twin of :func:`pack_int4` — THE one place the adjacent-pair
    nibble layout is written on the host side (the layout is load-bearing
    for tp sharding; a second hand-inlined copy could silently drift)."""
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    return (lo & 0xF) | (hi << 4)


def quantize_linear4_np(w, group_size: int | None = None) -> tuple:
    """Host-side (numpy) variant of :func:`quantize_linear4` for quantize-
    during-load. Returns ``(qp int8 packed, scale f32)``."""
    import numpy as np

    wf = np.asarray(w, np.float32)
    k = wf.shape[-2]
    if k % 2:
        raise ValueError(f"int4 packing needs an even in-dim, got {k}")
    if group_size is None:
        absmax = np.max(np.abs(wf), axis=-2)
        scale = np.where(absmax > 0, absmax / 7.0, 1.0).astype(np.float32)
        q = np.clip(np.round(wf / scale[..., None, :]), -7, 7).astype(np.int8)
    else:
        if k % group_size or group_size % 2:
            raise ValueError(
                f"group_size {group_size} must be even and divide "
                f"in-dim {k}"
            )
        g = k // group_size
        wg = wf.reshape(*wf.shape[:-2], g, group_size, wf.shape[-1])
        absmax = np.max(np.abs(wg), axis=-2)
        scale = np.where(absmax > 0, absmax / 7.0, 1.0).astype(np.float32)
        q = np.clip(np.round(wg / scale[..., :, None, :]), -7, 7)
        q = q.reshape(*wf.shape[:-2], k, wf.shape[-1]).astype(np.int8)
    # elementwise ops inherit the INPUT's memory order: quantizing a
    # transposed view (the loaders pass w.T) yields F-ordered outputs,
    # which raw-buffer serializers (safetensors) would scramble
    return (np.ascontiguousarray(pack_int4_np(q)),
            np.ascontiguousarray(scale))


def parse_quant_spec(spec: str | None) -> tuple[str | None, int | None]:
    """Parse a quantize spec string into ``(tier, group_size)``.

    ``None`` → ``(None, None)``; ``"int8"``/``"int4"`` → per-channel;
    ``"int4:gN"`` → int4 with N-row groups along the in axis. The spec
    string is what rides the CLI ``--quantize`` flag and every loader's
    ``quantize=`` parameter, so the grouped tier needs no extra plumbing.
    (Loading a pre-quantized grouped ``.q4`` checkpoint needs only
    ``"int4"`` — the stored scale's shape carries the grouping.)"""
    if spec is None:
        return None, None
    if spec in ("int8", "int4"):
        return spec, None
    import re

    m = re.fullmatch(r"int4:g(\d+)", spec)
    if m and int(m.group(1)) > 0:
        return "int4", int(m.group(1))
    raise ValueError(
        f"unsupported quantize spec {spec!r} (want int8, int4, or int4:gN "
        f"with N >= 1)"
    )


def dequantize_linear4(w: Quantized4Linear, dtype=jnp.bfloat16) -> jax.Array:
    q = unpack_int4(w.qp).astype(jnp.float32)
    if w.group_size is None:
        return (q * w.scale[..., None, :]).astype(dtype)
    k, n = q.shape[-2], q.shape[-1]
    g = w.scale.shape[-2]
    qg = q.reshape(*q.shape[:-2], g, k // g, n) * w.scale[..., :, None, :]
    return qg.reshape(*q.shape[:-2], k, n).astype(dtype)


def quant4_matmul_xla(
    x: jax.Array, qp: jax.Array, scale: jax.Array
) -> jax.Array:
    """Fallback path. Per-channel (``scale [out]``): even/odd two-dot
    formulation — each shift-unpack chain feeds its dot directly (the
    weight side never interleaves); the strided slices touch only the small
    activation operand. Grouped (``scale [ngroups, out]``): per-group
    batched dot with the scale applied to the f32 partials before the
    group-sum, so quantization error never crosses group boundaries."""
    if scale.ndim == qp.ndim:  # grouped
        k2, n = qp.shape[-2], qp.shape[-1]
        g = scale.shape[-2]
        # f32 operands: the batched-dot thunk on CPU cannot mix
        # bf16 x bf16 -> f32, and f32 partials match the kernel's
        # accumulation; this fallback trades speed for fidelity (the hot
        # grouped path is the Pallas kernel)
        wg = unpack_int4(qp).astype(jnp.float32).reshape(
            g, (2 * k2) // g, n)
        xg = x.astype(jnp.float32).reshape(
            *x.shape[:-1], g, (2 * k2) // g)
        partial = jnp.einsum("...gk,gkn->...gn", xg, wg)
        return (partial * scale).sum(axis=-2).astype(x.dtype)
    w_lo = ((qp << 4) >> 4).astype(x.dtype)
    w_hi = (qp >> 4).astype(x.dtype)
    y = jnp.dot(
        x[..., 0::2], w_lo, preferred_element_type=jnp.float32
    ) + jnp.dot(x[..., 1::2], w_hi, preferred_element_type=jnp.float32)
    return (y * scale).astype(x.dtype)


def quant_matmul_xla(x: jax.Array, q: jax.Array, scale: jax.Array) -> jax.Array:
    """Fallback path: XLA fuses the int8→x.dtype convert into the dot."""
    y = jnp.dot(x, q.astype(x.dtype), preferred_element_type=jnp.float32)
    return (y * scale).astype(x.dtype)


# Trace-time backend pin (see pinned_impl). None = per-shape measured gate.
# A ContextVar, not a module global: two serving instances with different
# pins may dispatch (and therefore trace) from different threads
# concurrently — a plain global could bake the WRONG pin into another
# instance's jit cache for its whole lifetime.
_PINNED: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "cake_quant_pinned", default=None)


def pinned() -> str | None:
    """The active backend pin in this context (None = measured gate)."""
    return _PINNED.get()


@contextlib.contextmanager
def pinned_impl(impl: str | None):
    """Pin ``quant_matmul``'s auto dispatch for the dynamic extent.

    The measured m>=16 crossover gate picks the backend per SHAPE, so the
    same stream's logits can differ in their low-order bits between batch
    -size buckets or between prefix-hit and prefix-miss admission prefills
    (different row counts -> different backend), which with temperature > 0
    can flip a near-boundary sampled token. A serving instance closes that
    by tracing every one of its programs under one pinned backend
    (runtime/batch_generator.py) — the pin only needs to surround the jit
    CALLS (tracing happens on first call), and it overrides the
    interpret-mode default too so CPU tests exercise the same invariance.
    ``"pallas"`` still falls back to XLA when the kernels are disabled or
    the shape is not tileable (a pin must never crash a program the gate
    would have run)."""
    token = _PINNED.set(impl)
    try:
        yield
    finally:
        _PINNED.reset(token)


def quant_matmul(
    x: jax.Array,  # [..., in]
    q: jax.Array,  # [in, out] int8
    scale: jax.Array,  # [out] f32
    impl: str = "auto",
) -> jax.Array:
    from cake_tpu.ops import pallas as pk

    if impl == "auto":
        pin = _PINNED.get()
        if pin is not None:
            # instance-lifetime pin (pinned_impl): one backend for every
            # shape this trace sees; tileability still guards the kernel
            impl = (
                "pallas"
                if pin == "pallas"
                and pk.kernels_enabled()
                and (
                    pk.interpret_default()
                    or (q.shape[0] % 256 == 0 and q.shape[1] % 256 == 0)
                )
                else "xla"
            )
        else:
            # The compiled kernel needs enough rows to tile the MXU; skinny
            # inputs run XLA's gemv path, which is ~67% faster at M=1 on
            # v5e (measured single-stream 8B int8: 84.7 vs 50.7 tok/s) and
            # ~40% faster at M=8 (batched decode). The crossover is ~M=16,
            # where the kernel's int8-in-VMEM streaming starts winning (522
            # vs 505 aggregate tok/s at batch 16) — see BASELINE.md r2.
            m = x.size // x.shape[-1]
            impl = (
                "pallas"
                if pk.kernels_enabled()
                and (
                    pk.interpret_default()
                    or (
                        m >= 16
                        and q.shape[0] % 256 == 0
                        and q.shape[1] % 256 == 0
                    )
                )
                else "xla"
            )
    if impl == "pallas":
        from cake_tpu.ops.pallas.quant import quant_matmul_pallas

        lead_shape = x.shape[:-1]
        y = quant_matmul_pallas(x.reshape(-1, x.shape[-1]), q, scale)
        return y.reshape(*lead_shape, q.shape[1])
    return quant_matmul_xla(x, q, scale)


def quant4_matmul(
    x: jax.Array,  # [..., in]
    qp: jax.Array,  # [in/2, out] int8 packed
    scale: jax.Array,  # [out] or [ngroups, out] f32
    impl: str = "auto",
) -> jax.Array:
    """int4 twin of :func:`quant_matmul` — same pin/auto dispatch contract.

    The auto gate reuses the int8 m>=16 crossover as its prior (the kernels
    share the streaming structure); the int4 frontier is re-measured on chip
    by tools/flash_sweep-style rows before any claim lands in BASELINE.md."""
    from cake_tpu.ops import pallas as pk

    k2, n = qp.shape[-2], qp.shape[-1]
    # grouped scales cap the K block at half a group — the gate checks the
    # unit the kernel will actually tile. 128 is the Mosaic lane width: a
    # smaller K block would make the activation BlockSpec's last dim
    # sub-lane and fail to lower on a real TPU, so the gate must guarantee
    # bk2 >= 128 (the pin contract: never crash a program the gate would
    # have run). Grouped at group_size=128 (g2=64) therefore runs XLA.
    kunit = k2 // scale.shape[-2] if scale.ndim == qp.ndim else k2
    tileable = kunit % 128 == 0 and n % 256 == 0
    if impl == "auto":
        pin = _PINNED.get()
        if pin is not None:
            impl = (
                "pallas"
                if pin == "pallas"
                and pk.kernels_enabled()
                and (pk.interpret_default() or tileable)
                else "xla"
            )
        else:
            # Unlike int8 (where XLA's gemv fuses the convert and wins below
            # m=16), the int4 XLA fallback cannot fuse the shift-unpack into
            # the dot: it re-materializes bf16 weights every step — 4x the
            # packed bytes (measured 47.8 tok/s at M=1 on the 8B v5e
            # single-stream bench, i.e. the bf16 rate). The kernel (with
            # sublane M-padding) streams the packed bytes, so tileability
            # is the only gate.
            impl = (
                "pallas"
                if pk.kernels_enabled()
                and (pk.interpret_default() or tileable)
                else "xla"
            )
    if impl == "pallas":
        from cake_tpu.ops.pallas.quant import quant4_matmul_pallas

        lead_shape = x.shape[:-1]
        y = quant4_matmul_pallas(x.reshape(-1, x.shape[-1]), qp, scale)
        return y.reshape(*lead_shape, n)
    return quant4_matmul_xla(x, qp, scale)


def out_features(w) -> int:
    """Output width of a linear weight (plain or quantized)."""
    if isinstance(w, QuantizedLinear):
        return w.q.shape[-1]
    if isinstance(w, Quantized4Linear):
        return w.qp.shape[-1]
    return w.shape[-1]


def dense(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for a plain array, :class:`QuantizedLinear`, or
    :class:`Quantized4Linear` — the single dispatch point every linear in
    the model routes through."""
    if isinstance(w, QuantizedLinear):
        return quant_matmul(x, w.q, w.scale)
    if isinstance(w, Quantized4Linear):
        return quant4_matmul(x, w.qp, w.scale)
    return x @ w
