"""Int8 weight quantization (per-output-channel, symmetric).

The reference runs f16/bf16 weights only (dtype plane, `cake/mod.rs:56-62`);
int8 is a capability the TPU build adds because it is load-bearing for the
70B-on-v5e-16 target (SURVEY.md §7: ~8.75 GB f16 weights + KV per 16 GB chip
leaves no headroom — int8 halves the weight bytes and decode is
HBM-bandwidth-bound, so it is also a throughput lever).

Scheme: symmetric per-output-channel absmax. For a weight ``w [in, out]``
(or stacked ``[L, in, out]``): ``scale = absmax(w, axis=in) / 127``,
``q = round(w / scale)`` in int8. Matmul dequantizes in the epilogue:
``y = (x @ q) * scale`` — the int8 weights stream from HBM at half the bf16
bytes and the MXU accumulates in f32 (on TPU via the Pallas kernel in
:mod:`cake_tpu.ops.pallas.quant`; elsewhere XLA fuses the int8→bf16 convert
into the dot).

Every linear site in the model goes through :func:`dense`, which accepts
either a plain array or a :class:`QuantizedLinear` — quantization is a pure
params-pytree transform (:func:`quantize_params`), no model code changes.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["q", "scale"],
    meta_fields=[],
)
@dataclasses.dataclass
class QuantizedLinear:
    """int8 weight + f32 per-output-channel scale.

    ``q: [..., in, out] int8``, ``scale: [..., out] f32`` (leading axes — the
    stacked layer axis — are shared)."""

    q: jax.Array
    scale: jax.Array


def quantize_linear(w: jax.Array) -> QuantizedLinear:
    """Symmetric per-output-channel int8 quantization of ``w [..., in, out]``."""
    wf = jnp.asarray(w, jnp.float32)
    absmax = jnp.max(jnp.abs(wf), axis=-2)  # [..., out]
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wf / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QuantizedLinear(q=q, scale=scale)


def quantize_linear_np(w) -> tuple:
    """Host-side (numpy) variant of :func:`quantize_linear` for quantize-
    during-load: the bf16 weight never reaches the device, so peak HBM is the
    int8 bytes, not bf16 + temporaries. Returns ``(q int8, scale f32)``."""
    import numpy as np

    wf = np.asarray(w, np.float32)
    absmax = np.max(np.abs(wf), axis=-2)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(wf / scale[..., None, :]), -127, 127).astype(np.int8)
    return q, scale


# Linear weight names eligible for quantization (norms/embed stay bf16; the
# embedding is a gather, not a matmul, and norm scales are tiny).
LAYER_LINEARS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params: dict) -> dict:
    """Quantize every linear in a params pytree (model or stage slice).

    Works on full params (embed/norm_f/lm_head + layers) and on bare stacked
    layer pytrees (a worker's slice)."""
    out = dict(params)
    if "layers" in params:
        out["layers"] = {
            k: (quantize_linear(v) if k in LAYER_LINEARS else v)
            for k, v in params["layers"].items()
        }
    elif all(k in params for k in ("wq", "wo")):  # bare layer-stack pytree
        return {
            k: (quantize_linear(v) if k in LAYER_LINEARS else v)
            for k, v in params.items()
        }
    if "lm_head" in params:
        out["lm_head"] = quantize_linear(params["lm_head"])
    return out


def dequantize_linear(w: QuantizedLinear, dtype=jnp.bfloat16) -> jax.Array:
    return (w.q.astype(jnp.float32) * w.scale[..., None, :]).astype(dtype)


def quant_matmul_xla(x: jax.Array, q: jax.Array, scale: jax.Array) -> jax.Array:
    """Fallback path: XLA fuses the int8→x.dtype convert into the dot."""
    y = jnp.dot(x, q.astype(x.dtype), preferred_element_type=jnp.float32)
    return (y * scale).astype(x.dtype)


# Trace-time backend pin (see pinned_impl). None = per-shape measured gate.
# A ContextVar, not a module global: two serving instances with different
# pins may dispatch (and therefore trace) from different threads
# concurrently — a plain global could bake the WRONG pin into another
# instance's jit cache for its whole lifetime.
_PINNED: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "cake_quant_pinned", default=None)


def pinned() -> str | None:
    """The active backend pin in this context (None = measured gate)."""
    return _PINNED.get()


@contextlib.contextmanager
def pinned_impl(impl: str | None):
    """Pin ``quant_matmul``'s auto dispatch for the dynamic extent.

    The measured m>=16 crossover gate picks the backend per SHAPE, so the
    same stream's logits can differ in their low-order bits between batch
    -size buckets or between prefix-hit and prefix-miss admission prefills
    (different row counts -> different backend), which with temperature > 0
    can flip a near-boundary sampled token. A serving instance closes that
    by tracing every one of its programs under one pinned backend
    (runtime/batch_generator.py) — the pin only needs to surround the jit
    CALLS (tracing happens on first call), and it overrides the
    interpret-mode default too so CPU tests exercise the same invariance.
    ``"pallas"`` still falls back to XLA when the kernels are disabled or
    the shape is not tileable (a pin must never crash a program the gate
    would have run)."""
    token = _PINNED.set(impl)
    try:
        yield
    finally:
        _PINNED.reset(token)


def quant_matmul(
    x: jax.Array,  # [..., in]
    q: jax.Array,  # [in, out] int8
    scale: jax.Array,  # [out] f32
    impl: str = "auto",
) -> jax.Array:
    from cake_tpu.ops import pallas as pk

    if impl == "auto":
        pin = _PINNED.get()
        if pin is not None:
            # instance-lifetime pin (pinned_impl): one backend for every
            # shape this trace sees; tileability still guards the kernel
            impl = (
                "pallas"
                if pin == "pallas"
                and pk.kernels_enabled()
                and (
                    pk.interpret_default()
                    or (q.shape[0] % 256 == 0 and q.shape[1] % 256 == 0)
                )
                else "xla"
            )
        else:
            # The compiled kernel needs enough rows to tile the MXU; skinny
            # inputs run XLA's gemv path, which is ~67% faster at M=1 on
            # v5e (measured single-stream 8B int8: 84.7 vs 50.7 tok/s) and
            # ~40% faster at M=8 (batched decode). The crossover is ~M=16,
            # where the kernel's int8-in-VMEM streaming starts winning (522
            # vs 505 aggregate tok/s at batch 16) — see BASELINE.md r2.
            m = x.size // x.shape[-1]
            impl = (
                "pallas"
                if pk.kernels_enabled()
                and (
                    pk.interpret_default()
                    or (
                        m >= 16
                        and q.shape[0] % 256 == 0
                        and q.shape[1] % 256 == 0
                    )
                )
                else "xla"
            )
    if impl == "pallas":
        from cake_tpu.ops.pallas.quant import quant_matmul_pallas

        lead_shape = x.shape[:-1]
        y = quant_matmul_pallas(x.reshape(-1, x.shape[-1]), q, scale)
        return y.reshape(*lead_shape, q.shape[1])
    return quant_matmul_xla(x, q, scale)


def out_features(w) -> int:
    """Output width of a linear weight (plain or quantized)."""
    return (w.q if isinstance(w, QuantizedLinear) else w).shape[-1]


def dense(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for either a plain array or a :class:`QuantizedLinear` —
    the single dispatch point every linear in the model routes through."""
    if isinstance(w, QuantizedLinear):
        return quant_matmul(x, w.q, w.scale)
    return x @ w
