"""Rotary position embeddings.

Equivalent of the reference's precomputed cos/sin tables + rope application
(`cache.rs:31-50` builds ``theta_i = rope_theta^(-2i/d)`` tables for
MAX_SEQ_LEN positions; `attention.rs:17-27` slices them by ``index_pos`` and
applies ``candle_nn::rotary_emb::rope``). Here the tables are a small constant
pytree computed once per model; slicing by position is a
``dynamic_slice`` so the decode step stays a single compiled program.

The rotation convention matches candle's ``rotary_emb::rope`` (non-interleaved
half-rotation, the HF Llama convention): split head_dim into two halves,
rotate ``(x1, x2) -> (x1*cos - x2*sin, x1*sin + x2*cos)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _scale_inv_freq(inv_freq: jnp.ndarray, scaling: dict) -> jnp.ndarray:
    """Apply HF ``rope_scaling`` to the base frequencies.

    Supports ``linear`` (uniform 1/factor) and Llama-3.1's ``llama3`` rule:
    wavelengths shorter than ``original_max/high_freq_factor`` keep their
    frequency, longer than ``original_max/low_freq_factor`` are divided by
    ``factor``, and the band between interpolates smoothly. (The reference
    predates rope scaling — cache.rs:31-50 is the unscaled table only — but
    Llama-3.1 checkpoints require it.)
    """
    kind = scaling.get("rope_type", scaling.get("type"))
    if kind is None:
        raise ValueError(
            f"rope_scaling config has no 'rope_type'/'type' key: {scaling}"
        )
    factor = float(scaling["factor"])
    if kind == "linear":
        return inv_freq / factor
    if kind == "llama3":
        lo = float(scaling["low_freq_factor"])
        hi = float(scaling["high_freq_factor"])
        orig = float(scaling["original_max_position_embeddings"])
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = (orig / wavelen - lo) / (hi - lo)
        interp = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        scaled = jnp.where(wavelen > orig / lo, inv_freq / factor, interp)
        return jnp.where(wavelen < orig / hi, inv_freq, scaled)
    raise ValueError(f"unsupported rope_scaling type '{kind}'")


def rope_tables(head_dim: int, max_seq: int, theta: float, dtype=jnp.float32,
                scaling: dict | None = None):
    """Precompute ``cos/sin [max_seq, head_dim // 2]`` (cache.rs:31-50)."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if scaling is not None:
        inv_freq = _scale_inv_freq(inv_freq, scaling)
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [max_seq, head_dim/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    pos: jax.Array,
) -> jax.Array:
    """Rotate ``x [batch, heads, T, head_dim]`` for absolute positions
    ``pos .. pos+T`` (the reference's ``cosine/sine(index_pos, seq_len)``
    slice, cache.rs:71-78).

    ``pos`` may be a scalar (shared by all batch rows) or ``[batch]``
    (per-row positions — the multi-stream serving path)."""
    b, h, t, d = x.shape
    half = d // 2
    pos = jnp.asarray(pos, jnp.int32)
    if pos.ndim == 0:
        cos_t = jax.lax.dynamic_slice_in_dim(cos, pos, t, axis=0)
        sin_t = jax.lax.dynamic_slice_in_dim(sin, pos, t, axis=0)
        cos_t = cos_t[None, None, :, :]  # [1,1,T,half]
        sin_t = sin_t[None, None, :, :]
    else:
        def rows(table):  # [B, 1, T, half] — per-row table slices
            return jax.vmap(
                lambda p: jax.lax.dynamic_slice_in_dim(table, p, t, axis=0)
            )(pos)[:, None, :, :]

        cos_t, sin_t = rows(cos), rows(sin)
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos_t - x2 * sin_t, x1 * sin_t + x2 * cos_t], axis=-1
    )
    return rotated.astype(x.dtype)
