"""Seeded token sampling: temperature / top-k / top-p / repeat penalty.

Equivalent of the reference's sampling plane: `create_logits_processor`
(llama.rs:45-58) maps flags to candle's ``Sampling`` enum — temp<=0 → ArgMax,
else All / TopK / TopP / TopKThenTopP — seeded with ``--seed`` (default
299792458); repeat penalty over the last ``repeat_last_n`` tokens
(llama.rs:250-259, candle's ``apply_repeat_penalty``: positive scores divided
by the penalty, negative multiplied).

TPU-first design: the whole sampler is a pure jittable function so it fuses
into the decode-step program — no logits download to host per token (the
reference ships full logits to the CPU sampler every step, llama.rs:241-265).
The token history for the repeat penalty is a fixed-size device ring buffer
(static shape; empty slots hold -1), not a growing host list.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# Reference flag defaults (cake-core/src/lib.rs:15-64).
DEFAULT_SEED = 299792458
DEFAULT_TEMPERATURE = 1.0
DEFAULT_REPEAT_PENALTY = 1.1
DEFAULT_REPEAT_LAST_N = 128


@dataclasses.dataclass(frozen=True)
class SamplerSettings:
    temperature: float = DEFAULT_TEMPERATURE
    top_k: int | None = None
    top_p: float | None = None
    repeat_penalty: float = DEFAULT_REPEAT_PENALTY
    repeat_last_n: int = DEFAULT_REPEAT_LAST_N
    seed: int = DEFAULT_SEED
    # Static per-server token biasing: ((token_id, bias), ...) added to
    # the raw logits before everything else. A tuple (not a dict) so the
    # settings object stays hashable/static; the serve API normalizes
    # request dicts to this form. Empty = bit-identical no-op.
    logit_bias: tuple[tuple[int, float], ...] = ()

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def validate_logit_bias(settings: SamplerSettings, vocab_size: int) -> None:
    """Engine-construction check: biasing an out-of-range id would clamp
    in the scatter and silently bias the wrong token."""
    bad = [i for i, _ in settings.logit_bias
           if not 0 <= int(i) < vocab_size]
    if bad:
        raise ValueError(
            f"logit_bias token ids out of range [0, {vocab_size}): "
            f"{bad[:5]}")


def apply_repeat_penalty(
    logits: jax.Array,  # [vocab] f32
    history: jax.Array,  # [repeat_last_n] int32, -1 = empty slot
    penalty: float,
) -> jax.Array:
    """Penalize every token present in ``history`` (llama.rs:250-259)."""
    vocab = logits.shape[0]
    ids = jnp.where(history >= 0, history, vocab)  # park empties out of range
    present = jnp.zeros((vocab + 1,), jnp.bool_).at[ids].set(True)[:vocab]
    penalized = jnp.where(logits >= 0.0, logits / penalty, logits * penalty)
    return jnp.where(present, penalized, logits)


def _mask_top_k(logits: jax.Array, k: int) -> jax.Array:
    vals = jax.lax.top_k(logits, k)[0]
    return jnp.where(logits < vals[-1], NEG_INF, logits)


def _mask_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filter: keep the smallest prefix of the sorted distribution
    whose cumulative probability reaches ``p`` (candle TopP semantics)."""
    sorted_logits = jnp.sort(logits)[::-1]
    probs = jax.nn.softmax(sorted_logits)
    cum_exclusive = jnp.cumsum(probs) - probs
    keep = cum_exclusive < p  # always keeps at least the top token
    threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf))
    return jnp.where(logits < threshold, NEG_INF, logits)


def _bias_and_mask(
    logits: jax.Array,  # [vocab] f32
    settings: SamplerSettings,
    mask: jax.Array | None,  # [vocab] bool — True = token allowed
) -> jax.Array:
    """Logit-bias scatter + constraint mask, applied to the RAW logits
    before the penalty/temperature/nucleus transforms so the nucleus is
    computed over the *allowed* distribution (masking after top-p could
    strand the whole nucleus at -inf). Both are static no-ops when unset
    — the unconstrained path stays bit-identical to the pre-mask sampler
    (``jnp.where`` with an all-True mask returns logits unchanged, and
    neither branch traces at all when absent)."""
    if settings.logit_bias:
        ids = jnp.asarray([int(i) for i, _ in settings.logit_bias],
                          jnp.int32)
        vals = jnp.asarray([float(b) for _, b in settings.logit_bias],
                           jnp.float32)
        logits = logits.at[ids].add(vals)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    return logits


def processed_logits(
    logits: jax.Array,  # [vocab] f32
    history: jax.Array,  # [repeat_last_n] int32 ring buffer, -1 empty
    settings: SamplerSettings,
    mask: jax.Array | None = None,  # [vocab] bool constraint mask
) -> jax.Array:
    """The exact pre-categorical transform of :func:`sample_token` —
    logit bias -> constraint mask -> repeat penalty -> temperature ->
    top-k -> top-p — factored out so rejection-sampling speculation
    (runtime/speculative.py) evaluates the SAME distribution the plain
    sampler draws from (one policy source). Requires ``temperature > 0``."""
    assert not settings.greedy, "processed_logits is the sampled-path transform"
    logits = _bias_and_mask(logits, settings, mask)
    if settings.repeat_penalty != 1.0:
        logits = apply_repeat_penalty(logits, history, settings.repeat_penalty)
    logits = logits / jnp.float32(settings.temperature)
    if settings.top_k is not None:
        logits = _mask_top_k(logits, settings.top_k)
    if settings.top_p is not None:
        logits = _mask_top_p(logits, settings.top_p)
    return logits


def sample_token(
    logits: jax.Array,  # [vocab] f32
    key: jax.Array,
    history: jax.Array,  # [repeat_last_n] int32 ring buffer, -1 empty
    settings: SamplerSettings,
    mask: jax.Array | None = None,  # [vocab] bool — True = allowed
) -> jax.Array:
    """Pure sampling step -> scalar int32 token. Jittable; ``settings`` is
    static (mode selection mirrors llama.rs:45-58). ``mask`` is the
    constrained-decoding operand (constrain/): disallowed tokens sample
    with probability ~0 on every path, greedy included."""
    if settings.greedy:
        logits = _bias_and_mask(logits, settings, mask)
        if settings.repeat_penalty != 1.0:
            logits = apply_repeat_penalty(logits, history,
                                          settings.repeat_penalty)
        return jnp.argmax(logits).astype(jnp.int32)
    return jax.random.categorical(
        key, processed_logits(logits, history, settings, mask)
    ).astype(jnp.int32)


def sample_tokens(
    logits: jax.Array,  # [B, vocab] f32
    key: jax.Array,
    history: jax.Array,  # [B, repeat_last_n] int32
    settings: SamplerSettings,
) -> jax.Array:
    """Batched :func:`sample_token` -> [B] int32 (vmapped, per-row keys).
    At B == 1 the row uses ``key`` itself (no split) so the single-stream
    batched path reproduces :func:`sample_token` exactly."""
    b = logits.shape[0]
    keys = key[None] if b == 1 else jax.random.split(key, b)
    return jax.vmap(lambda l, k, h: sample_token(l, k, h, settings))(
        logits, keys, history
    )


def sample_tokens_keyed(
    logits: jax.Array,  # [B, vocab] f32
    row_keys: jax.Array,  # [B, 2] uint32 — one PRNG key per stream
    history: jax.Array,  # [B, repeat_last_n] int32
    settings: SamplerSettings,
    mask: jax.Array | None = None,  # [B, vocab] bool per-stream constraint
) -> jax.Array:
    """Batched sampling with *explicit per-row keys* -> [B] int32.

    Unlike :func:`sample_tokens` (which derives row keys from one key by
    batch-size-dependent splitting), each stream here owns its key, so a
    stream's stochastic output depends only on (its key, its logits, its
    history) — invariant to batch composition and mesh layout. This is the
    multi-stream serving contract: stream key = fold_in(base, stream_id),
    stepped by fold_in(. , token_index) in the caller/program. ``mask``
    is the per-stream constrained-decoding row (unconstrained rows pass
    all-True and sample bit-identically to the mask-less call)."""
    if mask is None:
        return jax.vmap(lambda l, k, h: sample_token(l, k, h, settings))(
            logits, row_keys, history
        )
    return jax.vmap(
        lambda l, k, h, m: sample_token(l, k, h, settings, mask=m)
    )(logits, row_keys, history, mask)


def unpack_mask_bits(bits: jax.Array, vocab: int) -> jax.Array:
    """``[..., ceil(V/8)] uint8`` little-endian packed masks -> ``[..., V]``
    bool. The in-program twin of ``np.unpackbits(..., bitorder='little')``
    (jnp has no unpackbits) — used by the compiled decode step on the
    rows it gathers from the device-resident constraint table."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    b = (bits[..., :, None] >> shifts) & jnp.uint8(1)
    flat = b.reshape(bits.shape[:-1] + (bits.shape[-1] * 8,))
    return flat[..., :vocab].astype(jnp.bool_)


def topk_logprobs(logits: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Top-``k`` of ``log_softmax(logits)`` -> (values, ids), computed on
    the RAW model logits (pre bias/mask/penalty — the model's own
    distribution, which is what an OpenAI-style ``logprobs`` field
    reports). Works on any leading batch shape."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    vals, ids = jax.lax.top_k(lp, k)
    return vals, ids.astype(jnp.int32)


def push_history(history: jax.Array, slot: jax.Array, token: jax.Array):
    """Write ``token`` into the ring buffer at ``slot % len`` and bump slot."""
    n = history.shape[0]
    idx = jnp.mod(slot, n)
    return history.at[idx].set(token), slot + 1


def push_history_batched(history: jax.Array, slot: jax.Array, tokens: jax.Array):
    """Batched ring-buffer write: ``history [B, N]``, ``tokens [B]``. ``slot``
    is a shared scalar (single-stream paths: every row at the same ring
    position) or ``[B]`` (multi-stream serving: each stream's ring is seeded
    with its own prompt tail, so slots differ per row). Single source of the
    ring semantics for the sharded decode path."""
    n = history.shape[1]
    slot = jnp.asarray(slot, jnp.int32)
    idx = jnp.mod(slot, n)
    if slot.ndim == 0:
        return history.at[:, idx].set(tokens), slot + 1
    b = history.shape[0]
    return history.at[jnp.arange(b), idx].set(tokens), slot + 1


def init_history(repeat_last_n: int) -> tuple[jax.Array, jax.Array]:
    return jnp.full((repeat_last_n,), -1, jnp.int32), jnp.zeros((), jnp.int32)
