"""Checkpoint fetching: populate a model dir from a remote or local source.

The reference master always pulls config/tokenizer/weights from the HF Hub —
even when ``--model`` points at a local checkout, it re-resolves
``meta-llama/Meta-Llama-3-8B`` on every run (the local-path loading is
commented out: `/root/reference/cake-core/src/cake/mod.rs:80-96`). That
forced-re-download quirk is deliberately NOT reproduced; instead fetching is
an explicit, idempotent convenience (CLI ``--fetch``):

- ``hf://org/name[@revision]`` — snapshot the inference files from the HF Hub
  into the model dir (requires ``huggingface_hub`` and network).
- ``file:///path`` or a plain directory path — copy from a local source
  (also the offline test plane).

Files already present in the destination are kept (pass ``force=True`` to
re-copy) — a fresh machine gets a one-command setup, a warm one stays warm.
"""

from __future__ import annotations

import fnmatch
import logging
import os
import shutil
from pathlib import Path

log = logging.getLogger("cake_tpu.fetch")

# the inference file set: model config + tokenizer + weights (+ shard index)
DEFAULT_PATTERNS = (
    "config.json",
    "tokenizer.json",
    "tokenizer_config.json",
    "*.safetensors",
    "model.safetensors.index.json",
)


def fetch_checkpoint(
    src: str,
    dest: str | Path,
    patterns: tuple[str, ...] = DEFAULT_PATTERNS,
    force: bool = False,
) -> Path:
    """Materialize checkpoint files from ``src`` into ``dest``; returns
    ``dest``. Idempotent: existing files are kept unless ``force``."""
    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)

    if src.startswith("hf://"):
        return _fetch_hub(src[len("hf://"):], dest, patterns, force)

    srcdir = Path(src[len("file://"):] if src.startswith("file://") else src)
    if not srcdir.is_dir():
        raise FileNotFoundError(f"checkpoint source {srcdir} is not a directory")
    copied = 0
    for f in sorted(srcdir.iterdir()):
        if not f.is_file():
            continue
        if not any(fnmatch.fnmatch(f.name, p) for p in patterns):
            continue
        target = dest / f.name
        if target.exists() and not force:
            log.debug("fetch: %s already present, keeping", f.name)
            continue
        shutil.copy2(f, target)
        copied += 1
    log.info("fetched %d file(s) from %s into %s", copied, srcdir, dest)
    return dest


_STAMP = ".cake_fetched"


def _files_complete(dest: Path) -> bool:
    """config + every shard the safetensors index names (or at least one
    monolithic safetensors file)."""
    if not (dest / "config.json").exists():
        return False
    idx = dest / "model.safetensors.index.json"
    if idx.exists():
        import json

        try:
            shards = set(json.loads(idx.read_text())["weight_map"].values())
        except (ValueError, KeyError):
            return False
        return bool(shards) and all((dest / s).exists() for s in shards)
    return any(dest.glob("*.safetensors"))


def _hub_populated(dest: Path, want: str) -> bool:
    """Is this dir a COMPLETE checkout of ``want`` (``repo`` or
    ``repo@rev``)? Completeness cannot be judged from files alone (a repo
    may legitimately lack tokenizer.json; a download may have died between
    shards), so a successful snapshot writes a stamp recording what it
    fetched; stamp match + config + every index-named shard => skip the
    network."""
    stamp = dest / _STAMP
    return (stamp.exists() and stamp.read_text().strip() == want
            and _files_complete(dest))


# config.json fields that identify a model — architecture/size plus the
# content-bearing fields that differ between same-architecture repos
# (e.g. Llama-3 base vs Instruct differ in eos_token_id). A fingerprint,
# not byte verification: same-config same-architecture finetunes are
# indistinguishable, which the caller warns about.
_IDENTITY_KEYS = (
    "architectures", "hidden_size", "num_hidden_layers",
    "num_attention_heads", "num_key_value_heads", "vocab_size",
    "intermediate_size", "bos_token_id", "eos_token_id", "rope_theta",
    "rope_scaling", "torch_dtype", "max_position_embeddings",
)


def _legacy_identity_ok(repo: str, revision: str | None,
                        dest: Path) -> bool | None:
    """Best-effort identity check of an UNSTAMPED complete checkout against
    the hub repo's config.json (one small file, not the weights). Returns
    True (fingerprint matches), False (different model — the dir must not be
    served/stamped as ``repo``), or None (hub unreachable: cannot judge)."""
    import json
    import tempfile

    try:
        from huggingface_hub import hf_hub_download

        with tempfile.TemporaryDirectory() as td:
            p = hf_hub_download(repo_id=repo, revision=revision,
                                filename="config.json", local_dir=td)
            hub_cfg = json.loads(Path(p).read_text())
        local_cfg = json.loads((dest / "config.json").read_text())
    except Exception as e:
        log.warning(
            "fetch: cannot verify unstamped checkout %s against %s (%s)",
            dest, repo, e,
        )
        return None
    return ({k: hub_cfg.get(k) for k in _IDENTITY_KEYS}
            == {k: local_cfg.get(k) for k in _IDENTITY_KEYS})


def _fetch_hub(repo: str, dest: Path, patterns: tuple[str, ...],
               force: bool) -> Path:
    revision = None
    if "@" in repo:
        repo, revision = repo.split("@", 1)
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - env without the hub client
        raise RuntimeError(
            "hf:// fetch requires the huggingface_hub package"
        ) from e
    want = f"{repo}@{revision}" if revision else repo
    # Only an unpinned fetch or an immutable commit-hash pin may skip the
    # hub on a stamp match; a branch/tag pin (movable) must always consult
    # the hub or it would track a stale tip forever.
    import re

    immutable = revision is None or bool(
        re.fullmatch(r"[0-9a-f]{7,40}", revision)
    )
    if not force and immutable and _hub_populated(dest, want):
        log.info("fetch: %s already populated (%s), skipping hub", dest, want)
        return dest
    # Pre-stamp-era checkout (no stamp, but config + tokenizer + weights all
    # present): verify it actually IS ``repo`` before stamping — an unstamped
    # complete checkout of a *different* model must not be silently served
    # and permanently mislabeled as the requested repo. The check costs one
    # small config.json download; if the hub is unreachable the checkout is
    # used for this run but left unstamped so the next online run verifies.
    # UNPINNED fetches only: the architecture fingerprint cannot tell
    # revisions of the same repo apart, so a commit-hash pin always goes to
    # the hub for the true pinned files.
    if (
        not force and revision is None and not (dest / _STAMP).exists()
        and (dest / "tokenizer.json").exists() and _files_complete(dest)
    ):
        verdict = _legacy_identity_ok(repo, revision, dest)
        if verdict is None:
            # Hub unreachable: identity cannot be judged. Default is
            # serve-with-a-warning (an offline pod must not be bricked by
            # a transient hub outage); CAKE_FETCH_STRICT=1 closes the
            # remaining serve-model-B-as-A window by refusing instead —
            # the posture for anything where mislabeling is worse than
            # unavailability.
            if os.environ.get("CAKE_FETCH_STRICT") == "1":
                raise RuntimeError(
                    f"{dest} is a complete but unstamped checkout and the "
                    f"hub is unreachable to verify it is {repo}; refusing "
                    "under CAKE_FETCH_STRICT=1 (unset it, or re-run online "
                    "once so the checkout can be verified and stamped)"
                )
            log.warning(
                "fetch: using unstamped checkout %s unverified (hub "
                "unreachable); not stamping (set CAKE_FETCH_STRICT=1 to "
                "refuse instead)", dest,
            )
            return dest
        if verdict:
            (dest / _STAMP).write_text(want)
            log.warning(
                "fetch: %s matches %s's config fingerprint and was stamped "
                "— this verifies architecture + tokenizer/rope config, not "
                "weight bytes; use --refetch if the dir might hold a "
                "same-config finetune", dest, want,
            )
            return dest
        raise RuntimeError(
            f"{dest} holds a complete checkpoint whose config.json does not "
            f"match {repo}; refusing to serve it as {want} (use --refetch "
            f"to overwrite it with the requested model)"
        )
    # About to mutate dest: a download dying halfway must not leave a
    # valid-looking stamp certifying a mixed checkout.
    (dest / _STAMP).unlink(missing_ok=True)
    snapshot_download(
        repo_id=repo,
        revision=revision,
        local_dir=str(dest),
        allow_patterns=list(patterns),
    )
    (dest / _STAMP).write_text(want)
    log.info("fetched %s from the HF Hub into %s", want, dest)
    return dest
