"""A small FIXED text corpus for realistic-acceptance speculation benches.

The r4 synthetic speculation rows ran a self-repeating token stream — the
n-gram proposer's best case. The honest companion measurement replays real
text (`bench.py` ``CAKE_BENCH_SPEC_CORPUS=1`` →
:func:`cake_tpu.runtime.speculative.spec_replay_fn`): acceptance then
reflects the repetition statistics of actual prose and code, not a
constructed loop. The reference has no speculation plane at all
(SURVEY.md §2) — this exists to keep OUR claimed numbers honest.

The text is embedded and versioned so the measurement is reproducible
across rounds: technical prose (the register of real serving traffic)
plus a code-flavored section (identifiers and syntax repeat the way real
completion contexts do). Byte-level tokenization keeps the stream
model-agnostic; byte text has the same kind of local n-gram structure a
subword stream has, just at a finer granularity, and the row is labeled
``corpus_bytes`` so it can never be mistaken for a subword-stream number.
"""

from __future__ import annotations

import numpy as np

_TEXT = """\
The scheduler assigns each incoming request to the first free slot in the
running batch. When no slot is free, the request waits in a first-in
first-out queue, and the batch continues to decode without interruption.
Each decode step advances every live stream by one token. When a stream
emits its end-of-sequence token, the slot is marked free and the next
queued request begins its prefill. The prefill runs one chunk per step so
the running batch never stalls behind a long prompt.

The cache holds one key and one value vector per token per layer. The
cache is allocated once at startup and never resized; each stream writes
its new key and value at its own position, and positions beyond the
stream's frontier are never read. When the window is full, the stream is
finished. The window may be shared across devices, in which case each
device owns a contiguous range of positions and writes only the slots in
its own range.

Throughput is measured in tokens per second across all live streams. The
time to first token is measured from the arrival of the request to the
emission of the first token, including any time spent waiting in the
queue. Both numbers are recorded with the device name and a timestamp so
that a later failure cannot erase the record of the measurement.

def admit(self, prompt, stream_id):
    ids = self.encode(prompt)
    slot = self.free_slot()
    if slot is None:
        raise RuntimeError("no free slot: every stream is still live")
    cache = self.staging_cache(len(ids))
    for pos in range(0, len(ids), self.chunk):
        logits, cache = self.prefill_chunk(ids, cache, pos)
    token = self.sample(logits, stream_id)
    self.splice(slot, cache, token)
    return slot, token

def step(self):
    if self.pending:
        self.admission_tick()
    tokens = self.decode_block(self.batch)
    for slot, token in enumerate(tokens):
        stream = self.streams[slot]
        if stream.live:
            stream.emit(token)
            if token in self.eos_ids or stream.window_full():
                stream.finish()
    return tokens

The admission path and the decode path share one compiled program cache.
A program is compiled the first time its shape is seen and reused for
every later dispatch with the same shape. Shapes are bucketed so that a
prompt of any length maps to one of a small number of compiled programs.
The first dispatch after startup therefore pays compilation once, and a
server warms the expected shapes before accepting traffic, so that no
request ever waits on the compiler.

When the batch is idle the decode block grows, and when a request is
waiting the block shrinks back, so that admission latency stays within
one small block while idle throughput approaches the fused maximum. The
block size is chosen from a ladder of compiled sizes; growth doubles the
size and a waiting request resets it to the base of the ladder.
"""


def corpus_bytes() -> bytes:
    """The fixed corpus as bytes (embedded, versioned with the repo)."""
    return _TEXT.encode("utf-8")


def corpus_tokens(vocab_size: int, n: int | None = None) -> np.ndarray:
    """Byte-level token ids for the corpus: ``1 + byte`` (0 is reserved as
    the pad/embed-clamp id), folded into ``[1, vocab_size)`` for tiny
    vocabularies. The corpus repeats end-to-end if ``n`` exceeds its
    length. NOTE: the n-gram proposer searches the WHOLE replayed prefix,
    so once the stream wraps, every trailing n-gram has an exact earlier
    occurrence and acceptance degenerates back to the synthetic best case
    — the honest-measurement window is a single pass (the bench caps its
    replay at one corpus length for exactly this reason)."""
    raw = np.frombuffer(corpus_bytes(), np.uint8).astype(np.int64)
    ids = 1 + (raw % (vocab_size - 1))
    if n is not None:
        reps = -(-n // len(ids))
        ids = np.tile(ids, reps)[:n]
    return ids.astype(np.int32)
