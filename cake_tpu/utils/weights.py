"""HF-checkpoint → params-pytree conversion and safetensors loading.

Equivalent of the reference's weight plane: mmap'd safetensors via the
`model.safetensors.index.json` weight_map (`utils/mod.rs:36-91`), with per-
layer tensors resolved by HF names (``model.layers.{i}.self_attn.q_proj`` …,
transformer.rs:30-38, attention.rs:92-109, mlp.rs:21-32).

Differences by design:

- HF stores linear weights ``[out, in]`` (torch Linear); the params pytree
  stores ``[in, out]`` so forward is ``x @ w`` with no transposes inside jit.
- Per-layer tensors are **stacked** into a single ``[num_layers, ...]`` array
  per weight name (the scan/pipeline layout, see models/llama.py).
- Loading accepts a layer *range* so a worker/pipeline stage loads only its
  topology-assigned slice (the reference worker loads only its own blocks,
  worker.rs:85-98; the splitter bundles are just a pre-filtered checkpoint).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Iterable

import jax.numpy as jnp
import numpy as np

# our stacked name -> (HF suffix, transpose?)
_LAYER_MAP = {
    "attn_norm": ("input_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
}

# q/k/v projection biases (Qwen2 family; HF llama-arch `attention_bias`)
_BIAS_MAP = {
    "bq": ("self_attn.q_proj.bias", False),
    "bk": ("self_attn.k_proj.bias", False),
    "bv": ("self_attn.v_proj.bias", False),
}
# o_proj bias: HF llama-arch `attention_bias: true` biases o_proj too
# (Qwen2 does not) — tracked separately so each checkpoint loads exactly
# the tensors it stores.
_O_BIAS = ("bo", ("self_attn.o_proj.bias", False))

# Mixtral MoE naming: w1 = gate proj, w3 = up proj, w2 = down proj; the
# router is `block_sparse_moe.gate`. Expert tensors are stacked over a new
# leading E axis per layer ([L, E, in, out] in the pytree).
_MOE_EXPERT_MAP = {
    "w_gate": "block_sparse_moe.experts.{e}.w1.weight",
    "w_up": "block_sparse_moe.experts.{e}.w3.weight",
    "w_down": "block_sparse_moe.experts.{e}.w2.weight",
}
_MOE_ROUTER = "block_sparse_moe.gate.weight"


def hf_layer_map(num_experts: int = 0, attention_bias: bool = False,
                 o_bias: bool = False) -> dict:
    """The per-layer name map for a model family (the dense/bias-free base
    plus q/k/v biases and, for HF llama-arch ``attention_bias`` checkpoints,
    the o_proj bias; Mixtral expert tensors are handled separately because
    they stack over an expert axis)."""
    m = dict(_LAYER_MAP)
    if attention_bias:
        m.update(_BIAS_MAP)
    if o_bias:
        m[_O_BIAS[0]] = _O_BIAS[1]
    if num_experts:
        for k in ("w_gate", "w_up", "w_down"):
            del m[k]
    return m


def params_from_hf_tensors(
    get: Callable[[str], np.ndarray],
    num_layers: int,
    dtype="bfloat16",
    layer_range: tuple[int, int] | None = None,
    tie_word_embeddings: bool = False,
    include_embed: bool = True,
    include_head: bool = True,
    quantize: str | None = None,
    prequantized: bool = False,
    num_experts: int = 0,
    attention_bias: bool = False,
    o_bias: bool = False,
) -> dict:
    """Build the params pytree from a tensor lookup ``get(hf_name)``.

    ``num_experts``/``attention_bias`` select the model family's extra
    tensors (Mixtral routed experts / Qwen2 q-k-v biases — see
    ``hf_layer_map``); pass them from
    ``config.num_local_experts``/``config.attention_bias``.

    ``layer_range=(lo, hi)`` loads only blocks ``lo..hi-1`` (still stacked,
    dense from 0) — the worker/stage path.

    ``quantize="int8"``/``"int4"``/``"int4:gN"`` quantizes every linear *on
    the host as it streams in* (symmetric per-output-channel, ops.quant;
    int4 is packed two-per-byte; ``:gN`` selects N-row group-wise scales,
    int4's accuracy tier) — the bf16 weights never reach the device, so
    peak HBM is the quantized bytes. Norms and the embedding stay in
    ``dtype``. ``prequantized=True`` (a checkpoint written by
    tools/quantize_model: ``<name>.q8``/``.q4`` + ``<name>.scale`` tensors)
    reads the stored quantized bytes directly — a fraction of the IO, zero
    quantize compute; a grouped checkpoint's scale shape carries its own
    grouping, so plain ``"int4"`` loads it."""
    from cake_tpu.ops.quant import (
        LAYER_LINEARS,
        Quantized4Linear,
        QuantizedLinear,
        parse_quant_spec,
        quantize_linear4_np,
        quantize_linear_np,
    )

    tier, gsize = parse_quant_spec(quantize)
    if prequantized and tier is None:
        raise ValueError(
            "prequantized=True requires quantize='int8' or 'int4'"
        )

    lo, hi = layer_range or (0, num_layers)
    dt = jnp.dtype(dtype)

    _det: list = []  # lazy one-slot cache for _stored_group()

    def _stored_group() -> int | None:
        """The group size a pre-quantized int4 checkpoint was written at
        (None = per-channel), read off a stored scale's shape. Lazy: only
        probed when a tied head must match the layers' tier or an explicit
        :gN spec needs validation."""
        if not _det:
            try:
                name = f"model.layers.{lo}.self_attn.q_proj.weight"
                s = np.asarray(get(f"{name}.scale"))
                if s.ndim == 2:
                    in_dim = 2 * np.asarray(get(f"{name}.q4")).shape[1]
                    _det.append(in_dim // s.shape[0])
                else:
                    _det.append(None)
            except KeyError:
                _det.append(None)
        return _det[0]

    if prequantized and tier == "int4" and gsize is not None:
        stored = _stored_group()
        if stored != gsize:
            raise ValueError(
                f"checkpoint stores "
                f"{'group_size=' + str(stored) if stored else 'per-channel'}"
                f" int4, but quantize spec asked for g{gsize}"
            )

    def get_quant(name: str) -> tuple[np.ndarray, np.ndarray]:
        """(q [in, out] or qp [in/2, out] int8, scale f32) for one linear —
        stored pre-quantized or quantized here on the fly (a tied head
        reads the un-quantized embedding even in a pre-quantized
        checkpoint, at the checkpoint's OWN group size so both loaders
        stay bit-equal)."""
        if prequantized:
            suffix = ".q8" if tier == "int8" else ".q4"
            try:
                # stored in the HF [out, in] orientation (int4: [out, in/2]
                # packed along in) — transpose to the pytree layout; the
                # scale is stored in the pytree layout already
                return (np.asarray(get(f"{name}{suffix}")).T,
                        np.asarray(get(f"{name}.scale")))
            except KeyError:
                pass
        if tier == "int8":
            return quantize_linear_np(np.asarray(get(name)).T)
        g_eff = _stored_group() if prequantized else gsize
        return quantize_linear4_np(np.asarray(get(name)).T, group_size=g_eff)

    qcls = QuantizedLinear if tier == "int8" else Quantized4Linear

    if num_experts and tier == "int4":
        from cake_tpu.ops.quant import reject_int4_moe

        reject_int4_moe()

    params: dict = {}
    if hi > lo:
        layers = {}
        for ours, (suffix, transpose) in hf_layer_map(
            num_experts, attention_bias, o_bias
        ).items():
            do_quant = tier is not None and ours in LAYER_LINEARS
            per, scales = [], []
            for i in range(lo, hi):
                name = f"model.layers.{i}.{suffix}"
                if do_quant:
                    q, s = get_quant(name)
                    per.append(q)
                    scales.append(s)
                else:
                    w = np.asarray(get(name))
                    per.append(w.T if transpose else w)
            if do_quant:
                layers[ours] = qcls(
                    jnp.asarray(np.stack(per)),
                    jnp.asarray(np.stack(scales)),
                )
            else:
                layers[ours] = jnp.asarray(np.stack(per)).astype(dt)
        if num_experts:
            per_r = [
                np.asarray(get(f"model.layers.{i}.{_MOE_ROUTER}")).T
                for i in range(lo, hi)
            ]
            layers["router"] = jnp.asarray(np.stack(per_r)).astype(dt)
            for ours, pattern in _MOE_EXPERT_MAP.items():
                if tier == "int8":
                    # per-expert per-output-channel int8 (through get_quant
                    # so pre-quantized .q8 expert tensors load identically)
                    per_q, per_s = [], []
                    for i in range(lo, hi):
                        qs = [
                            get_quant(
                                f"model.layers.{i}.{pattern.format(e=e)}")
                            for e in range(num_experts)
                        ]
                        per_q.append(np.stack([q for q, _ in qs]))
                        per_s.append(np.stack([s for _, s in qs]))
                    layers[ours] = qcls(
                        jnp.asarray(np.stack(per_q)),  # [L, E, in, out]
                        jnp.asarray(np.stack(per_s)),  # [L, E, out]
                    )
                    continue
                per = [
                    np.stack([
                        np.asarray(
                            get(f"model.layers.{i}.{pattern.format(e=e)}")
                        ).T
                        for e in range(num_experts)
                    ])
                    for i in range(lo, hi)
                ]  # [L, E, in, out]
                layers[ours] = jnp.asarray(np.stack(per)).astype(dt)
        params["layers"] = layers
    if include_embed:
        params["embed"] = jnp.asarray(np.asarray(get("model.embed_tokens.weight"))).astype(dt)
    if include_head:
        params["norm_f"] = jnp.asarray(np.asarray(get("model.norm.weight"))).astype(dt)
        head_name = (
            "model.embed_tokens.weight" if tie_word_embeddings else "lm_head.weight"
        )
        if tier is not None:
            q, s = get_quant(head_name)
            params["lm_head"] = qcls(jnp.asarray(q), jnp.asarray(s))
        else:
            params["lm_head"] = jnp.asarray(np.asarray(get(head_name)).T).astype(dt)
    return params


def load_safetensors_index(model_dir: str | Path) -> dict[str, Path]:
    """Resolve tensor name -> shard file from ``model.safetensors.index.json``
    (utils/mod.rs:36-91), falling back to a single ``model.safetensors`` (the
    splitter also writes ``reduced.safetensors``)."""
    model_dir = Path(model_dir)
    index = model_dir / "model.safetensors.index.json"
    if index.exists():
        weight_map = json.loads(index.read_text())["weight_map"]
        return {name: model_dir / fname for name, fname in weight_map.items()}
    for candidate in ("model.safetensors", "reduced.safetensors"):
        f = model_dir / candidate
        if f.exists():
            from safetensors import safe_open

            with safe_open(f, framework="np") as sf:
                return {name: f for name in sf.keys()}
    raise FileNotFoundError(f"no safetensors index or file under {model_dir}")


def detect_tied_head(name_to_file: dict, model_dir, logger_name: str) -> bool:
    """True when the checkpoint stores NO lm_head.weight (plain or
    pre-quantized ``.q8``/``.q4``) — such a checkpoint can only be tied
    (Gemma, Llama-3.2-1B, Qwen2-small). Shared by both loaders; logs when
    it fires so an untied checkpoint with a broken index stays
    diagnosable."""
    import logging

    if any(n in name_to_file for n in (
            "lm_head.weight", "lm_head.weight.q8", "lm_head.weight.q4")):
        return False
    logging.getLogger(logger_name).info(
        "no stored lm_head.weight in %s — loading a tied head (the "
        "embedding); if this checkpoint is supposed to be untied, its "
        "index is incomplete", model_dir,
    )
    return True


def detect_family(name_to_file: dict) -> tuple[int, bool, bool]:
    """Detect a checkpoint's family tensors from its name index:
    ``(num_experts, attention_bias, o_bias)``. Zero/False for the Llama
    base. Keyed off the stored names themselves so no call site can
    silently drop a family's tensors by forgetting a flag."""
    import re

    bias = any(n.endswith("self_attn.q_proj.bias") for n in name_to_file)
    o_bias = any(n.endswith("self_attn.o_proj.bias") for n in name_to_file)
    experts = set()
    pat = re.compile(r"block_sparse_moe\.experts\.(\d+)\.")
    for n in name_to_file:
        m = pat.search(n)
        if m:
            experts.add(int(m.group(1)))
    return len(experts), bias, o_bias


def is_prequantized(name_to_file: dict) -> str | None:
    """Which tier tools/quantize_model wrote this checkpoint at: ``"int8"``
    (``.q8`` tensors), ``"int4"`` (``.q4``), or None (not pre-quantized).
    Truthy exactly when pre-quantized, so boolean use keeps working."""
    if any(n.endswith(".q8") for n in name_to_file):
        return "int8"
    if any(n.endswith(".q4") for n in name_to_file):
        return "int4"
    return None


def check_prequantized(name_to_file: dict, quantize: str | None) -> bool:
    """Detect a pre-quantized checkpoint and validate the requested load
    mode against it (shared by the host and direct-to-mesh loaders)."""
    from cake_tpu.ops.quant import parse_quant_spec

    pre = is_prequantized(name_to_file)
    tier, _ = parse_quant_spec(quantize)
    if pre and tier != pre:
        raise ValueError(
            f"this checkpoint is pre-quantized ({pre} .q8/.q4/.scale "
            f"tensors); load it with quantize='{pre}' (--quantize {pre})"
        )
    return bool(pre)


def load_llama_params(
    model_dir: str | Path,
    num_layers: int,
    dtype="bfloat16",
    layer_range: tuple[int, int] | None = None,
    tie_word_embeddings: bool = False,
    include_embed: bool = True,
    include_head: bool = True,
    quantize: str | None = None,
    num_experts: int | None = None,
    attention_bias: bool | None = None,
    o_bias: bool | None = None,
) -> dict:
    """Load a Llama-family checkpoint directory into the params pytree.

    Shards are opened lazily with ``safetensors.safe_open`` (zero-copy mmap,
    the equivalent of VarBuilder::from_mmaped_safetensors, cake/mod.rs:100-101)
    and only requested tensors are materialized — a worker loading 4 of 32
    layers reads only those bytes. Pre-quantized checkpoints
    (tools/quantize_model) are detected automatically, and so are the model
    family's extra tensors (Qwen2 q/k/v biases, Mixtral experts) via
    :func:`detect_family` — pass ``num_experts``/``attention_bias`` only to
    override the detection.
    """
    from safetensors import safe_open

    name_to_file = load_safetensors_index(model_dir)
    det_experts, det_bias, det_o = detect_family(name_to_file)
    if num_experts is None:
        num_experts = det_experts
    if attention_bias is None:
        attention_bias = det_bias
    if o_bias is None:
        o_bias = det_o
    if (include_head and not tie_word_embeddings
            and detect_tied_head(name_to_file, model_dir,
                                 "cake_tpu.weights")):
        tie_word_embeddings = True
    handles: dict[Path, object] = {}

    def get(name: str) -> np.ndarray:
        f = name_to_file[name]
        if f not in handles:
            handles[f] = safe_open(f, framework="np")
        return handles[f].get_tensor(name)

    try:
        return params_from_hf_tensors(
            get,
            num_layers,
            dtype=dtype,
            layer_range=layer_range,
            tie_word_embeddings=tie_word_embeddings,
            include_embed=include_embed,
            include_head=include_head,
            quantize=quantize,
            prequantized=check_prequantized(name_to_file, quantize),
            num_experts=num_experts,
            attention_bias=attention_bias,
            o_bias=o_bias,
        )
    finally:
        for h in handles.values():
            if hasattr(h, "close"):
                h.close()
            elif hasattr(h, "__exit__"):
                h.__exit__(None, None, None)


def save_llama_params(params: dict, model_dir: str | Path, num_layers: int | None = None):
    """Write a params pytree back to HF-format safetensors (test fixtures and
    the splitter round-trip). Inverse of :func:`load_llama_params`."""
    from safetensors.numpy import save_file

    model_dir = Path(model_dir)
    model_dir.mkdir(parents=True, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}
    if "embed" in params:
        tensors["model.embed_tokens.weight"] = np.asarray(params["embed"])
    if "norm_f" in params:
        tensors["model.norm.weight"] = np.asarray(params["norm_f"])
        tensors["lm_head.weight"] = np.asarray(params["lm_head"]).T
    L = params["layers"]["wq"].shape[0] if num_layers is None else num_layers
    layers = params["layers"]
    moe = "router" in layers
    fam_map = hf_layer_map(
        num_experts=layers["w_gate"].shape[1] if moe else 0,
        attention_bias="bq" in layers,
        o_bias="bo" in layers,
    )
    for ours, (suffix, transpose) in fam_map.items():
        stacked = np.asarray(layers[ours])
        for i in range(L):
            w = stacked[i]
            tensors[f"model.layers.{i}.{suffix}"] = w.T if transpose else np.ascontiguousarray(w)
    if moe:
        router = np.asarray(layers["router"])  # [L, H, E]
        E = router.shape[-1]
        for i in range(L):
            tensors[f"model.layers.{i}.{_MOE_ROUTER}"] = router[i].T
        # materialize ONE expert stack to host at a time (for a
        # Mixtral-scale pytree each [L, E, in, out] leaf is tens of GB;
        # holding all three at once would triple peak host RAM)
        for ours, pattern in _MOE_EXPERT_MAP.items():
            stacked = np.asarray(layers[ours])
            for i in range(L):
                for e in range(E):
                    # real copy (not a .T view) so `del stacked` frees the
                    # stack before the next one materializes
                    tensors[
                        f"model.layers.{i}.{pattern.format(e=e)}"
                    ] = np.ascontiguousarray(stacked[i, e].T)
            del stacked

    out = model_dir / "model.safetensors"
    # bf16 numpy isn't universally supported by safetensors.numpy; store f32
    tensors = {k: np.ascontiguousarray(v, dtype=np.float32) for k, v in tensors.items()}
    save_file(tensors, out)
    index = {
        "metadata": {"total_size": int(sum(v.nbytes for v in tensors.values()))},
        "weight_map": {k: "model.safetensors" for k in tensors},
    }
    (model_dir / "model.safetensors.index.json").write_text(json.dumps(index))
    return out
