"""Process/device memory reporting.

Equivalent of the reference's RSS logging at every phase via memory_stats +
human_bytes (cake/mod.rs:67-73, master.rs:25-28, worker.rs:102-106,
llama.rs:203-206), plus TPU-side HBM stats the reference has no analog for.
"""

from __future__ import annotations

import resource


def rss_bytes() -> int:
    """Peak resident set size of this process (linux: ru_maxrss is KiB)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def human_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} PiB"


def hbm_budget(
    config,
    num_stages: int = 1,
    tp: int = 1,
    sp: int = 1,
    ep: int = 1,
    max_seq: int | None = None,
    batch: int = 1,
    quant: str | None = None,
    cache_bytes_per_el: int = 2,
) -> dict:
    """Per-chip HBM budget (bytes) for a (stage, tp, sp, ep) mesh layout.

    Mirrors the sharding actually used (parallel/mesh.py param_specs +
    CACHE_SPEC): stacked layers shard over stage, linear in/out features over
    tp, KV sequence over sp and kv-heads over tp; **embed is replicated** on
    every chip and lm_head shards its vocab over tp. ``quant='int8'`` prices
    the linears at 1 byte + f32 scales, ``quant='int4'`` at half a byte
    (packed) + f32 scales (ops/quant.py layouts).

    This is the planning arithmetic behind BASELINE.md configs 4/5 (70B on
    v5e-16): it makes the "int8 is load-bearing, not optional" claim of
    SURVEY.md §7 checkable.
    """
    c = config
    el = 2 if c.dtype in ("bfloat16", "float16") else 4
    group = None
    if quant:
        from cake_tpu.ops.quant import parse_quant_spec

        quant, group = parse_quant_spec(quant)
    if quant == "int8":
        lin_el, scale_el = 1, 4
    elif quant == "int4":
        lin_el, scale_el = 0.5, 4  # packed two-per-byte (ops/quant.py int4)
    else:
        lin_el, scale_el = el, 0
    S = max_seq or c.max_seq_len
    d = c.head_dim

    # per-layer linear params (full, unsharded). MoE (Mixtral families):
    # the MLP triplet multiplies by num_local_experts and its expert axis
    # shards over ep (mesh.param_specs P(STAGE, EP, ., TP)); the router is
    # tiny and replicated. ep divides ONLY the expert stacks — attention
    # and norms are replicated across ep ranks.
    n_exp = getattr(c, "num_local_experts", 0) or 0
    qkv_out = (c.num_attention_heads + 2 * c.num_key_value_heads) * d
    lin = c.hidden_size * qkv_out  # wq+wk+wv
    lin += c.num_attention_heads * d * c.hidden_size  # wo
    mlp = 3 * c.hidden_size * c.intermediate_size  # gate/up/down
    mlp_out = 2 * c.intermediate_size + c.hidden_size
    if n_exp:
        # integer division is exact here: validate_shardable guarantees
        # ep | n_exp, so the byte counts stay integral for MoE configs
        mlp = mlp * n_exp // ep
        mlp_out = mlp_out * n_exp // ep
    lin += mlp
    lin_out = qkv_out + c.hidden_size + mlp_out
    norms = 2 * c.hidden_size
    if n_exp:
        # per-layer router [H, E], replicated, full precision — priced with
        # the norms (both ride the `* el` term below)
        norms += c.hidden_size * n_exp

    layers_per_chip = c.num_hidden_layers / num_stages
    # scale elements: one per output channel (per-channel), or one per
    # (in-group, channel) = weight elements / group_size (grouped int4 —
    # e.g. g=128 on 70B w_down stores 224 scales per channel, ~6% of the
    # int4 weight bytes; a near-limit config must price them)
    layer_scales = lin / group if group else lin_out
    layer_bytes = layers_per_chip * (
        lin / tp * lin_el + layer_scales / tp * scale_el + norms * el
    )
    embed_bytes = c.vocab_size * c.hidden_size * el  # replicated
    head_scales = (
        c.hidden_size * c.vocab_size / group if group else c.vocab_size
    )
    head_bytes = (
        c.hidden_size * c.vocab_size / tp * lin_el
        + (head_scales / tp) * scale_el
        + c.hidden_size * el
    )
    kv_bytes = (
        layers_per_chip * batch * (c.num_key_value_heads / tp)
        * (S / sp) * d * 2 * cache_bytes_per_el
    )
    if cache_bytes_per_el == 1:
        # int8 KV (kvcache.QuantizedKV): one f32 scale per slot per head
        kv_bytes += (
            layers_per_chip * batch * (c.num_key_value_heads / tp)
            * (S / sp) * 2 * 4
        )
    total = layer_bytes + embed_bytes + head_bytes + kv_bytes
    return {
        "layers": int(layer_bytes),
        "embed_replicated": int(embed_bytes),
        "head": int(head_bytes),
        "kv_cache": int(kv_bytes),
        "total": int(total),
    }


def memory_report() -> str:
    parts = [f"rss {human_bytes(rss_bytes())}"]
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_in_use" in stats:
            parts.append(f"hbm {human_bytes(stats['bytes_in_use'])}")
            if "bytes_limit" in stats:
                parts.append(f"of {human_bytes(stats['bytes_limit'])}")
    except Exception:
        pass
    return ", ".join(parts)
