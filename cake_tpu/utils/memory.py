"""Process/device memory reporting.

Equivalent of the reference's RSS logging at every phase via memory_stats +
human_bytes (cake/mod.rs:67-73, master.rs:25-28, worker.rs:102-106,
llama.rs:203-206), plus TPU-side HBM stats the reference has no analog for.
"""

from __future__ import annotations

import resource


def rss_bytes() -> int:
    """Peak resident set size of this process (linux: ru_maxrss is KiB)."""
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def human_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} PiB"


def memory_report() -> str:
    parts = [f"rss {human_bytes(rss_bytes())}"]
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_in_use" in stats:
            parts.append(f"hbm {human_bytes(stats['bytes_in_use'])}")
            if "bytes_limit" in stats:
                parts.append(f"of {human_bytes(stats['bytes_limit'])}")
    except Exception:
        pass
    return ", ".join(parts)
