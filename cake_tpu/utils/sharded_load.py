"""Direct-to-mesh checkpoint loading: each shard's bytes, nothing more.

The reference worker loads ONLY its topology-assigned blocks' weights
(`cake-core/src/cake/worker.rs:85-98`); this is the mesh-path equivalent.
:func:`load_llama_params_on_mesh` assembles the sharded params pytree with
``jax.make_array_from_callback``: every *addressable* shard's bytes are read
straight out of the mmap'd safetensors (``safe_open(...).get_slice``) and
placed on its device — there is never a full-model host copy, and on a
multi-host pod each host reads only the layer ranges its local devices'
stages own. Contrast ``load_llama_params`` + ``shard_params``, which builds
the entire pytree on host first (~70 GB host RAM for 70B int8, with
full-model quantize time, on *every* host).

Quantize-on-load (``quantize="int8"``) stays shard-local where the math
allows: column-parallel linears (wq/wk/wv/w_gate/w_up, and lm_head) shard
out-features, and the per-output-channel scale depends only on the full
in-axis — present in every shard — so quantizing the column slice equals
quantizing the full weight and slicing. Row-parallel linears (wo/w_down)
shard the in-axis, so their callbacks read the full ``[in, out]`` layer
weight, quantize, and slice — one layer at a time, never the whole stage.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cake_tpu.models.config import LlamaConfig
from cake_tpu.parallel.mesh import STAGE, TP
from cake_tpu.utils.weights import _LAYER_MAP, load_safetensors_index

# column-parallel: out-features shard over tp, in-axis full per shard
_COL_PARALLEL = ("wq", "wk", "wv", "w_gate", "w_up")
# row-parallel: in-features shard over tp (scale needs the full in-axis)
_ROW_PARALLEL = ("wo", "w_down")


class CheckpointReader:
    """Sliced mmap reads over a safetensors checkpoint, with byte
    accounting (``bytes_read``) so tests can assert a stage loads ~1/S of
    the model."""

    def __init__(self, model_dir):
        self.name_to_file = load_safetensors_index(model_dir)
        self._handles: dict = {}
        self.bytes_read = 0

    def _slice(self, name: str):
        from safetensors import safe_open

        f = self.name_to_file[name]
        h = self._handles.get(f)
        if h is None:
            h = self._handles[f] = safe_open(f, framework="np")
        return h.get_slice(name)

    def read1d(self, name: str, sl: slice = slice(None)) -> np.ndarray:
        out = np.asarray(self._slice(name)[sl])
        self.bytes_read += out.nbytes
        return out

    def read2d(self, name: str, rows: slice, cols: slice,
               transpose: bool) -> np.ndarray:
        """Logical ``[rows, cols]`` slice; ``transpose=True`` when the
        checkpoint stores the torch ``[out, in]`` layout and the logical
        layout is ``[in, out]``."""
        if transpose:
            out = np.asarray(self._slice(name)[cols, rows]).T
        else:
            out = np.asarray(self._slice(name)[rows, cols])
        self.bytes_read += out.nbytes
        return out

    def close(self) -> None:
        for h in self._handles.values():
            if hasattr(h, "close"):
                h.close()
        self._handles.clear()


def _np_dtype(dtype) -> np.dtype:
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16 if str(dtype) == "bfloat16" else dtype)


def _memo(cb):
    cache: dict = {}

    def wrapped(index):
        key = tuple((s.start, s.stop, s.step) for s in index)
        if key not in cache:
            cache[key] = cb(index)
        return cache[key]

    return wrapped


def _assemble(shape, mesh: Mesh, spec: P, cb):
    return jax.make_array_from_callback(
        tuple(shape), NamedSharding(mesh, spec), _memo(cb)
    )


def load_llama_params_on_mesh(
    model_dir,
    config: LlamaConfig,
    mesh: Mesh,
    quantize: str | None = None,
    tie_word_embeddings: bool = False,
) -> dict:
    """Load a checkpoint directory directly into mesh-sharded global arrays
    (the layout of :func:`cake_tpu.parallel.mesh.param_specs`). Bitwise
    equal to ``shard_params(load_llama_params(...), mesh)`` — tested — but
    reads only addressable shards' bytes and holds at most one layer weight
    of host scratch at a time."""
    if quantize not in (None, "int8"):
        raise ValueError(f"unsupported quantize={quantize!r}")
    from cake_tpu.ops.quant import QuantizedLinear, quantize_linear_np
    from cake_tpu.utils.weights import check_prequantized

    reader = CheckpointReader(model_dir)
    prequantized = check_prequantized(reader.name_to_file, quantize)
    dt = _np_dtype(config.dtype)
    L = config.num_hidden_layers
    h = config.hidden_size
    d = h // config.num_attention_heads
    shapes = {
        "attn_norm": (L, h),
        "wq": (L, h, config.num_attention_heads * d),
        "wk": (L, h, config.num_key_value_heads * d),
        "wv": (L, h, config.num_key_value_heads * d),
        "wo": (L, config.num_attention_heads * d, h),
        "mlp_norm": (L, h),
        "w_gate": (L, h, config.intermediate_size),
        "w_up": (L, h, config.intermediate_size),
        "w_down": (L, config.intermediate_size, h),
    }

    def norm_cb(suffix):
        def cb(index):
            lsl, _ = index
            lo, hi, _ = lsl.indices(L)
            return np.stack([
                reader.read1d(f"model.layers.{i}.{suffix}")
                for i in range(lo, hi)
            ]).astype(dt)

        return cb

    def linear_cb(suffix, transpose):
        def cb(index):
            lsl, rsl, csl = index
            lo, hi, _ = lsl.indices(L)
            return np.stack([
                reader.read2d(f"model.layers.{i}.{suffix}", rsl, csl,
                              transpose)
                for i in range(lo, hi)
            ]).astype(dt)

        return cb

    # Per-(tensor, column-range) scale memo. Scales are tiny ([out] f32 per
    # layer) but cost a weight read to compute — the memo means each weight
    # is read for quantization context exactly once per distinct need:
    # row-parallel shards read one full weight for the scale, then only
    # their own row slices; the scale leaf's callbacks are pure memo hits.
    scale_memo: dict[tuple, np.ndarray] = {}

    def _key(name: str, csl: slice) -> tuple:
        return (name, csl.start, csl.stop)

    def _scale(name: str, transpose: bool, csl: slice) -> np.ndarray:
        """Scale for columns ``csl`` (full in-axis — exact by construction)."""
        key = _key(name, csl)
        if key not in scale_memo:
            full = _key(name, slice(None))
            if full in scale_memo:
                scale_memo[key] = scale_memo[full][csl]
            else:
                w = reader.read2d(name, slice(None), csl, transpose)
                scale_memo[key] = quantize_linear_np(w)[1]
        return scale_memo[key]

    def quant_q_cb(suffix, transpose, row_parallel):
        def cb(index):
            lsl, rsl, csl = index
            lo, hi, _ = lsl.indices(L)
            per = []
            for i in range(lo, hi):
                name = f"model.layers.{i}.{suffix}"
                if prequantized:
                    # stored int8 in the HF [out, in] orientation: read
                    # exactly this shard's slice, no quantize compute
                    per.append(reader.read2d(f"{name}.q8", rsl, csl, True))
                elif row_parallel:
                    # scale needs the full in-axis (memoized: one full read
                    # per layer, shared across tp shards and the scale
                    # leaf); the int8 bytes then need only this shard's rows
                    s = _scale(name, transpose, csl)
                    w = reader.read2d(name, rsl, csl, transpose)
                    per.append(np.clip(
                        np.round(np.asarray(w, np.float32) / s),
                        -127, 127).astype(np.int8))
                else:
                    q, s = quantize_linear_np(
                        reader.read2d(name, rsl, csl, transpose))
                    scale_memo.setdefault(_key(name, csl), s)
                    per.append(q)
            return np.stack(per)

        return cb

    def quant_scale_cb(suffix, transpose):
        def cb(index):
            lsl, csl = index
            lo, hi, _ = lsl.indices(L)
            if prequantized:
                return np.stack([
                    reader.read1d(f"model.layers.{i}.{suffix}.scale", csl)
                    for i in range(lo, hi)
                ])
            return np.stack([
                _scale(f"model.layers.{i}.{suffix}", transpose, csl)
                for i in range(lo, hi)
            ])

        return cb

    try:
        layers: dict = {}
        for ours, (suffix, transpose) in _LAYER_MAP.items():
            shape = shapes[ours]
            if len(shape) == 2:
                layers[ours] = _assemble(shape, mesh, P(STAGE, None),
                                         norm_cb(suffix))
                continue
            spec = (P(STAGE, TP, None) if ours in _ROW_PARALLEL
                    else P(STAGE, None, TP))
            if quantize == "int8":
                scale_spec = (P(STAGE, None) if ours in _ROW_PARALLEL
                              else P(STAGE, TP))
                layers[ours] = QuantizedLinear(
                    q=_assemble(shape, mesh, spec,
                                quant_q_cb(suffix, transpose,
                                           ours in _ROW_PARALLEL)),
                    scale=_assemble((L, shape[2]), mesh, scale_spec,
                                    quant_scale_cb(suffix, transpose)),
                )
            else:
                layers[ours] = _assemble(shape, mesh, spec,
                                         linear_cb(suffix, transpose))

        embed_name = "model.embed_tokens.weight"
        head_name = embed_name if tie_word_embeddings else "lm_head.weight"
        params: dict = {"layers": layers}
        params["embed"] = _assemble(
            (config.vocab_size, h), mesh, P(None, None),
            lambda index: reader.read2d(embed_name, index[0], index[1],
                                        False).astype(dt),
        )
        params["norm_f"] = _assemble(
            (h,), mesh, P(None),
            lambda index: reader.read1d("model.norm.weight",
                                        index[0]).astype(dt),
        )
        if quantize == "int8":
            # lm_head is column-parallel over vocab: shard-local quantize
            # is exact (full in-axis per shard); its scales ride the same
            # memo so the scale leaf re-reads nothing. A tied head has no
            # stored .q8 (the embedding stays full-precision) and falls
            # back to on-the-fly quantize.
            head_prequant = (prequantized
                             and f"{head_name}.q8" in reader.name_to_file)

            def head_q(index):
                if head_prequant:
                    return reader.read2d(f"{head_name}.q8", index[0],
                                         index[1], True)
                q, s = quantize_linear_np(
                    reader.read2d(head_name, index[0], index[1], True))
                scale_memo.setdefault(_key(head_name, index[1]), s)
                return q

            def head_scale(index):
                if head_prequant:
                    return reader.read1d(f"{head_name}.scale", index[0])
                return _scale(head_name, True, index[0])

            params["lm_head"] = QuantizedLinear(
                q=_assemble((h, config.vocab_size), mesh, P(None, TP),
                            head_q),
                scale=_assemble((config.vocab_size,), mesh, P(TP),
                                head_scale),
            )
        else:
            params["lm_head"] = _assemble(
                (h, config.vocab_size), mesh, P(None, TP),
                lambda index: reader.read2d(head_name, index[0], index[1],
                                            True).astype(dt),
            )
        return params
    finally:
        reader.close()
