"""Direct-to-mesh checkpoint loading: each shard's bytes, nothing more.

The reference worker loads ONLY its topology-assigned blocks' weights
(`cake-core/src/cake/worker.rs:85-98`); this is the mesh-path equivalent.
:func:`load_llama_params_on_mesh` assembles the sharded params pytree with
``jax.make_array_from_callback``: every *addressable* shard's bytes are read
straight out of the mmap'd safetensors (``safe_open(...).get_slice``) and
placed on its device — there is never a full-model host copy, and on a
multi-host pod each host reads only the layer ranges its local devices'
stages own. Contrast ``load_llama_params`` + ``shard_params``, which builds
the entire pytree on host first (~70 GB host RAM for 70B int8, with
full-model quantize time, on *every* host).

Quantize-on-load (``quantize="int8"``/``"int4"``) stays shard-local where
the math allows: column-parallel linears (wq/wk/wv/w_gate/w_up, and lm_head)
shard out-features, and the per-output-channel scale depends only on the
full in-axis — present in every shard — so quantizing the column slice
equals quantizing the full weight and slicing. Row-parallel linears
(wo/w_down) shard the in-axis, so their callbacks read the full
``[in, out]`` layer weight, quantize, and slice — one layer at a time,
never the whole stage. For int4 the *packed* row axis is what shards:
adjacent-pair packing (ops/quant.py) keeps every packed-row range a
contiguous original-row range, so the reads stay single slices.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cake_tpu.models.config import LlamaConfig
from cake_tpu.parallel.mesh import EP, STAGE, TP
from cake_tpu.utils.weights import (
    _BIAS_MAP,
    _LAYER_MAP,
    _MOE_EXPERT_MAP,
    _MOE_ROUTER,
    detect_family,
    detect_tied_head,
    hf_layer_map,
    load_safetensors_index,
)

# column-parallel: out-features shard over tp, in-axis full per shard
_COL_PARALLEL = ("wq", "wk", "wv", "w_gate", "w_up")
# row-parallel: in-features shard over tp (scale needs the full in-axis)
_ROW_PARALLEL = ("wo", "w_down")


class CheckpointReader:
    """Sliced mmap reads over a safetensors checkpoint, with byte
    accounting (``bytes_read``) so tests can assert a stage loads ~1/S of
    the model."""

    def __init__(self, model_dir):
        self.name_to_file = load_safetensors_index(model_dir)
        self._handles: dict = {}
        self.bytes_read = 0

    def _slice(self, name: str):
        from safetensors import safe_open

        f = self.name_to_file[name]
        h = self._handles.get(f)
        if h is None:
            h = self._handles[f] = safe_open(f, framework="np")
        return h.get_slice(name)

    def read1d(self, name: str, sl: slice = slice(None)) -> np.ndarray:
        out = np.asarray(self._slice(name)[sl])
        self.bytes_read += out.nbytes
        return out

    def read2d(self, name: str, rows: slice, cols: slice,
               transpose: bool) -> np.ndarray:
        """Logical ``[rows, cols]`` slice; ``transpose=True`` when the
        checkpoint stores the torch ``[out, in]`` layout and the logical
        layout is ``[in, out]``."""
        if transpose:
            out = np.asarray(self._slice(name)[cols, rows]).T
        else:
            out = np.asarray(self._slice(name)[rows, cols])
        self.bytes_read += out.nbytes
        return out

    def shape(self, name: str) -> tuple:
        """Stored shape without reading tensor bytes."""
        return tuple(self._slice(name).get_shape())

    def close(self) -> None:
        for h in self._handles.values():
            if hasattr(h, "close"):
                h.close()
        self._handles.clear()


def _np_dtype(dtype) -> np.dtype:
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16 if str(dtype) == "bfloat16" else dtype)


def _memo(cb):
    cache: dict = {}

    def wrapped(index):
        key = tuple((s.start, s.stop, s.step) for s in index)
        if key not in cache:
            cache[key] = cb(index)
        return cache[key]

    return wrapped


def _assemble(shape, mesh: Mesh, spec: P, cb):
    return jax.make_array_from_callback(
        tuple(shape), NamedSharding(mesh, spec), _memo(cb)
    )


def load_llama_params_on_mesh(
    model_dir,
    config: LlamaConfig,
    mesh: Mesh,
    quantize: str | None = None,
    tie_word_embeddings: bool = False,
) -> dict:
    """Load a checkpoint directory directly into mesh-sharded global arrays
    (the layout of :func:`cake_tpu.parallel.mesh.param_specs`). Bitwise
    equal to ``shard_params(load_llama_params(...), mesh)`` — tested — but
    reads only addressable shards' bytes and holds at most one layer weight
    of host scratch at a time."""
    from cake_tpu.ops.quant import (
        Quantized4Linear,
        QuantizedLinear,
        pack_int4_np,
        parse_quant_spec,
        quantize_linear4_np,
        quantize_linear_np,
    )
    from cake_tpu.utils.weights import check_prequantized

    tier, gsize = parse_quant_spec(quantize)
    int4 = tier == "int4"
    # tier plumbing: stored-tensor suffix, host quantizer, quantized class,
    # and the packed-row factor (int4 stores K/2 rows per K in-features)
    qsuffix = ".q4" if int4 else ".q8"
    np_qfn = quantize_linear4_np if int4 else quantize_linear_np
    qcls = Quantized4Linear if int4 else QuantizedLinear
    qmax = 7 if int4 else 127
    krows = 2 if int4 else 1  # original rows per stored quantized row

    reader = CheckpointReader(model_dir)
    num_experts, attention_bias, o_bias = detect_family(reader.name_to_file)
    if not tie_word_embeddings and detect_tied_head(
            reader.name_to_file, model_dir, "cake_tpu.sharded_load"):
        tie_word_embeddings = True
    if num_experts and int4:
        from cake_tpu.ops.quant import reject_int4_moe

        reject_int4_moe()
    prequantized = check_prequantized(reader.name_to_file, quantize)
    # Grouped int4 (the accuracy tier): the direct-to-mesh path supports it
    # for PRE-QUANTIZED checkpoints (stored [ngroups, out] scales slice
    # like any tensor); on-the-fly grouped quantize would re-read full
    # weights per shard for no benefit over quantizing once offline.
    group = None  # in-rows per scale group, detected from the checkpoint
    if int4 and prequantized:
        probe = f"model.layers.0.{_LAYER_MAP['wq'][0]}.scale"
        if probe in reader.name_to_file:
            sshape = reader.shape(probe)
            if len(sshape) == 2:
                group = config.hidden_size // sshape[0]
    if gsize is not None and not prequantized:
        raise ValueError(
            "grouped int4 quantize-on-load is not supported on the "
            "direct-to-mesh path; pre-quantize once with "
            "`python -m cake_tpu.tools.quantize_model --bits 4 "
            f"--group-size {gsize}` and load that checkpoint"
        )
    if gsize is not None and prequantized and gsize != group:
        # covers both a different stored group size AND a per-channel
        # checkpoint (group None) — never silently drop a requested tier
        raise ValueError(
            f"checkpoint stores "
            f"{'group_size=' + str(group) if group else 'per-channel'} "
            f"int4, but quantize spec asked for g{gsize}"
        )
    dt = _np_dtype(config.dtype)
    L = config.num_hidden_layers
    h = config.hidden_size
    d = config.head_dim  # explicit per-head width (Gemma: heads*d != h)
    shapes = {
        "attn_norm": (L, h),
        "wq": (L, h, config.num_attention_heads * d),
        "wk": (L, h, config.num_key_value_heads * d),
        "wv": (L, h, config.num_key_value_heads * d),
        "wo": (L, config.num_attention_heads * d, h),
        "mlp_norm": (L, h),
        "w_gate": (L, h, config.intermediate_size),
        "w_up": (L, h, config.intermediate_size),
        "w_down": (L, config.intermediate_size, h),
    }

    def norm_cb(suffix):
        def cb(index):
            lsl, _ = index
            lo, hi, _ = lsl.indices(L)
            return np.stack([
                reader.read1d(f"model.layers.{i}.{suffix}")
                for i in range(lo, hi)
            ]).astype(dt)

        return cb

    def linear_cb(suffix, transpose):
        def cb(index):
            lsl, rsl, csl = index
            lo, hi, _ = lsl.indices(L)
            return np.stack([
                reader.read2d(f"model.layers.{i}.{suffix}", rsl, csl,
                              transpose)
                for i in range(lo, hi)
            ]).astype(dt)

        return cb

    # Per-(tensor, column-range) scale memo. Scales are tiny ([out] f32 per
    # layer) but cost a weight read to compute — the memo means each weight
    # is read for quantization context exactly once per distinct need:
    # row-parallel shards read one full weight for the scale, then only
    # their own row slices; the scale leaf's callbacks are pure memo hits.
    scale_memo: dict[tuple, np.ndarray] = {}

    def _key(name: str, csl: slice) -> tuple:
        return (name, csl.start, csl.stop)

    def _scale(name: str, transpose: bool, csl: slice) -> np.ndarray:
        """Scale for columns ``csl`` (full in-axis — exact by construction)."""
        key = _key(name, csl)
        if key not in scale_memo:
            full = _key(name, slice(None))
            if full in scale_memo:
                scale_memo[key] = scale_memo[full][csl]
            else:
                w = reader.read2d(name, slice(None), csl, transpose)
                scale_memo[key] = np_qfn(w)[1]
        return scale_memo[key]

    def quant_q_cb(suffix, transpose, row_parallel, kdim):
        def cb(index):
            lsl, rsl, csl = index
            lo, hi, _ = lsl.indices(L)
            # int4 shards the PACKED row axis: stored rows [a, b) are the
            # contiguous original rows [2a, 2b) (adjacent-pair packing,
            # ops/quant.py), so the weight read stays one contiguous slice
            a, b, _ = rsl.indices(kdim // krows)
            wr = slice(a * krows, b * krows)
            per = []
            for i in range(lo, hi):
                name = f"model.layers.{i}.{suffix}"
                if prequantized:
                    # stored quantized bytes in the HF [out, in(/2)]
                    # orientation: read exactly this shard's slice
                    per.append(
                        reader.read2d(f"{name}{qsuffix}", rsl, csl, True))
                elif row_parallel:
                    # scale needs the full in-axis (memoized: one full read
                    # per layer, shared across tp shards and the scale
                    # leaf); the quantized bytes then need only this
                    # shard's rows
                    s = _scale(name, transpose, csl)
                    w = reader.read2d(name, wr, csl, transpose)
                    q = np.clip(
                        np.round(np.asarray(w, np.float32) / s),
                        -qmax, qmax).astype(np.int8)
                    if int4:
                        q = pack_int4_np(q)
                    per.append(q)
                else:
                    q, s = np_qfn(reader.read2d(name, wr, csl, transpose))
                    scale_memo.setdefault(_key(name, csl), s)
                    per.append(q)
            return np.stack(per)

        return cb

    def quant_scale_cb(suffix, transpose):
        def cb(index):
            if group is not None:
                # grouped scale leaf [L, ngroups, out]: stored exactly so
                lsl, gsl, csl = index
                lo, hi, _ = lsl.indices(L)
                return np.stack([
                    reader.read2d(f"model.layers.{i}.{suffix}.scale",
                                  gsl, csl, False)
                    for i in range(lo, hi)
                ])
            lsl, csl = index
            lo, hi, _ = lsl.indices(L)
            if prequantized:
                return np.stack([
                    reader.read1d(f"model.layers.{i}.{suffix}.scale", csl)
                    for i in range(lo, hi)
                ])
            return np.stack([
                _scale(f"model.layers.{i}.{suffix}", transpose, csl)
                for i in range(lo, hi)
            ])

        return cb

    try:
        layers: dict = {}
        for ours, (suffix, transpose) in hf_layer_map(
            num_experts, attention_bias, o_bias
        ).items():
            if ours == "bo":
                # o_proj bias [L, hidden]: applied after the tp psum, so
                # replicated like the norms
                layers[ours] = _assemble((L, h), mesh, P(STAGE, None),
                                         norm_cb(suffix))
                continue
            if ours in _BIAS_MAP:
                # q/k/v bias [L, out]: shards with the projection's
                # out-features (column-parallel tp)
                out_dim = shapes[ours.replace("b", "w", 1)][2]

                def bias_cb(sfx):
                    def cb(index):
                        lsl, csl = index
                        lo, hi, _ = lsl.indices(L)
                        return np.stack([
                            reader.read1d(f"model.layers.{i}.{sfx}", csl)
                            for i in range(lo, hi)
                        ]).astype(dt)

                    return cb

                layers[ours] = _assemble((L, out_dim), mesh, P(STAGE, TP),
                                         bias_cb(suffix))
                continue
            shape = shapes[ours]
            if len(shape) == 2:
                layers[ours] = _assemble(shape, mesh, P(STAGE, None),
                                         norm_cb(suffix))
                continue
            spec = (P(STAGE, TP, None) if ours in _ROW_PARALLEL
                    else P(STAGE, None, TP))
            if tier is not None:
                qshape = (L, shape[1] // krows, shape[2])
                if group is not None:
                    # grouped scale [L, ngroups, out] takes the weight's
                    # spec — the group axis lives along (and shards with)
                    # the in axis (mesh.param_specs, same rule)
                    scale_spec = spec
                    scale_shape = (L, shape[1] // group, shape[2])
                else:
                    scale_spec = (P(STAGE, None) if ours in _ROW_PARALLEL
                                  else P(STAGE, TP))
                    scale_shape = (L, shape[2])
                layers[ours] = qcls(
                    _assemble(qshape, mesh, spec,
                              quant_q_cb(suffix, transpose,
                                         ours in _ROW_PARALLEL, shape[1])),
                    _assemble(scale_shape, mesh, scale_spec,
                              quant_scale_cb(suffix, transpose)),
                )
            else:
                layers[ours] = _assemble(shape, mesh, spec,
                                         linear_cb(suffix, transpose))

        if num_experts:
            # router [L, H, E]: tiny, replicated (every rank routes every
            # token); expert stacks [L, E, in, out]: expert axis over ep,
            # features over tp like the dense MLP
            def router_cb(index):
                lsl, rsl, csl = index
                lo, hi, _ = lsl.indices(L)
                return np.stack([
                    reader.read2d(f"model.layers.{i}.{_MOE_ROUTER}",
                                  rsl, csl, True)
                    for i in range(lo, hi)
                ]).astype(dt)

            layers["router"] = _assemble((L, h, num_experts), mesh,
                                         P(STAGE, None, None), router_cb)

            def expert_cb(pattern):
                def cb(index):
                    lsl, esl, rsl, csl = index
                    lo, hi, _ = lsl.indices(L)
                    e_lo, e_hi, _ = esl.indices(num_experts)
                    return np.stack([
                        np.stack([
                            reader.read2d(
                                f"model.layers.{i}."
                                f"{pattern.format(e=e)}", rsl, csl, True)
                            for e in range(e_lo, e_hi)
                        ])
                        for i in range(lo, hi)
                    ]).astype(dt)

                return cb

            def expert_quant_q_cb(pattern, row_parallel):
                """Expert int8 bytes [L', E', rows, cols] — same
                shard-local-exactness rules as the dense linears: column-
                parallel quantizes the column slice directly (scale needs
                only the full in-axis, present per shard); row-parallel
                reads the full in-axis once per (layer, expert) for the
                memoized scale, then only its own rows."""
                def cb(index):
                    lsl, esl, rsl, csl = index
                    lo, hi, _ = lsl.indices(L)
                    e_lo, e_hi, _ = esl.indices(num_experts)
                    per = []
                    for i in range(lo, hi):
                        rows_e = []
                        for e in range(e_lo, e_hi):
                            name = (f"model.layers.{i}."
                                    f"{pattern.format(e=e)}")
                            if prequantized:
                                rows_e.append(reader.read2d(
                                    f"{name}{qsuffix}", rsl, csl, True))
                            elif row_parallel:
                                s = _scale(name, True, csl)
                                w = reader.read2d(name, rsl, csl, True)
                                rows_e.append(np.clip(
                                    np.round(np.asarray(w, np.float32) / s),
                                    -qmax, qmax).astype(np.int8))
                            else:
                                q, s = np_qfn(
                                    reader.read2d(name, rsl, csl, True))
                                scale_memo.setdefault(_key(name, csl), s)
                                rows_e.append(q)
                        per.append(np.stack(rows_e))
                    return np.stack(per)

                return cb

            def expert_scale_cb(pattern):
                def cb(index):
                    lsl, esl, csl = index
                    lo, hi, _ = lsl.indices(L)
                    e_lo, e_hi, _ = esl.indices(num_experts)
                    per = []
                    for i in range(lo, hi):
                        rows_e = []
                        for e in range(e_lo, e_hi):
                            name = (f"model.layers.{i}."
                                    f"{pattern.format(e=e)}")
                            if prequantized:
                                rows_e.append(
                                    reader.read1d(f"{name}.scale", csl))
                            else:
                                rows_e.append(_scale(name, True, csl))
                        per.append(np.stack(rows_e))
                    return np.stack(per)

                return cb

            fdim = config.intermediate_size
            for ours, (din, dout, spec) in {
                "w_gate": (h, fdim, P(STAGE, EP, None, TP)),
                "w_up": (h, fdim, P(STAGE, EP, None, TP)),
                "w_down": (fdim, h, P(STAGE, EP, TP, None)),
            }.items():
                pattern = _MOE_EXPERT_MAP[ours]
                if tier == "int8":
                    row_par = ours == "w_down"
                    scale_spec = (P(STAGE, EP, None) if row_par
                                  else P(STAGE, EP, TP))
                    layers[ours] = qcls(
                        _assemble((L, num_experts, din, dout), mesh, spec,
                                  expert_quant_q_cb(pattern, row_par)),
                        _assemble((L, num_experts, dout), mesh, scale_spec,
                                  expert_scale_cb(pattern)),
                    )
                else:
                    layers[ours] = _assemble(
                        (L, num_experts, din, dout), mesh, spec,
                        expert_cb(pattern),
                    )

        embed_name = "model.embed_tokens.weight"
        head_name = embed_name if tie_word_embeddings else "lm_head.weight"
        params: dict = {"layers": layers}
        params["embed"] = _assemble(
            (config.vocab_size, h), mesh, P(None, None),
            lambda index: reader.read2d(embed_name, index[0], index[1],
                                        False).astype(dt),
        )
        params["norm_f"] = _assemble(
            (h,), mesh, P(None),
            lambda index: reader.read1d("model.norm.weight",
                                        index[0]).astype(dt),
        )
        if tier is not None:
            # lm_head is column-parallel over vocab: shard-local quantize
            # is exact (full in-axis per shard); its scales ride the same
            # memo so the scale leaf re-reads nothing. A tied head has no
            # stored .q8/.q4 (the embedding stays full-precision) and falls
            # back to on-the-fly quantize — at the checkpoint's detected
            # group size, so the head matches the layers' tier.
            head_prequant = (
                prequantized
                and f"{head_name}{qsuffix}" in reader.name_to_file
            )

            # one read + one quantize per column range for the grouped
            # tied-head fallback — head_q and head_scale share the result
            # (the grouped analog of scale_memo; both specs are P(None, TP),
            # so the row axis is always full and columns key the memo)
            head_g_memo: dict[tuple, tuple] = {}

            def _head_grouped(csl: slice) -> tuple:
                key = (csl.start, csl.stop)
                if key not in head_g_memo:
                    w = reader.read2d(head_name, slice(0, h), csl, True)
                    head_g_memo[key] = quantize_linear4_np(
                        w, group_size=group)
                return head_g_memo[key]

            def head_q(index):
                if head_prequant:
                    return reader.read2d(f"{head_name}{qsuffix}", index[0],
                                         index[1], True)
                if group is not None:
                    return _head_grouped(index[1])[0]
                a, b, _ = index[0].indices(h // krows)
                w = reader.read2d(
                    head_name, slice(a * krows, b * krows), index[1], True)
                q, s = np_qfn(w)
                scale_memo.setdefault(_key(head_name, index[1]), s)
                return q

            def head_scale(index):
                if group is not None:
                    if head_prequant:
                        return reader.read2d(f"{head_name}.scale",
                                             index[0], index[1], False)
                    return _head_grouped(index[1])[1]
                if head_prequant:
                    return reader.read1d(f"{head_name}.scale", index[0])
                return _scale(head_name, True, index[0])

            if group is not None:
                head_scale_leaf = _assemble(
                    (h // group, config.vocab_size), mesh, P(None, TP),
                    head_scale)
            else:
                head_scale_leaf = _assemble(
                    (config.vocab_size,), mesh, P(TP), head_scale)
            params["lm_head"] = qcls(
                _assemble((h // krows, config.vocab_size), mesh,
                          P(None, TP), head_q),
                head_scale_leaf,
            )
        else:
            params["lm_head"] = _assemble(
                (h, config.vocab_size), mesh, P(None, TP),
                lambda index: reader.read2d(head_name, index[0], index[1],
                                            True).astype(dt),
            )
        return params
    finally:
        reader.close()
