"""Public per-chip hardware specs (one copy — bench.py and the tools
share it so a spec correction can never leave one caller's roofline
denominator stale).

Sources: published TPU spec sheets. These feed roofline DENOMINATORS
(weights-bound ideal tok/s = HBM bytes/s / model bytes; MFU = FLOPs/s /
peak) — they are never presented as measurements.
"""

from __future__ import annotations

# chip kind substring -> HBM GB/s
HBM_GBPS = {
    "v5 lite": 819.0,  # v5e: 16 GiB @ 819 GB/s
    "v5e": 819.0,
    "v4": 1228.0,
    "v5p": 2765.0,
    "v6e": 1640.0,
    "cpu": 50.0,
}

# chip kind substring -> approx bf16 peak TFLOP/s
PEAK_TFLOPS = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v4": 275.0,
    "v5p": 459.0,
    "v6e": 918.0,
    "cpu": 1.0,
}

# chip kind substring -> HBM capacity GiB
HBM_GIB = {
    "v5 lite": 16.0,
    "v5e": 16.0,
    "v4": 32.0,
    "v5p": 95.0,
    "v6e": 32.0,
}


def device_spec(device, table: dict, default: float) -> float:
    """Look up a spec by substring match on ``device.device_kind``."""
    kind = getattr(device, "device_kind", "cpu").lower()
    for k, v in table.items():
        if k in kind:
            return v
    return default


def hbm_gbps(device) -> float:
    return device_spec(device, HBM_GBPS, 819.0)
