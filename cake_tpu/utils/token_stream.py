"""Incremental UTF-8-safe streaming detokenizer.

Equivalent of `cake-core/src/utils/token_output_stream.rs` (itself adapted
from HF text-generation-inference, token_output_stream.rs:35): emit text only
when the decoded string grows and ends in an alphanumeric character
(:36-53) so multi-token UTF-8 sequences and merge-dependent spaces are never
split; ``decode_rest`` flushes the tail (:55-69).
"""

from __future__ import annotations

from typing import Callable, Protocol


class _Decoder(Protocol):
    def decode(self, ids: list[int]) -> str: ...


class TokenOutputStream:
    """Wraps any object with ``decode(list[int]) -> str`` (HF ``tokenizers``
    and ``transformers`` tokenizers both qualify)."""

    def __init__(self, tokenizer: _Decoder):
        self.tokenizer = tokenizer
        self.tokens: list[int] = []
        self.prev_index = 0
        self.current_index = 0

    def _decode(self, ids: list[int]) -> str:
        return self.tokenizer.decode(ids)

    def next_token(self, token: int) -> str | None:
        """Feed one token id; return newly-safe text or None."""
        prev_text = (
            self._decode(self.tokens[self.prev_index : self.current_index])
            if self.tokens
            else ""
        )
        self.tokens.append(token)
        text = self._decode(self.tokens[self.prev_index :])
        if len(text) > len(prev_text) and text and text[-1].isalnum():
            out = text[len(prev_text) :]
            self.prev_index = self.current_index
            self.current_index = len(self.tokens)
            return out
        return None

    def decode_rest(self) -> str | None:
        """Flush any withheld tail text (token_output_stream.rs:55-69)."""
        prev_text = (
            self._decode(self.tokens[self.prev_index : self.current_index])
            if self.tokens
            else ""
        )
        text = self._decode(self.tokens[self.prev_index :])
        if len(text) > len(prev_text):
            return text[len(prev_text) :]
        return None

    def clear(self) -> None:
        self.tokens.clear()
        self.prev_index = 0
        self.current_index = 0
