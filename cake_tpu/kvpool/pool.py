"""Device-resident KV page pool + the gather/scatter programs over it.

Physical layout: one pooled buffer per cache half,

    ``[num_layers, num_pages, kv_heads, page_size, head_dim]``

(int8 KV adds the per-slot scale half minus the trailing ``head_dim``,
mirroring :class:`cake_tpu.ops.kvcache.QuantizedKV`). The page axis is
UNSHARDED — pages are the allocation unit, addressed by value through
per-stream page tables — while layers shard over ``stage`` and kv heads
over ``tp`` exactly like the contiguous cache, so a pool page's HBM
placement matches the cache rows it replaces.

Inside a compiled decode step the pool is addressed through two small
int32 operands (shapes static -> no retrace, same discipline as the
constrain mask tables):

- ``page_map [B, pages_per_stream]`` — each stream's logical->physical
  page list, sink-padded past its frontier. The step GATHERS these pages
  into the standard contiguous ``[L, B, KH, S, D]`` view and runs the
  unchanged attention/KV-write body over it, so paged streams are
  bit-identical to slot streams by construction (the gathered view IS
  the slot cache's contents).
- ``scatter_ids [B, W]`` — the physical pages receiving this dispatch's
  KV writes (the ``W`` pages covering ``[pos, pos+steps)`` per row; sink
  for retired/dummy/overrun rows). Only these pages scatter back —
  admission and retirement never touch the pool tensor at all.

The host-called programs (``row_gather`` / ``row_scatter`` /
``batch_scatter``) move whole staged rows between the admission plane's
contiguous staging caches and pool pages; each compiles once per
geometry and is memoized exactly like ``mesh.init_cache_on_mesh``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from cake_tpu.models.config import LlamaConfig
from cake_tpu.ops.kvcache import KVCache, QuantizedKV
from cake_tpu.parallel.mesh import STAGE, TP, cache_specs

# Thread domain (cakelint CK-THREAD): the compiled-program memo
# (_POOL_PROGRAMS) and every host-called pool program dispatch are
# engine-thread work — same single-writer contract as the page tables
# these programs move rows for.
_THREAD_DOMAIN = "engine"


def pool_specs(kv_quant: str | None = None):
    """PartitionSpec pytree for the pool: layers over stage, kv heads
    over tp, the page axis replicated (pages are addressed by value —
    sharding them would need per-shard id spaces)."""
    spec = P(STAGE, None, TP, None, None)
    if kv_quant == "int8":
        half = QuantizedKV(q=spec, scale=P(STAGE, None, TP, None))
        return KVCache(k=half, v=half)
    return KVCache(k=spec, v=spec)


def _pool_shardings(mesh, kv_quant):
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        pool_specs(kv_quant),
                        is_leaf=lambda x: isinstance(x, P))


def page_size_of(pool: KVCache) -> int:
    k = pool.k.q if isinstance(pool.k, QuantizedKV) else pool.k
    return k.shape[3]


def num_pages_of(pool: KVCache) -> int:
    k = pool.k.q if isinstance(pool.k, QuantizedKV) else pool.k
    return k.shape[1]


def writeback_width(steps: int, page_size: int, pages_per_stream: int) -> int:
    """Pages a ``steps``-token dispatch can touch per row: the span of
    ``steps`` consecutive positions crosses at most this many page
    boundaries regardless of alignment."""
    return min(pages_per_stream, 1 + (steps + page_size - 2) // page_size)


# compiled pool programs, memoized by geometry (a fresh jit closure per
# call would retrace per admission — the stall the slot path's splice
# already taught this repo to kill)
_POOL_PROGRAMS: dict = {}


def init_pool_on_mesh(config: LlamaConfig, mesh, num_pages: int,
                      page_size: int, quant: str | None = None) -> KVCache:
    """Allocate a zeroed, mesh-sharded page pool (same no-host-copy
    contract as ``init_cache_on_mesh``: zeros come out of a compiled
    program with explicit output shardings)."""
    key = ("init", mesh, config.num_hidden_layers,
           config.num_key_value_heads, config.head_dim, str(config.dtype),
           num_pages, page_size, quant)
    make = _POOL_PROGRAMS.get(key)
    if make is None:
        L = config.num_hidden_layers
        KH = config.num_key_value_heads
        D = config.head_dim
        dt = config.jax_dtype
        shape = (L, num_pages, KH, page_size, D)

        def zeros():
            if quant == "int8":
                def half():
                    return QuantizedKV(q=jnp.zeros(shape, jnp.int8),
                                       scale=jnp.zeros(shape[:-1],
                                                       jnp.float32))

                return KVCache(k=half(), v=half())
            return KVCache(k=jnp.zeros(shape, dt), v=jnp.zeros(shape, dt))

        make = jax.jit(zeros, out_shardings=_pool_shardings(mesh, quant))
        _POOL_PROGRAMS[key] = make
    return make()


# -- trace-level helpers (used INSIDE compiled programs) ---------------------
def _gather_buf(buf: jax.Array, page_map: jax.Array) -> jax.Array:
    """``[L, P, KH, ps(, D)]`` pool half + ``[B, Ppp]`` page map ->
    contiguous ``[L, B, KH, S(, D)]`` view (S = Ppp * ps)."""
    g = jnp.take(buf, page_map, axis=1)  # [L, B, Ppp, KH, ps(, D)]
    g = jnp.moveaxis(g, 2, 3)            # [L, B, KH, Ppp, ps(, D)]
    sh = g.shape
    return g.reshape(sh[:3] + (sh[3] * sh[4],) + sh[5:])


def gather_view(pool: KVCache, page_map: jax.Array) -> KVCache:
    """Materialize the standard contiguous cache view of every stream's
    pages — the unchanged decode body (attention, per-row KV writes) runs
    over this, which is what makes paged streams bit-identical to slot
    streams."""
    return jax.tree.map(lambda b: _gather_buf(b, page_map), pool)


def scatter_back(pool: KVCache, view: KVCache, first_page: jax.Array,
                 scatter_ids: jax.Array) -> KVCache:
    """Write each row's touched pages from the contiguous view back into
    the pool at ``scatter_ids [B, W]`` (sink ids absorb retired/dummy/
    overrun rows — the sink's content is never attendable, so duplicate
    sink writes are harmless)."""
    w = scatter_ids.shape[1]
    ids = scatter_ids.reshape(-1)

    def one(pbuf, vbuf):
        ps = pbuf.shape[3]
        sh = vbuf.shape
        L, B, KH, S = sh[:4]
        paged = vbuf.reshape((L, B, KH, S // ps, ps) + sh[4:])
        rows = jnp.moveaxis(paged, 1, 0)  # [B, L, KH, Ppp, ps(, D)]

        def slice_row(row, fp):  # row [L, KH, Ppp, ps(, D)]
            return jax.lax.dynamic_slice_in_dim(row, fp, w, axis=2)

        u = jax.vmap(slice_row)(rows, first_page)  # [B, L, KH, w, ps(, D)]
        u = jnp.moveaxis(u, 0, 1)                  # [L, B, KH, w, ps(, D)]
        u = jnp.moveaxis(u, 3, 2)                  # [L, B, w, KH, ps(, D)]
        u = u.reshape((L, B * w) + u.shape[3:])    # [L, B*w, KH, ps(, D)]
        return pbuf.at[:, ids].set(u)

    return jax.tree.map(one, pool, view)


# -- host-called staged-row programs -----------------------------------------
def _builders(config: LlamaConfig, mesh, quant: str | None):
    """The three staged-row programs for one (mesh, geometry), compiled
    lazily and memoized: row_gather (pool pages -> a batch-1 staging
    cache: the prefix-hit admission start), row_scatter (a finished
    staging row -> its allocated pages: the admission 'splice', now a
    page write instead of a batch-cache scatter), and batch_scatter
    (a whole prefilled batch cache -> per-row pages: set_prompts
    pageification)."""
    key = ("progs", mesh, config.num_hidden_layers,
           config.num_key_value_heads, config.head_dim, str(config.dtype),
           quant)
    progs = _POOL_PROGRAMS.get(key)
    if progs is not None:
        return progs
    pool_sh = _pool_shardings(mesh, quant)
    stage_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        cache_specs(quant, batch_replicated=True),
        is_leaf=lambda x: isinstance(x, P))

    @partial(jax.jit, out_shardings=stage_sh)
    def row_gather(pool, ids):  # ids [Ppp] int32 (sink-padded)
        def one(pbuf):
            g = jnp.take(pbuf, ids, axis=1)   # [L, Ppp, KH, ps(, D)]
            g = jnp.moveaxis(g, 1, 2)         # [L, KH, Ppp, ps(, D)]
            sh = g.shape
            return g.reshape((sh[0], 1, sh[1], sh[2] * sh[3]) + sh[4:])

        return jax.tree.map(one, pool)

    @partial(jax.jit, out_shardings=pool_sh, donate_argnums=(0,))
    def row_scatter(pool, staging, ids):  # ids [Ppp] (sink = keep)
        def one(pbuf, sbuf):
            ps = pbuf.shape[3]
            sh = sbuf.shape
            L, _, KH, S = sh[:4]
            paged = sbuf.reshape((L, KH, S // ps, ps) + sh[4:])
            u = jnp.moveaxis(paged, 2, 1)     # [L, Ppp, KH, ps(, D)]
            return pbuf.at[:, ids].set(u)

        return jax.tree.map(one, pool, staging)

    @partial(jax.jit, out_shardings=pool_sh, donate_argnums=(0,))
    def batch_scatter(pool, cache, ids):  # ids [B*Ppp] (sink = keep)
        def one(pbuf, cbuf):
            ps = pbuf.shape[3]
            sh = cbuf.shape
            L, B, KH, S = sh[:4]
            paged = cbuf.reshape((L, B, KH, S // ps, ps) + sh[4:])
            u = jnp.moveaxis(paged, 3, 2)     # [L, B, Ppp, KH, ps(, D)]
            u = u.reshape((L, B * (S // ps)) + u.shape[3:])
            return pbuf.at[:, ids].set(u)

        return jax.tree.map(one, pool, cache)

    progs = {"row_gather": row_gather, "row_scatter": row_scatter,
             "batch_scatter": batch_scatter}
    _POOL_PROGRAMS[key] = progs
    return progs


def row_gather_prog(config, mesh, quant):
    return _builders(config, mesh, quant)["row_gather"]


def row_scatter_prog(config, mesh, quant):
    return _builders(config, mesh, quant)["row_scatter"]


def batch_scatter_prog(config, mesh, quant):
    return _builders(config, mesh, quant)["batch_scatter"]
