"""Shared-prefix structures for both KV layouts.

:class:`PrefixTree` is the paged layout's prefix cache: a trie whose
edges are FULL page-sized token chunks and whose nodes each hold one
refcounted physical page of the pool. ``n`` streams opening with the
same system prompt walk the same chain and share the same physical
prefill pages (refcount n + 1 with the tree's own claim) — the
copy-on-write prefix sharing the slot layout's row store approximated
with whole-cache staged rows. Eviction is a REAL policy: when the free
list runs dry, least-recently-used leaves are dropped (deepest first —
an interior node cannot go while a child still chains through it) and
their pool references released; a page shared with a live stream
survives until that stream retires.

:class:`PrefixLRU` is the legacy slot layout's store, replacing the
hand-rolled ``dict`` pop-reinsert / ``next(iter(...))`` idiom in
``BatchGenerator`` with an explicit recency structure (same semantics:
insert-or-refresh, match bumps recency, evict the least recent past the
cap — now stated by the type instead of implied by dict ordering).
"""

from __future__ import annotations

from collections import OrderedDict

from cake_tpu.kvpool.table import PagePool
from cake_tpu.obs import metrics as obs_metrics


class _Node:
    __slots__ = ("page", "children", "last_use")

    def __init__(self, page: int):
        self.page = page
        self.children: dict[tuple, _Node] = {}
        self.last_use = 0


class PrefixTree:
    """Page-granular shared-prefix trie over a :class:`PagePool`.

    Engine-thread only. Every node holds one tree reference on its page
    (released at eviction); streams that match take their own references.
    """

    # cakelint CK-THREAD: tree mutations ride the pool's page claims,
    # so the runtime twin asserts through the shared pool stamp
    _THREAD_DOMAIN = "engine"

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._root = _Node(page=-1)
        self._clock = 0
        self._count = 0
        self._nodes_g = obs_metrics.Gauge("kvpool.prefix_nodes")
        obs_metrics.registry().publish(self._nodes_g)
        self._nodes_g.set(0)

    def __len__(self) -> int:
        return self._count

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, ids: list[int]) -> tuple[int, list[int]]:
        """Longest chain of full prompt pages STRICTLY shorter than the
        prompt (>= 1 remainder token must stay to produce the first-token
        logits — the same rule as the slot store). Returns
        ``(base_tokens, page_ids)``; base is always page-aligned. The
        caller takes its own pool references on the returned pages BEFORE
        anything can evict them."""
        ps = self.page_size
        node, pages, n = self._root, [], 0
        while True:
            lo = n * ps
            if lo + ps >= len(ids):  # full page + >= 1 remainder token
                break
            child = node.children.get(tuple(ids[lo: lo + ps]))
            if child is None:
                break
            child.last_use = self._tick()
            pages.append(child.page)
            node = child
            n += 1
        return n * ps, pages

    def insert(self, ids: list[int], pages: list[int]) -> int:
        """Register ``pages`` as the chain of full prompt pages for
        ``ids`` (``pages[j]`` holds tokens ``ids[j*ps:(j+1)*ps]``). Nodes
        already present keep their existing page (the caller matched them
        on the way in); each NEW node takes one tree reference on the
        caller's page. Returns the number of new nodes."""
        ps = self.page_size
        node, new = self._root, 0
        for j, pid in enumerate(pages):
            chunk = tuple(ids[j * ps: (j + 1) * ps])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(page=pid)
                self.pool.ref(pid)
                node.children[chunk] = child
                self._count += 1
                new += 1
            child.last_use = self._tick()
            node = child
        if new:
            self._nodes_g.set(self._count)
        return new

    def _lru_leaf(self) -> tuple[_Node, tuple] | None:
        """Oldest childless node and its edge key (None when empty)."""
        best: tuple[_Node, _Node, tuple] | None = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for key, child in node.children.items():
                if child.children:
                    stack.append(child)
                elif best is None or child.last_use < best[1].last_use:
                    best = (node, child, key)
        if best is None:
            return None
        parent, child, key = best
        del parent.children[key]
        self._count -= 1
        return child, key

    def evict_one(self) -> bool:
        """Drop the least-recently-used leaf and release its page claim
        (the page frees only when no live stream still shares it).
        Returns False when the tree is empty."""
        dropped = self._lru_leaf()
        if dropped is None:
            return False
        node, _ = dropped
        self.pool.unref(node.page)
        self.pool.count_eviction()
        self._nodes_g.set(self._count)
        return True

    def evict_until_free(self, need: int) -> bool:
        """Evict until ``need`` pages are free (True) or the tree is
        empty (False if still short)."""
        while self.pool.free_count < need:
            if not self.evict_one():
                return self.pool.free_count >= need
        return True


class PrefixLRU:
    """Explicit LRU for the slot layout's staged prefix rows.

    Same behavior the old dict idiom implemented implicitly — insert or
    refresh to most-recent, longest-strictly-shorter-prefix match bumps
    recency, eviction drops the least recent past ``cap`` — with the
    policy readable in one place (and its own regression test).
    """

    def __init__(self, cap: int):
        self.cap = max(0, cap)
        self._d: OrderedDict[tuple, object] = OrderedDict()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key: tuple) -> bool:
        return key in self._d

    def keys(self):
        return self._d.keys()

    def put(self, key: tuple, row) -> None:
        """Insert-or-refresh; evicts the least recently used past cap."""
        if self.cap <= 0:
            return
        if key in self._d:
            self._d.move_to_end(key)
        self._d[key] = row
        while len(self._d) > self.cap:
            self._d.popitem(last=False)

    def match(self, ids: list[int]) -> tuple[int, object | None]:
        """Longest stored prefix STRICTLY shorter than the prompt (at
        least one remainder token must produce the first-token logits);
        a hit becomes most-recent. Returns ``(base, row-or-None)``."""
        best, row = 0, None
        for key in self._d:
            m = len(key)
            if m > best and m < len(ids) and tuple(ids[:m]) == key:
                best, row = m, self._d[key]
        if row is not None:
            self._d.move_to_end(tuple(ids[:best]))
        return best, row
