"""Host-side bookkeeping for the paged KV pool: free list + refcounts.

The device side (:mod:`cake_tpu.kvpool.pool`) is a dumb page array; ALL
ownership lives here, on the engine thread, as plain Python state — which
is what makes admission and retirement O(pages touched) list operations
instead of cache-tensor dispatches. A physical page is:

- **free**: on the free list, refcount 0;
- **owned**: refcount 1 — exactly one stream's page table points at it;
- **shared**: refcount > 1 — several streams (and/or the prefix tree,
  :mod:`cake_tpu.kvpool.prefix`) point at the same physical page. Shared
  pages are immutable by construction: only FULL prompt pages are ever
  shared, and a stream's writes always land at/past its own frontier,
  which sits beyond every full prompt page it shares. Copy-on-write is
  therefore an allocation policy, not a trap: content that would be
  written into a partially-shared page is materialized into a fresh
  owned page instead (counted by ``kvpool.cow_copies``);
- **pinned**: held by an in-flight KV transfer (``pin``/``unpin`` — the
  disagg export/import plane, :mod:`cake_tpu.disagg`). A pin is a claim
  OUTSIDE stream tables and the prefix tree: a page a decode replica
  imported but no stream has attached yet, or one an export still reads.
  Refcounts used to assume only those two claim kinds existed; the pin
  kind makes the third explicit, so eviction under pool pressure can
  never free a page mid-transfer (the pin's reference protects it) and
  admission deferral (``kvpool.admit_defers``) becomes reachable even
  under the enforced pool sizing — pinned pages sit outside the
  batch*pages_per_stream budget.

Page 0 is the reserved **sink** page: every gather index that points
beyond a stream's frontier — and every scatter index for a retired /
dummy / out-of-window row — targets it. Its content is garbage by
design and is never attendable (the same masked-beyond-``pos``
invariant bucketed-prefill padding relies on).
"""

from __future__ import annotations

from collections import deque

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.runtime import threadcheck

# the reserved garbage-sink page id (gathers beyond the frontier, scatters
# from retired/dummy rows); never allocated, never attendable
SINK = 0


class PoolExhausted(RuntimeError):
    """No free page and nothing evictable; the caller decides whether this
    defers an admission or faults the engine (mid-decode it cannot happen
    when the pool is sized >= batch * pages_per_stream + 1, which
    ``BatchGenerator`` enforces)."""


class PagePool:
    """Refcounted free-list allocator over ``num_pages`` physical pages.

    Engine-thread only (the same single-writer contract as every other
    BatchGenerator mutation); publishes the ``kvpool.*`` gauges/counters.
    """

    # Thread domain, machine-checked by cakelint CK-THREAD: page claims
    # (alloc/ref/unref/pin/unpin) are engine-thread mutations. The
    # owning BatchGenerator shares its _domain_stamp with the pool, so
    # the runtime twin (CAKE_THREAD_STRICT=1) asserts the same contract
    # in execution; a standalone pool's stamp is never stamped and the
    # checks are vacuous.
    _THREAD_DOMAIN = "engine"

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (sink + one), got {num_pages}")
        if num_pages & (num_pages - 1):
            raise ValueError(f"num_pages must be a power of two, "
                             f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        # replaced by the owning engine's stamp when one adopts the pool
        self._domain_stamp = threadcheck.DomainStamp("engine")
        self._refs = [0] * num_pages
        self._refs[SINK] = 1  # pinned: the sink is never allocatable
        self._free: deque[int] = deque(range(1, num_pages))
        # per-instance instruments (the Registry.publish pattern the engine
        # histograms use): gauges must reflect THIS pool, not a predecessor
        self._free_g = obs_metrics.Gauge("kvpool.pages_free")
        self._shared_g = obs_metrics.Gauge("kvpool.pages_shared")
        self._pinned_g = obs_metrics.Gauge("kvpool.pages_pinned")
        self._cow_ctr = obs_metrics.Counter("kvpool.cow_copies")
        self._evict_ctr = obs_metrics.Counter("kvpool.evictions")
        self._defer_ctr = obs_metrics.Counter("kvpool.admit_defers")
        obs_metrics.registry().publish(
            self._free_g, self._shared_g, self._pinned_g, self._cow_ctr,
            self._evict_ctr, self._defer_ctr)
        self._shared = 0  # pages with refcount > 1 (kept incrementally)
        self._pins = [0] * num_pages  # transfer-pin claims per page
        self._pinned = 0  # pages with >= 1 pin claim
        self._sync_gauges()

    # -- allocation -----------------------------------------------------------
    def alloc(self) -> int:
        """Take a free page (refcount 1). Raises :class:`PoolExhausted`
        when the free list is empty — callers evict from the prefix tree
        first (``BatchGenerator._alloc_page``)."""
        self._domain_stamp.check("PagePool.alloc")
        if not self._free:
            raise PoolExhausted(
                f"kv page pool exhausted ({self.num_pages} pages, "
                f"page_size {self.page_size})")
        pid = self._free.popleft()
        self._refs[pid] = 1
        self._sync_gauges()
        return pid

    def ref(self, pid: int) -> None:
        """Add a reference (a stream or the prefix tree sharing the page)."""
        self._domain_stamp.check("PagePool.ref")
        if pid == SINK:
            return
        if self._refs[pid] <= 0:
            raise ValueError(f"ref of free page {pid}")
        self._refs[pid] += 1
        if self._refs[pid] == 2:
            self._shared += 1
        self._sync_gauges()

    def unref(self, pid: int) -> bool:
        """Drop one reference; returns True when the page went back to the
        free list."""
        self._domain_stamp.check("PagePool.unref")
        if pid == SINK:
            return False
        if self._refs[pid] <= 0:
            raise ValueError(f"unref of free page {pid}")
        self._refs[pid] -= 1
        if self._refs[pid] == 1:
            self._shared -= 1
        freed = self._refs[pid] == 0
        if freed:
            self._free.append(pid)
        self._sync_gauges()
        return freed

    # -- transfer pins --------------------------------------------------------
    def pin(self, pid: int) -> None:
        """Take a TRANSFER claim on a live page (an in-flight export, or
        an imported page no stream has attached yet). Counts as a
        reference — eviction storms can drop every tree claim and every
        sharing stream can retire, and the page still cannot return to
        the free list (and so can never be reallocated and overwritten)
        until the last pin drops."""
        self._domain_stamp.check("PagePool.pin")
        if pid == SINK:
            return
        self.ref(pid)
        self._pins[pid] += 1
        if self._pins[pid] == 1:
            self._pinned += 1
        self._sync_gauges()

    def unpin(self, pid: int) -> bool:
        """Drop one transfer claim; returns True when the page freed
        (the transfer was its last claim)."""
        self._domain_stamp.check("PagePool.unpin")
        if pid == SINK:
            return False
        if self._pins[pid] <= 0:
            raise ValueError(f"unpin of unpinned page {pid}")
        self._pins[pid] -= 1
        if self._pins[pid] == 0:
            self._pinned -= 1
        return self.unref(pid)

    # -- views ----------------------------------------------------------------
    def refcount(self, pid: int) -> int:
        return self._refs[pid]

    def pincount(self, pid: int) -> int:
        return self._pins[pid]

    @property
    def pinned_count(self) -> int:
        """Pages held by >= 1 in-flight transfer claim — the
        ``kvpool.pages_pinned`` gauge."""
        return self._pinned

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def shared_count(self) -> int:
        """Physical pages referenced more than once (streams and/or the
        prefix tree) — the ``kvpool.pages_shared`` gauge."""
        return self._shared

    @property
    def used_count(self) -> int:
        return self.num_pages - 1 - len(self._free)  # sink excluded

    def count_cow(self, n: int = 1) -> None:
        self._cow_ctr.inc(n)

    def count_eviction(self, n: int = 1) -> None:
        self._evict_ctr.inc(n)

    def count_defer(self) -> None:
        self._defer_ctr.inc()

    def _sync_gauges(self) -> None:
        self._free_g.set(len(self._free))
        self._shared_g.set(self._shared)
        self._pinned_g.set(self._pinned)

    def stats(self) -> dict:
        return {
            "pages_total": self.num_pages,
            "page_size": self.page_size,
            "pages_free": self.free_count,
            "pages_used": self.used_count,
            "pages_shared": self.shared_count,
            "pages_pinned": self.pinned_count,
        }
