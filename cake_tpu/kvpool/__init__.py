"""Paged KV-cache pool with copy-on-write prefix sharing.

The slot layout (``runtime.batch_generator`` default) gives every batch
row a contiguous ``max_seq`` KV strip: admission and retirement move
cache-sized tensors, and two streams can never share prefill work
physically. This package pools the same HBM as fixed-size pages
addressed through per-stream page tables (the vLLM / PagedAttention
design, on the mesh):

- :mod:`cake_tpu.kvpool.pool` — the device page array and the compiled
  gather/scatter programs (page tables enter the decode step as int32
  gather indices; static shapes, no retrace);
- :mod:`cake_tpu.kvpool.table` — host-side free list + refcounts
  (admission/retire touch page tables, never cache tensors);
- :mod:`cake_tpu.kvpool.prefix` — the page-granular shared-prefix trie
  (n same-system-prompt streams share physical prefill pages) with real
  LRU eviction, plus :class:`~cake_tpu.kvpool.prefix.PrefixLRU` for the
  legacy slot store.

Select with ``BatchGenerator(kv_layout="paged")`` / ``--kv-layout
paged``; token streams are bit-identical between layouts.
"""

from cake_tpu.kvpool.pool import (  # noqa: F401
    batch_scatter_prog,
    gather_view,
    init_pool_on_mesh,
    num_pages_of,
    page_size_of,
    pool_specs,
    row_gather_prog,
    row_scatter_prog,
    scatter_back,
    writeback_width,
)
from cake_tpu.kvpool.prefix import PrefixLRU, PrefixTree  # noqa: F401
from cake_tpu.kvpool.table import SINK, PagePool, PoolExhausted  # noqa: F401
