"""Embeddable worker API.

Equivalent of the reference's iOS/FFI surface (`cake-ios/src/lib.rs:11-57`):
a single ``start_worker(name, model_path, topology_path)`` export that an
embedding application calls to turn the current process into a cake worker
serving its topology-assigned layers. The reference exposes this through
UniFFI to Swift; here the same contract is exposed two ways:

- Python: ``cake_tpu.embed.start_worker(...)`` (blocking) or
  ``spawn_worker(...)`` (background, returns a handle with ``.shutdown()``).
- C: ``cake_start_worker(name, model_path, topology_path, address)`` in
  ``native/cake_embed.cc``, a CPython-embedding shim that any C/C++ host can
  link against (the TPU-native stand-in for the UniFFI boundary).

Defaults mirror the reference: bind ``0.0.0.0:10128`` (`lib.rs:20`).
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger("cake_tpu.embed")

DEFAULT_ADDRESS = "0.0.0.0:10128"


def _build_worker(name: str, model_path: str, topology_path: str,
                  address: str = DEFAULT_ADDRESS, quantize: str | None = None,
                  max_seq: int | None = None):
    from pathlib import Path

    from cake_tpu.models.config import LlamaConfig
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.worker import Worker
    from cake_tpu.utils.weights import load_llama_params

    config = LlamaConfig.from_hf_json(Path(model_path) / "config.json")
    topology = Topology.from_path(topology_path)

    def loader(lo, hi):
        return load_llama_params(
            model_path, config.num_hidden_layers, dtype=config.dtype,
            layer_range=(lo, hi), include_embed=False, include_head=False,
            quantize=quantize,
        )["layers"]

    return Worker(name, config, topology, loader, address=address,
                  max_seq=max_seq)


class WorkerHandle:
    """A running background worker; ``port`` is the bound port (useful when
    the address requested port 0) and ``shutdown()`` stops serving."""

    def __init__(self, worker, thread: threading.Thread):
        self._worker = worker
        self._thread = thread
        self.port: int = worker.port

    def shutdown(self, timeout: float = 5.0) -> None:
        self._worker.shutdown()
        self._thread.join(timeout=timeout)


def start_worker(name: str, model_path: str, topology_path: str,
                 address: str = DEFAULT_ADDRESS,
                 quantize: str | None = None,
                 max_seq: int | None = None) -> None:
    """Run a worker in the calling thread until interrupted (the blocking
    contract of the reference export, `cake-ios/src/lib.rs:33-57`)."""
    worker = _build_worker(name, model_path, topology_path, address, quantize,
                           max_seq)
    log.info("embedded worker '%s' serving on port %d", name, worker.port)
    try:
        worker.serve_forever()
    except KeyboardInterrupt:
        worker.shutdown()


def spawn_worker(name: str, model_path: str, topology_path: str,
                 address: str = DEFAULT_ADDRESS,
                 quantize: str | None = None,
                 max_seq: int | None = None) -> WorkerHandle:
    """Start a worker on a background thread and return a handle."""
    worker = _build_worker(name, model_path, topology_path, address, quantize,
                           max_seq)
    thread = worker.serve_in_background()
    return WorkerHandle(worker, thread)
