"""Multi-host bootstrap plane (parallel/distributed.py).

Real multi-process pods cannot run in CI; covered here: the single-process
path is a no-op that reports correct topology, and the >1-process path
passes the right arguments into jax.distributed.initialize (stubbed)."""

import jax
import pytest

from cake_tpu.parallel import distributed


def test_single_process_noop():
    info = distributed.initialize()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["global_devices"] == len(jax.devices())
    assert info["local_devices"] == info["global_devices"]


def test_multi_process_args_forwarded(monkeypatch):
    calls = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None):
        calls.update(coordinator=coordinator_address, n=num_processes,
                     pid=process_id)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    distributed.initialize(coordinator="10.0.0.2:8476", num_processes=4,
                           process_id=2)
    assert calls == {"coordinator": "10.0.0.2:8476", "n": 4, "pid": 2}


def test_env_process_count_triggers_init(monkeypatch):
    hit = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: hit.update(kw))
    monkeypatch.setenv("CAKE_NUM_PROCESSES", "2")
    distributed.initialize()
    # the env value must actually be forwarded, not just gate the call
    assert hit["num_processes"] == 2
