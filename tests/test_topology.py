import pytest

from cake_tpu.parallel.topology import Topology, expand_layer_ranges


EXAMPLE = {
    "worker-a": {
        "host": "10.0.0.1:10128",
        "description": "gpu box",
        "layers": ["model.layers.0-19"],
    },
    "worker-b": {
        "host": "10.0.0.2:10128",
        "description": "laptop",
        "layers": ["model.layers.20-31"],
    },
}


def test_range_expansion():
    out = expand_layer_ranges(["model.layers.0-2", "model.layers.7"])
    assert out == [
        "model.layers.0",
        "model.layers.1",
        "model.layers.2",
        "model.layers.7",
    ]


def test_range_expansion_rejects_bad_range():
    with pytest.raises(ValueError):
        expand_layer_ranges(["model.layers.5-5"])
    with pytest.raises(ValueError):
        expand_layer_ranges(["model.layers.9-3"])


def test_from_dict_and_lookup():
    t = Topology.from_dict(EXAMPLE)
    assert len(t) == 2
    assert t.get_node_for_layer("model.layers.0").name == "worker-a"
    assert t.get_node_for_layer("model.layers.20").name == "worker-b"
    assert t.get_node_for_layer("model.layers.31").name == "worker-b"
    assert t.get_node_for_layer("model.layers.32") is None
    assert "worker-a" in t
    assert t["worker-b"].host == "10.0.0.2:10128"


def test_is_layer_owner_prefix_match():
    t = Topology.from_dict(EXAMPLE)
    a = t["worker-a"]
    assert a.is_layer_owner("model.layers.3.self_attn.q_proj.weight")
    assert not a.is_layer_owner("model.layers.20.mlp.up_proj.weight")
    assert a.is_layer_owner("model.layers.19.mlp.up_proj.weight")
    assert not t["worker-b"].is_layer_owner("model.layers.2.input_layernorm.weight")
    assert not a.is_layer_owner("model.norm.weight")


def test_is_layer_owner_no_false_string_prefix():
    """A node owning exactly layer 1 must NOT own layer 19's tensors (string
    prefix 'model.layers.1' of 'model.layers.19...' must not match)."""
    t = Topology.from_dict({"w": {"layers": ["model.layers.1"]}})
    n = t["w"]
    assert n.is_layer_owner("model.layers.1.self_attn.q_proj.weight")
    assert not n.is_layer_owner("model.layers.19.self_attn.q_proj.weight")
    assert not n.is_layer_owner("model.layers.10.mlp.up_proj.weight")


def test_layer_indices():
    t = Topology.from_dict(EXAMPLE)
    assert t["worker-b"].layer_indices() == list(range(20, 32))


def test_yaml_roundtrip(tmp_path):
    t = Topology.from_dict(EXAMPLE)
    p = tmp_path / "topology.yml"
    t.save(p)
    t2 = Topology.from_path(p)
    assert t2["worker-a"].layers == t["worker-a"].layers
    assert t2["worker-b"].host == t["worker-b"].host


def test_segments_coalesce_contiguous_runs():
    t = Topology.from_dict(
        {
            "w1": {"layers": ["model.layers.0-3"]},
            "w2": {"layers": ["model.layers.4-5"]},
        }
    )
    segs = t.segments(num_layers=8)
    assert [(s.start, s.stop, s.owner) for s in segs] == [
        (0, 4, "w1"),
        (4, 6, "w2"),
        (6, 8, None),  # unassigned -> local to master
    ]


def test_segments_interleaved_owner():
    t = Topology.from_dict(
        {
            "w1": {"layers": ["model.layers.0", "model.layers.2"]},
        }
    )
    segs = t.segments(num_layers=3)
    assert [(s.start, s.stop, s.owner) for s in segs] == [
        (0, 1, "w1"),
        (1, 2, None),
        (2, 3, "w1"),
    ]


def test_device_extension():
    t = Topology.from_dict(
        {"stage0": {"device": 0, "layers": ["model.layers.0-1"]}}
    )
    assert t["stage0"].device == 0
