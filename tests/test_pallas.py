"""Parity tests: Pallas kernels vs the pure-JAX reference math.

Kernels run in interpret mode on the CPU test mesh (conftest forces
``jax_platforms=cpu``); the pure-JAX ops in :mod:`cake_tpu.ops` are the
oracle (themselves golden-tested against HF transformers in
test_hf_parity.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.ops.attention import attend
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.pallas import flash_attention, flash_decode


def _qkv(key, b, h, kvh, t, s, d, dtype=jnp.float32, pos=0):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, d), dtype)
    # Fill the cache only up to the causal frontier; beyond it is garbage
    # that both impls must mask out identically.
    k_all = jax.random.normal(kk, (b, kvh, s, d), dtype)
    v_all = jax.random.normal(kv, (b, kvh, s, d), dtype)
    return q, k_all, v_all


@pytest.mark.parametrize("pos", [0, 3])
@pytest.mark.parametrize("group", [1, 4])
def test_flash_prefill_matches_xla(pos, group):
    b, kvh, t, s, d = 2, 2, 8, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(0), b, h, kvh, t, s, d, pos=pos)
    ref = attend(q, k_all, v_all, pos)
    out = flash_attention(q, k_all, v_all, pos, block_q=4, block_k=8,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_prefill_ignores_future_kv():
    """KV content beyond the causal frontier must not affect the output."""
    b, kvh, group, t, s, d = 1, 2, 2, 4, 16, 8
    h = kvh * group
    pos = 2
    q, k_all, v_all = _qkv(jax.random.PRNGKey(1), b, h, kvh, t, s, d)
    out1 = flash_attention(q, k_all, v_all, pos, block_q=2, block_k=4,
                           interpret=True)
    frontier = pos + t
    k_poison = k_all.at[:, :, frontier:].set(1e6)
    v_poison = v_all.at[:, :, frontier:].set(-1e6)
    out2 = flash_attention(q, k_poison, v_poison, pos, block_q=2, block_k=4,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))


@pytest.mark.parametrize("pos", [0, 3])
@pytest.mark.parametrize("group", [1, 4])
def test_flash_prefill_q8_matches_dequant_oracle(pos, group):
    """Int8-KV flash kernel vs the XLA path over trace-level-dequantized
    buffers — identical quantized inputs, so the only difference is
    accumulation order."""
    from cake_tpu.ops.kvcache import dequant_kv, quant_kv
    from cake_tpu.ops.pallas import flash_attention_q8

    b, kvh, t, s, d = 2, 2, 8, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(3), b, h, kvh, t, s, d)
    kq, vq = quant_kv(k_all), quant_kv(v_all)
    ref = attend(q, dequant_kv(kq, q.dtype), dequant_kv(vq, q.dtype), pos,
                 impl="xla")
    out = flash_attention_q8(q, kq.q, kq.scale, vq.q, vq.scale, pos,
                             block_q=4, block_k=8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_prefill_q8_ignores_future_kv():
    from cake_tpu.ops.kvcache import quant_kv
    from cake_tpu.ops.pallas import flash_attention_q8

    b, kvh, group, t, s, d = 1, 2, 2, 4, 16, 8
    h = kvh * group
    pos = 2
    q, k_all, v_all = _qkv(jax.random.PRNGKey(4), b, h, kvh, t, s, d)
    kq, vq = quant_kv(k_all), quant_kv(v_all)
    out1 = flash_attention_q8(q, kq.q, kq.scale, vq.q, vq.scale, pos,
                              block_q=2, block_k=4, interpret=True)
    frontier = pos + t
    kq2 = quant_kv(k_all.at[:, :, frontier:].set(1e6))
    vq2 = quant_kv(v_all.at[:, :, frontier:].set(-1e6))
    out2 = flash_attention_q8(q, kq2.q, kq2.scale, vq2.q, vq2.scale, pos,
                              block_q=2, block_k=4, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))


def test_int8_kv_long_prefill_routes_to_q8_kernel(monkeypatch):
    """With an int8 cache and a flash-regime window, self_attention_block
    dispatches the quantization-aware kernel (never plain flash, whose
    operand would be a materialized bf16 KV buffer)."""
    import cake_tpu.ops.attention as attn
    from cake_tpu.ops import pallas as pk
    from cake_tpu.ops.attention import PREFILL_FLASH_MIN_S, PREFILL_FLASH_MIN_T

    monkeypatch.setattr(pk, "kernels_enabled", lambda: True)
    monkeypatch.setattr(pk, "force_kernels", lambda: False)
    monkeypatch.setattr(pk, "interpret_default", lambda: True)
    calls = []
    monkeypatch.setattr(
        attn.pk, "flash_attention_q8",
        lambda q, kq, ks, vq, vs, pos, **kw: (calls.append("q8"), q)[1])
    from cake_tpu.ops.kvcache import init_cache
    from cake_tpu.models.config import tiny

    cfg = tiny(max_seq_len=PREFILL_FLASH_MIN_S)
    cache = init_cache(cfg, batch=1, max_seq=PREFILL_FLASH_MIN_S,
                       quant="int8")
    x = jnp.zeros((1, PREFILL_FLASH_MIN_T, cfg.hidden_size), jnp.bfloat16)
    wq = jnp.zeros((cfg.hidden_size,
                    cfg.num_attention_heads * cfg.head_dim), jnp.bfloat16)
    wkv = jnp.zeros((cfg.hidden_size,
                     cfg.num_key_value_heads * cfg.head_dim), jnp.bfloat16)
    wo = jnp.zeros((cfg.num_attention_heads * cfg.head_dim,
                    cfg.hidden_size), jnp.bfloat16)
    from cake_tpu.ops.rope import rope_tables

    cos, sin = rope_tables(cfg.head_dim, PREFILL_FLASH_MIN_S,
                           cfg.rope_theta)
    attn.self_attention_block(
        x, wq, wkv, wkv, wo, jax.tree.map(lambda a: a[0], cache.k),
        jax.tree.map(lambda a: a[0], cache.v), cos, sin, jnp.int32(0),
        cfg.num_attention_heads, cfg.num_key_value_heads,
    )
    assert calls == ["q8"]


@pytest.mark.parametrize("pos", [0, 5, 30])
@pytest.mark.parametrize("group", [1, 4])
def test_flash_decode_matches_xla(pos, group):
    b, kvh, s, d = 1, 2, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(2), b, h, kvh, 1, s, d)
    ref = attend(q, k_all, v_all, pos)
    out = flash_decode(q, k_all, v_all, pos, block_k=8, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_decode_bf16():
    b, kvh, group, s, d = 1, 2, 4, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(3), b, h, kvh, 1, s, d,
                           dtype=jnp.bfloat16)
    ref = attend(q, k_all, v_all, 7)
    out = flash_decode(q, k_all, v_all, 7, block_k=8, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_flash_decode_per_row_positions():
    """pos [B]: each batch row attends to its own causal frontier (the
    multi-stream serving path) — parity with per-row XLA attention and with
    per-row single-stream kernel calls."""
    b, kvh, group, s, d = 3, 2, 2, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(6), b, h, kvh, 1, s, d)
    pos = jnp.asarray([2, 17, 30], jnp.int32)
    out = flash_decode(q, k_all, v_all, pos, block_k=8, interpret=True)
    ref = attend(q, k_all, v_all, pos, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    for i in range(b):
        one = flash_decode(q[i:i + 1], k_all[i:i + 1], v_all[i:i + 1],
                           int(pos[i]), block_k=8, interpret=True)
        np.testing.assert_allclose(np.asarray(out[i:i + 1]), np.asarray(one),
                                   rtol=1e-5, atol=1e-5)


def test_flash_under_jit_static_pos_variants():
    """pos is a traced scalar: one compile serves every position."""
    b, kvh, group, s, d = 1, 1, 2, 16, 8
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(4), b, h, kvh, 1, s, d)

    @jax.jit
    def step(q, k, v, pos):
        return flash_decode(q, k, v, pos, block_k=4, interpret=True)

    for pos in (0, 3, 11):
        ref = attend(q, k_all, v_all, pos)
        out = step(q, k_all, v_all, jnp.int32(pos))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)




def test_generator_greedy_parity_with_kernels(monkeypatch, tiny_config, tiny_params):
    """End-to-end: the full generator produces identical greedy tokens with
    Pallas kernels forced on (interpreted) vs the XLA path."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    prompt = [1, 5, 9, 2]

    def run():
        gen = LlamaGenerator(
            tiny_config, tiny_params,
            settings=SamplerSettings(temperature=0.0), max_seq=64,
        )
        gen.set_prompt(prompt)
        return [gen.next_token(i).id for i in range(6)]

    monkeypatch.setenv("CAKE_PALLAS", "0")
    ids_xla = run()
    monkeypatch.setenv("CAKE_PALLAS", "1")
    ids_flash = run()
    assert ids_xla == ids_flash


def test_dispatch_policy(monkeypatch):
    from cake_tpu.ops import pallas as pk

    monkeypatch.setenv("CAKE_PALLAS", "0")
    assert not pk.kernels_enabled()
    monkeypatch.setenv("CAKE_PALLAS", "1")
    assert pk.kernels_enabled()
    assert pk.force_kernels()
    monkeypatch.setenv("CAKE_PALLAS", "auto")
    assert not pk.force_kernels()
    assert pk.kernels_enabled() == (jax.default_backend() == "tpu")


def test_auto_dispatch_measured_crossover(monkeypatch):
    """impl='auto' follows the measured crossover (tools/flash_sweep.py on
    v5e): prefill routes to flash only from S >= PREFILL_FLASH_MIN_S; decode
    and short-context prefill run XLA, where the sweep says XLA wins.
    CAKE_PALLAS=1 still forces the kernels everywhere."""
    import cake_tpu.ops.attention as attn
    from cake_tpu.ops import pallas as pk
    from cake_tpu.ops.attention import (
        PREFILL_FLASH_MIN_S,
        PREFILL_FLASH_MIN_T,
        attend,
    )

    monkeypatch.setattr(pk, "kernels_enabled", lambda: True)
    monkeypatch.setattr(pk, "force_kernels", lambda: False)
    monkeypatch.setattr(pk, "interpret_default", lambda: True)
    calls = []
    monkeypatch.setattr(
        attn.pk, "flash_attention",
        lambda q, k, v, pos, **kw: (calls.append("prefill"), q)[1])
    monkeypatch.setattr(
        attn.pk, "flash_decode",
        lambda q, k, v, pos, **kw: (calls.append("decode"), q)[1])

    b, h, kvh, d = 1, 2, 1, 8
    key = jax.random.PRNGKey(0)

    def run(t, s):
        q = jax.random.normal(key, (b, h, t, d), jnp.bfloat16)
        k = jax.random.normal(key, (b, kvh, s, d), jnp.bfloat16)
        v = jax.random.normal(key, (b, kvh, s, d), jnp.bfloat16)
        attend(q, k, v, jnp.int32(s - t - 1))

    run(PREFILL_FLASH_MIN_T, PREFILL_FLASH_MIN_S)  # long prefill -> flash
    assert calls == ["prefill"]
    calls.clear()
    run(PREFILL_FLASH_MIN_T, PREFILL_FLASH_MIN_S // 2)  # short -> XLA
    run(8, PREFILL_FLASH_MIN_S)  # tiny T (speculative verify) -> XLA
    run(1, 4096)  # decode -> XLA at any S
    assert calls == []
    monkeypatch.setattr(pk, "force_kernels", lambda: True)
    run(1, 512)  # forced -> flash decode regardless of the crossover
    assert calls == ["decode"]


@pytest.mark.parametrize("pos", [0, 5])
@pytest.mark.parametrize("window", [3, 8, 17, 1000])
def test_flash_prefill_windowed_matches_xla(pos, window):
    """Sliding-window flash prefill vs the windowed XLA oracle — windows
    smaller than / spanning / exceeding the block size, and far larger
    than the history (degenerates to full causal)."""
    from cake_tpu.ops.attention import _attend_xla

    b, kvh, group, t, s, d = 2, 2, 4, 8, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(2), b, h, kvh, t, s, d,
                           pos=pos)
    ref = _attend_xla(q, k_all, v_all, pos, window=window)
    out = flash_attention(q, k_all, v_all, pos, block_q=4, block_k=8,
                          window=window, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_windowed_skips_out_of_window_blocks():
    """A KV block entirely below the window must not influence the
    output: poison it with NaNs and require a finite, oracle-exact
    result (proves the block skip is real, not just masking)."""
    from cake_tpu.ops.attention import _attend_xla

    b, h, kvh, t, s, d = 1, 2, 2, 4, 32, 16
    pos, window = 20, 4
    q, k_all, v_all = _qkv(jax.random.PRNGKey(3), b, h, kvh, t, s, d)
    # rows [0, 8) are >= window behind every query (frontier 20..23):
    # two full 8-wide blocks below the lower bound
    k_all = k_all.at[:, :, :8, :].set(jnp.nan)
    v_all = v_all.at[:, :, :8, :].set(jnp.nan)
    out = flash_attention(q, k_all, v_all, pos, block_q=4, block_k=8,
                          window=window, interpret=True)
    assert bool(jnp.isfinite(out).all())
    ref = _attend_xla(
        q, jnp.nan_to_num(k_all), jnp.nan_to_num(v_all), pos, window=window
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_windowed_prefill_dispatch(monkeypatch):
    """attend() with a window routes long prefill to the flash kernel at
    the measured crossover and decode/per-row to XLA."""
    import cake_tpu.ops.attention as A

    calls = []
    real = A.pk.flash_attention

    def spy(*a, **kw):
        calls.append(kw.get("window"))
        return real(*a, interpret=True, **kw)

    monkeypatch.setattr(A.pk, "flash_attention", spy)
    monkeypatch.setattr(A.pk, "kernels_enabled", lambda: True)
    monkeypatch.setattr(A, "PREFILL_FLASH_MIN_S", 32)
    monkeypatch.setattr(A, "PREFILL_FLASH_MIN_T", 8)
    monkeypatch.setattr(A, "_flash_ok", lambda t, s, d: True)
    b, h, kvh, t, s, d = 1, 2, 2, 8, 32, 16
    q, k_all, v_all = _qkv(jax.random.PRNGKey(4), b, h, kvh, t, s, d)
    A.attend(q, k_all, v_all, 0, window=8)
    assert calls == [8]
    # decode with window: auto stays XLA (no prefill-kernel call) until a
    # measured win flips it...
    q1 = q[:, :, :1, :]
    xla_out = A.attend(q1, k_all, v_all, 20, window=8)
    assert calls == [8]
    # ...but an explicit impl='flash' reaches the windowed decode kernel
    flash_out = A.attend(q1, k_all, v_all, 20, window=8, impl="flash")
    np.testing.assert_allclose(np.asarray(flash_out), np.asarray(xla_out),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pos", [6, 20, 31])
@pytest.mark.parametrize("window", [3, 8, 17, 1000])
def test_flash_decode_windowed_matches_xla(pos, window):
    from cake_tpu.ops.attention import _attend_xla

    b, kvh, group, s, d = 2, 2, 4, 32, 16
    h = kvh * group
    q, k_all, v_all = _qkv(jax.random.PRNGKey(5), b, h, kvh, 1, s, d)
    ref = _attend_xla(q, k_all, v_all, pos, window=window)
    out = flash_decode(q, k_all, v_all, pos, block_k=8, window=window,
                       interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_decode_windowed_per_row_and_block_skip():
    """Per-row frontiers with a window: each row's lower bound is its own;
    NaN-poisoned out-of-window blocks must not leak (real skip)."""
    from cake_tpu.ops.attention import _attend_xla

    b, kvh, group, s, d = 2, 2, 2, 32, 16
    h = kvh * group
    window = 4
    pos = jnp.asarray([20, 29], jnp.int32)
    q, k_all, v_all = _qkv(jax.random.PRNGKey(6), b, h, kvh, 1, s, d)
    # rows far below both windows: blocks [0, 16) dead for both rows
    k_all = k_all.at[:, :, :16, :].set(jnp.nan)
    v_all = v_all.at[:, :, :16, :].set(jnp.nan)
    out = flash_decode(q, k_all, v_all, pos, block_k=8, window=window,
                       interpret=True)
    assert bool(jnp.isfinite(out).all())
    ref = _attend_xla(q, jnp.nan_to_num(k_all), jnp.nan_to_num(v_all), pos,
                      window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("window", [3, 8, 17])
def test_flash_prefill_q8_windowed_matches_dequant_oracle(window):
    """Windowed int8-KV flash prefill vs the windowed XLA path over the
    same quantized buffers (Mistral long-context on the quantized cache)."""
    from cake_tpu.ops.attention import _attend_xla
    from cake_tpu.ops.kvcache import dequant_kv, quant_kv
    from cake_tpu.ops.pallas import flash_attention_q8

    b, kvh, group, t, s, d = 2, 2, 4, 8, 32, 16
    h = kvh * group
    pos = 5
    q, k_all, v_all = _qkv(jax.random.PRNGKey(7), b, h, kvh, t, s, d)
    kq, vq = quant_kv(k_all), quant_kv(v_all)
    ref = _attend_xla(q, dequant_kv(kq, q.dtype), dequant_kv(vq, q.dtype),
                      pos, window=window)
    out = flash_attention_q8(q, kq.q, kq.scale, vq.q, vq.scale, pos,
                             block_q=4, block_k=8, window=window,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
