import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.utils.weights import (
    load_llama_params,
    save_llama_params,
    load_safetensors_index,
)


def test_safetensors_roundtrip(tmp_path):
    cfg = tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    save_llama_params(params, tmp_path)
    loaded = load_llama_params(tmp_path, cfg.num_hidden_layers, dtype="float32")
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0),
        params,
        loaded,
    )


def test_layer_range_loads_slice(tmp_path):
    cfg = tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    save_llama_params(params, tmp_path)
    part = load_llama_params(
        tmp_path, cfg.num_hidden_layers, dtype="float32",
        layer_range=(1, 3), include_embed=False, include_head=False,
    )
    assert "embed" not in part and "lm_head" not in part
    assert part["layers"]["wq"].shape[0] == 2
    np.testing.assert_allclose(
        np.asarray(part["layers"]["wq"]),
        np.asarray(params["layers"]["wq"][1:3]),
        atol=0,
    )


def test_index_resolution(tmp_path):
    cfg = tiny(num_hidden_layers=2)
    params = llama.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    save_llama_params(params, tmp_path)
    index = load_safetensors_index(tmp_path)
    assert "model.embed_tokens.weight" in index
    assert "model.layers.1.mlp.down_proj.weight" in index
