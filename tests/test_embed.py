"""Embeddable worker API (the reference's UniFFI surface, cake-ios/src/lib.rs).

Covers the Python entry (spawn_worker against a real model dir on disk: load
assigned layers, handshake, serve one op) and the C shim build contract
(exported symbols of native/cake_embed.cc).
"""

import ctypes
import json
import shutil
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest
import yaml

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.utils.weights import save_llama_params

CFG = tiny(max_seq_len=32)
REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    """Model dir + topology file, like an embedding app would ship."""
    d = tmp_path_factory.mktemp("embed")
    params = llama.init_params(CFG, jax.random.PRNGKey(1), dtype="float32")
    model_dir = d / "model"
    save_llama_params(params, model_dir)
    (model_dir / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    topo = d / "topology.yml"
    topo.write_text(yaml.safe_dump(
        {"phone": {"host": "127.0.0.1:0", "layers": ["model.layers.0-3"]}}
    ))
    return model_dir, topo


def test_spawn_worker_serves(bundle):
    from cake_tpu import embed
    from cake_tpu.runtime import protocol, wire
    from cake_tpu.runtime.protocol import MsgType, WorkerInfo

    model_dir, topo = bundle
    h = embed.spawn_worker("phone", str(model_dir), str(topo),
                           address="127.0.0.1:0")
    try:
        conn = wire.connect("127.0.0.1", h.port)
        conn.send(MsgType.HELLO)
        t, payload = conn.recv()
        assert t == MsgType.WORKER_INFO
        info = WorkerInfo.from_bytes(payload)
        assert info.name == "phone"
        assert info.layers == [f"model.layers.{i}" for i in range(4)]
        x = np.zeros((1, 1, CFG.hidden_size), np.float32)
        conn.send(MsgType.BATCH,
                  protocol.encode_ops(x, [("model.layers.0", 0)]))
        t, payload = conn.recv()
        assert t == MsgType.TENSOR
        conn.close()
    finally:
        h.shutdown()


def test_spawn_worker_unknown_name_raises(bundle):
    from cake_tpu import embed

    model_dir, topo = bundle
    with pytest.raises(ValueError, match="not present"):
        embed.spawn_worker("nope", str(model_dir), str(topo),
                           address="127.0.0.1:0")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_c_shim_exports(tmp_path):
    """The C embedding library builds and exports the stable C ABI."""
    pycfg = next(
        (c for c in (sys.executable + "-config", "python3-config")
         if shutil.which(c)), None,
    )
    if pycfg is None:
        pytest.skip("python-config unavailable")
    cfg = subprocess.run([pycfg, "--includes"], capture_output=True, text=True)
    ld = subprocess.run([pycfg, "--ldflags", "--embed"],
                        capture_output=True, text=True)
    so = tmp_path / "libcakeembed.so"
    cmd = (
        ["g++", "-O2", "-fPIC", "-shared", "-o", str(so),
         str(REPO / "native" / "cake_embed.cc")]
        + cfg.stdout.split() + ld.stdout.split()
    )
    r = subprocess.run(cmd, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    lib = ctypes.CDLL(str(so))
    assert lib.cake_worker_api_version() == 1
    assert hasattr(lib, "cake_start_worker")
