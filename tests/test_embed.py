"""Embeddable worker API (the reference's UniFFI surface, cake-ios/src/lib.rs).

Covers the Python entry (spawn_worker against a real model dir on disk: load
assigned layers, handshake, serve one op) and the C shim build contract
(exported symbols of native/cake_embed.cc).
"""

import ctypes
import json
import os
import shutil
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest
import yaml

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.utils.weights import save_llama_params

CFG = tiny(max_seq_len=32)
REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    """Model dir + topology file, like an embedding app would ship."""
    d = tmp_path_factory.mktemp("embed")
    params = llama.init_params(CFG, jax.random.PRNGKey(1), dtype="float32")
    model_dir = d / "model"
    save_llama_params(params, model_dir)
    (model_dir / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    topo = d / "topology.yml"
    topo.write_text(yaml.safe_dump(
        {"phone": {"host": "127.0.0.1:0", "layers": ["model.layers.0-3"]}}
    ))
    return model_dir, topo


def test_spawn_worker_serves(bundle):
    from cake_tpu import embed
    from cake_tpu.runtime import protocol, wire
    from cake_tpu.runtime.protocol import MsgType, WorkerInfo

    model_dir, topo = bundle
    h = embed.spawn_worker("phone", str(model_dir), str(topo),
                           address="127.0.0.1:0")
    try:
        conn = wire.connect("127.0.0.1", h.port)
        conn.send(MsgType.HELLO)
        t, payload = conn.recv()
        assert t == MsgType.WORKER_INFO
        info = WorkerInfo.from_bytes(payload)
        assert info.name == "phone"
        assert info.layers == [f"model.layers.{i}" for i in range(4)]
        x = np.zeros((1, 1, CFG.hidden_size), np.float32)
        conn.send(MsgType.BATCH,
                  protocol.encode_ops(x, [("model.layers.0", 0)]))
        t, payload = conn.recv()
        assert t == MsgType.TENSOR
        conn.close()
    finally:
        h.shutdown()


def test_spawn_worker_unknown_name_raises(bundle):
    from cake_tpu import embed

    model_dir, topo = bundle
    with pytest.raises(ValueError, match="not present"):
        embed.spawn_worker("nope", str(model_dir), str(topo),
                           address="127.0.0.1:0")


def _build_embed_lib(tmp_path):
    """Build libcakeembed.so; returns its path (or skips the test)."""
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    pycfg = next(
        (c for c in (sys.executable + "-config", "python3-config")
         if shutil.which(c)), None,
    )
    if pycfg is None:
        pytest.skip("python-config unavailable")
    cfg = subprocess.run([pycfg, "--includes"], capture_output=True, text=True)
    ld = subprocess.run([pycfg, "--ldflags", "--embed"],
                        capture_output=True, text=True)
    so = tmp_path / "libcakeembed.so"
    cmd = (
        ["g++", "-O2", "-fPIC", "-shared", "-o", str(so),
         str(REPO / "native" / "cake_embed.cc")]
        + cfg.stdout.split() + ld.stdout.split()
    )
    r = subprocess.run(cmd, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return so


def test_c_shim_exports(tmp_path):
    """The C embedding library builds and exports the stable C ABI."""
    so = _build_embed_lib(tmp_path)
    lib = ctypes.CDLL(str(so))
    assert lib.cake_worker_api_version() == 1
    assert hasattr(lib, "cake_start_worker")


def test_c_host_serves_op_end_to_end(bundle, tmp_path):
    """A real C host (native/cake_host_demo.c — the reference's runnable
    worker app, ContentView.swift:28-56) links the embed library, calls
    cake_start_worker through the C ABI, and serves a layer op to a Python
    client over the wire."""
    import socket
    import time

    from cake_tpu.runtime import protocol, wire
    from cake_tpu.runtime.protocol import MsgType, WorkerInfo

    so = _build_embed_lib(tmp_path)
    gcc = shutil.which("gcc") or shutil.which("g++")
    host_bin = tmp_path / "cake_host_demo"
    r = subprocess.run(
        [gcc, "-O2", "-o", str(host_bin),
         str(REPO / "native" / "cake_host_demo.c"),
         f"-L{tmp_path}", "-lcakeembed", f"-Wl,-rpath,{tmp_path}"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr

    # pick a free port for the host to bind
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    model_dir, topo = bundle
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)  # embedded CPython must find cake_tpu
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [str(host_bin), "phone", str(model_dir), str(topo),
         f"127.0.0.1:{port}"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        conn = None
        for _ in range(120):  # embedded interpreter + jax import takes a bit
            if proc.poll() is not None:
                out, err = proc.communicate()
                pytest.fail(f"host exited early rc={proc.returncode}: "
                            f"{err.decode()[-2000:]}")
            try:
                conn = wire.connect("127.0.0.1", port, timeout_ms=1000)
                break
            except Exception:
                time.sleep(0.5)
        assert conn is not None, "host never started listening"
        conn.send(MsgType.HELLO)
        t, payload = conn.recv()
        assert t == MsgType.WORKER_INFO
        assert WorkerInfo.from_bytes(payload).name == "phone"
        x = np.zeros((1, 1, CFG.hidden_size), np.float32)
        conn.send(MsgType.BATCH,
                  protocol.encode_ops(x, [("model.layers.0", 0)]))
        # the connection's default recv deadline is the 1s connect timeout
        # (fine for the instant HELLO reply above); the first op compiles
        # in the embedded interpreter, so give it the op-scale headroom a
        # real master would (--op-timeout semantics)
        t, payload = conn.recv(timeout=180.0)
        assert t == MsgType.TENSOR
        assert protocol.decode_tensor(payload).shape == x.shape
        conn.close()
    finally:
        proc.terminate()
        proc.wait(timeout=30)
