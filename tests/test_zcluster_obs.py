"""Cluster-wide observability: trace stitching, clock alignment, metrics
aggregation, straggler detection.

Covers the cross-process tier on top of cake_tpu/obs: NTP-style clock
offset estimation (obs.clock), the trailer-based trace-context propagation
and span-digest stitching over the OPS wire (protocol/worker/runner), the
merged multi-process Perfetto export (obs.trace), the cluster scraper with
straggler flagging (obs.cluster), the shared status HTTP surface
(obs.statusd), and artifact durability on signals. The loopback smoke at
the bottom is `make cluster-trace-smoke`.

(Named with a z-prefix on purpose: this is the heaviest loopback suite in
the tree and the tier-1 run is wall-clock budgeted — it must sort after
the fast unit suites, not displace them.)
"""

import json
import os
import signal
import time
import urllib.request

import jax
import numpy as np
import pytest

from cake_tpu import obs
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import flight, metrics, trace
from cake_tpu.obs.clock import ClockSync
from cake_tpu.obs.cluster import ClusterScraper, HttpSource
from cake_tpu.obs import top as obs_top
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import protocol
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.worker import Worker

CFG = tiny(max_seq_len=32)


# -- clock alignment ---------------------------------------------------------

def test_clock_offset_min_of_n_beats_noisy_samples():
    """Synthetic skewed clocks: the worker runs 123.456s ahead; network
    delay is asymmetric on most samples. The min-RTT sample must win and
    bound the offset error by its own asymmetry, not the worst one's."""
    D = 123.456
    cs = ClockSync()
    t = 10.0
    # (outbound delay, inbound delay) per ping; the 0.5ms symmetric pair
    # has the smallest RTT and zero asymmetry error
    for out, inn in [(0.004, 0.020), (0.0005, 0.0005), (0.010, 0.002)]:
        t0 = t
        tw = t0 + out + D
        t1 = t0 + out + inn
        cs.add(t0, tw, t1)
        t += 1.0
    assert cs.synced
    assert cs.rtt_s == pytest.approx(0.001)
    assert cs.offset_s == pytest.approx(D, abs=1e-9)
    snap = cs.snapshot()
    assert snap["samples"] == 3 and snap["rtt_ms"] == pytest.approx(1.0)

    # rebasing keeps worker-side ordering and lands on the master timeline
    worker_times = [D + 11.0, D + 11.001, D + 11.5]
    rebased = [cs.to_master(tw) for tw in worker_times]
    assert rebased == sorted(rebased)
    for tw, tm in zip(worker_times, rebased):
        assert tm == pytest.approx(tw - D, abs=1e-9)


def test_clock_offset_error_bounded_by_asymmetry():
    """With only asymmetric samples the estimate is off by at most half
    the best sample's RTT — the Cristian bound the merge step relies on."""
    D = -7.5  # worker behind the master
    cs = ClockSync()
    out, inn = 0.003, 0.001  # 1ms asymmetry -> <=1ms offset error
    cs.add(5.0, 5.0 + out + D, 5.0 + out + inn)
    assert abs(cs.offset_s - D) <= (out + inn) / 2
    with pytest.raises(ValueError, match="non-causal"):
        cs.add(1.0, 0.0, 0.5)


# -- merged multi-process trace export ---------------------------------------

def test_trace_merge_emits_multiprocess_perfetto_doc():
    tr = trace.tracer()
    tr.start()
    try:
        with trace.span("decode.step", index=1):
            with trace.span("segment.remote_rtt", addr="w1:1"):
                pass
        base = time.perf_counter()
        tr.record_remote("w1@h:1", "ops.handle", base, 0.001,
                         {"seq": 1, "trace_id": tr.trace_id})
        tr.record_remote("w2@h:2", "ops.handle", base + 0.002, 0.001,
                         {"seq": 1})
    finally:
        tr.stop()
    doc = json.loads(json.dumps(tr.to_chrome_trace()))  # JSON round-trip
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert all(e["ph"] in ("X", "M") for e in evs)
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    # one pid per process: the master plus each stitched-in worker
    pids = {e["pid"] for e in xs}
    assert len(pids) == 3 and os.getpid() in pids
    pnames = {e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"w1@h:1", "w2@h:2"} <= pnames
    # the local span ids feed trace propagation
    assert trace.current_span_id() == 0
    assert tr.trace_id and len(tr.trace_id) == 16
    tr.clear()


# -- OPS trailer: byte compatibility + round trip ----------------------------

@pytest.mark.parametrize("codec", ["none", "bf16", "int8"])
def test_ops_trailer_roundtrip_and_legacy_bytes(codec):
    """No trace context -> byte-identical legacy frames; with one, the
    trailer rides after the self-describing tensor and strips back off."""
    x = np.arange(12, dtype=np.float32).reshape(1, 3, 4)
    ops = [("model.layers.0", 3), ("model.layers.1", 3)]
    legacy = (
        protocol.encode_ops(x, ops, codec)
        if codec != "none"
        else b"".join(
            [len(json.dumps(ops).encode()).to_bytes(4, "little"),
             json.dumps(ops).encode(), protocol.encode_tensor(x)]
        )
    )
    assert protocol.encode_ops(x, ops, codec) == legacy

    tc = {"tid": "ab" * 8, "psid": 7, "seq": 42, "pos": 3}
    framed = protocol.encode_ops(x, ops, codec, trace_ctx=tc)
    assert framed.startswith(legacy) and len(framed) > len(legacy)
    x2, ops2, codec2, trailer = protocol.decode_ops_traced(framed)
    assert ops2 == ops and codec2 == codec and trailer == {"tc": tc}
    assert x2.shape == x.shape
    if codec == "none":
        np.testing.assert_array_equal(x2, x)
    # the trailer-blind decoder (old peers' code path) still works
    x3, ops3, codec3 = protocol.decode_ops(framed)
    assert ops3 == ops and codec3 == codec
    # reply-side split: activation + digest trailer
    digest = {"digest": {"name": "w", "seq": 42, "spans": [["ops.handle",
                                                            1.0, 0.5]]}}
    reply = protocol.encode_activation(x, codec) + json.dumps(digest).encode()
    act, tr2 = protocol.split_activation(reply)
    assert tr2 == digest
    out, got = protocol.decode_activation(act)
    assert got == codec and out.shape == x.shape
    act3, tr3 = protocol.split_activation(protocol.encode_activation(x, codec))
    assert tr3 is None and len(act3) == protocol.activation_nbytes(act3)


def test_worker_info_caps_default_empty_for_old_peer():
    import dataclasses

    d = dataclasses.asdict(protocol.WorkerInfo(name="old"))
    d.pop("caps")
    got = protocol.WorkerInfo.from_bytes(json.dumps(d).encode())
    assert got.caps == []
    assert set(protocol.ALL_CAPS) == {"trace", "ping", "stats"}


# -- straggler detection -----------------------------------------------------

class _FakeSource:
    def __init__(self, name, p99, rtt_ms=1.0, up=True):
        self.name, self.addr, self._p99, self._up = name, f"{name}:1", p99, up
        self._rtt = rtt_ms

    def fetch(self):
        if not self._up:
            return None
        return {
            "name": self.name, "layer_runs": [[0, 2]], "ops_total": 10,
            "bytes_in": 1000, "bytes_out": 1000, "connections_live": 1,
            "uptime_s": 5.0,
            "forward_ms": {"count": 10, "p50": self._p99 / 2,
                           "p99": self._p99},
        }

    def link(self):
        return {"rtt_ms": self._rtt, "clock_offset_ms": 0.5}


def test_straggler_flagged_on_synthetic_slow_worker():
    reg = metrics.Registry(enabled=True)
    scraper = ClusterScraper(
        [_FakeSource("a", 2.0), _FakeSource("b", 2.2),
         _FakeSource("slow", 40.0), _FakeSource("dead", 1.0, up=False)],
        straggler_factor=2.0, registry=reg,
    )
    rep = scraper.scrape()
    assert rep["stragglers"] == ["slow"]
    assert rep["workers"]["slow"]["straggler"] is True
    assert rep["workers"]["a"]["straggler"] is False
    assert rep["workers"]["dead"]["up"] is False
    assert rep["median_forward_p99_ms"] == pytest.approx(2.2)
    snap = reg.snapshot(prefix="cluster.")
    assert snap["cluster.slow.straggler"]["value"] == 1
    assert snap["cluster.a.straggler"]["value"] == 0
    assert snap["cluster.slow.forward_p99_ms"]["value"] == 40.0
    assert snap["cluster.workers_up"]["value"] == 3
    assert snap["cluster.stragglers"]["value"] == 1
    assert snap["cluster.dead.up"]["value"] == 0
    # the live panel renders every state without a terminal
    frame = obs_top.render(rep)
    assert "slow" in frame and "SLOW" in frame and "DOWN" in frame
    assert "stragglers: slow" in frame

    with pytest.raises(ValueError, match="straggler factor"):
        ClusterScraper([], straggler_factor=1.0, registry=reg)


def test_top_refresher_repaints_in_place():
    """The --top thread: frames land on the stream with ANSI cursor-up
    rewrites between them, and stop() leaves a final frame behind."""
    import io

    reg = metrics.Registry(enabled=True)
    scraper = ClusterScraper([_FakeSource("a", 2.0)], straggler_factor=2.0,
                             registry=reg)
    out = io.StringIO()
    view = obs_top.Top(scraper, out=out, interval_s=0.01)
    view.start()
    time.sleep(0.08)
    view.stop()
    text = out.getvalue()
    assert text.count("WORKER") >= 2  # repainted at least once
    assert "\x1b[" in text  # in-place rewrite, not a scrolling log
    assert "a" in text


def test_two_workers_cannot_both_outrun_median_times_two():
    """With N=2 the median is the mean: no worker can exceed 2x it, so
    flagging needs either N>=3 or a sub-2 factor — pin the N>=2 guard."""
    reg = metrics.Registry(enabled=True)
    rep = ClusterScraper([_FakeSource("a", 1.0), _FakeSource("b", 30.0)],
                         straggler_factor=1.5, registry=reg).scrape()
    assert rep["stragglers"] == ["b"]
    rep = ClusterScraper([_FakeSource("only", 9.0)],
                         straggler_factor=1.5, registry=reg).scrape()
    assert rep["stragglers"] == []  # a cluster of one has no stragglers


# -- shared status HTTP surface (master /metrics parity) ---------------------

def test_statusd_serves_json_and_prometheus():
    from cake_tpu.obs import statusd

    metrics.registry().gauge("cluster.wtest.up").set(1)
    httpd, port = statusd.start_status_server(
        lambda: {"role": "master", "metrics": {"x": 1}})
    try:
        assert httpd.server_address[0] == "127.0.0.1"  # loopback default
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/",
                                    timeout=10) as r:
            assert r.headers["Content-Type"] == "application/json"
            st = json.loads(r.read())
        assert st["role"] == "master"
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                    timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            prom = r.read().decode()
        # the merged cluster series ride the same exposition
        assert "cake_cluster_wtest_up 1" in prom
    finally:
        httpd.shutdown()
        httpd.server_close()
        metrics.registry().unregister("cluster.wtest.up")


# -- artifact durability on signals ------------------------------------------

def test_flush_handlers_land_artifacts_on_sigint(tmp_path):
    rec = flight.recorder()
    fl = tmp_path / "flight.jsonl"
    mt = tmp_path / "metrics.json"
    prev = {s: signal.getsignal(s) for s in (signal.SIGINT, signal.SIGTERM)}
    rec.enable(path=str(fl))
    try:
        rec.record(index=0, kind="decode", total_ms=1.0)
        assert fl.read_text() == ""  # batched: nothing on disk yet
        obs.install_flush_handlers(metrics_out=str(mt))
        with pytest.raises(KeyboardInterrupt):
            signal.raise_signal(signal.SIGINT)  # chains to the default
        lines = fl.read_text().splitlines()
        assert len(lines) == 1 and json.loads(lines[0])["kind"] == "decode"
        assert isinstance(json.loads(mt.read_text()), dict)
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        obs._flush_state["metrics_out"] = None
        rec.close()
        rec.clear()


# -- loopback: old peer negotiation ------------------------------------------

@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(11))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def _head(params):
    return {k: params[k] for k in ("embed", "norm_f", "lm_head")}


def test_old_peer_handshake_gets_no_trailer_and_no_pings(params, monkeypatch):
    """A worker whose handshake advertises no caps (the old-peer wire
    dialect) must see byte-for-byte legacy op frames even from a tracing
    master: no trace trailer, no PING/STATS frames, reply digest absent."""
    w = Worker("w1", CFG, Topology.from_dict(
        {"w1": {"layers": ["model.layers.0-3"]}}), _loader(params),
        address="127.0.0.1:0", max_seq=CFG.max_seq_len)
    # strip the capability advertisement, exactly like a pre-caps peer
    # whose WorkerInfo JSON lacks the field
    real_info = w._info

    def old_info():
        info = real_info()
        info.caps = []
        return info

    monkeypatch.setattr(w, "_info", old_info)
    seen_trailers = []
    real_decode = protocol.decode_ops_traced

    def spy_decode(buf):
        out = real_decode(buf)
        seen_trailers.append(out[3])
        return out

    monkeypatch.setattr(protocol, "decode_ops_traced", spy_decode)
    w.serve_in_background()
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{w.port}",
               "layers": ["model.layers.0-3"]},
    })
    tr = trace.tracer()
    tr.start()
    try:
        runners = build_runners(CFG, topo, _loader(params))
        assert runners[0].caps == set()
        assert not runners[0].clock.synced  # no PING without the cap
        assert runners[0].fetch_stats() is None  # no STATS either
        g = DistributedGenerator(
            CFG, _head(params), runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        for i in range(3):
            g.next_token(i)
        # legacy link shape the CLI's segment log must format: handshake
        # RTT fallback present, no ping-estimated clock offset
        (s,) = g.runner_stats()
        assert "rtt_ms" in s and "clock_offset_ms" not in s
        g.close()
    finally:
        tr.stop()
        w.shutdown()
    assert seen_trailers and all(t is None for t in seen_trailers)
    # nothing got stitched: the merged trace has exactly one pid
    xs = [e for e in tr.to_chrome_trace()["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {os.getpid()}
    tr.clear()


def test_scraper_falls_back_to_http_for_worker_without_cap_stats(
        params, monkeypatch):
    """A peer that advertises a status page but not CAP_STATS is scraped
    over HTTP at its connection host instead of being reported DOWN; link
    health (RTT/offset) still comes from the master's own connection."""
    w = Worker("w1", CFG, Topology.from_dict(
        {"w1": {"layers": ["model.layers.0-3"]}}), _loader(params),
        address="127.0.0.1:0", max_seq=CFG.max_seq_len)
    w.start_status_server(0)  # loopback-bound, ephemeral; advertised in caps
    real_info = w._info

    def no_stats_cap():
        info = real_info()
        info.caps = [c for c in info.caps if c != protocol.CAP_STATS]
        return info

    monkeypatch.setattr(w, "_info", no_stats_cap)
    w.serve_in_background()
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{w.port}",
               "layers": ["model.layers.0-3"]},
    })
    try:
        runners = build_runners(CFG, topo, _loader(params))
        assert runners[0].fetch_stats() is None  # in-band path is gone
        assert runners[0].info.status_port == w._status_port > 0
        g = DistributedGenerator(
            CFG, _head(params), runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        for i in range(3):
            g.next_token(i)
        scraper = g.cluster_scraper()
        assert isinstance(scraper.sources[0], HttpSource)
        row = scraper.scrape()["workers"]["w1"]
        assert row["up"] is True and row["ops_total"] > 0
        assert row["forward_p50_ms"] > 0
        assert row["rtt_ms"] > 0 and row["clock_offset_ms"] is not None
        g.close()
    finally:
        w.shutdown()


def test_failed_clock_refresh_recovers_instead_of_desyncing(params):
    """A ping exchange that dies mid-flight poisons the connection's frame
    stream (a late PING reply would surface where the next forward expects
    its TENSOR). The runner must raise a wire fault so the master's normal
    reconnect+replay recovery runs deliberately — and after the reconnect,
    warmup classification must not reset (XLA's compile cache is
    per-process, not per-connection)."""
    w = Worker("w1", CFG, Topology.from_dict(
        {"w1": {"layers": ["model.layers.0-3"]}}), _loader(params),
        address="127.0.0.1:0", max_seq=CFG.max_seq_len)
    w.serve_in_background()
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{w.port}",
               "layers": ["model.layers.0-3"]},
    })
    try:
        runners = build_runners(CFG, topo, _loader(params))
        r = runners[0]
        g = DistributedGenerator(
            CFG, _head(params), runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        g.next_token(0)
        g.next_token(1)  # decode shape now compiled process-wide
        real_sync = r._sync_clock
        state = {"failed": False}

        def flaky(n=3):
            if not state["failed"]:
                state["failed"] = True
                raise OSError("simulated recv timeout mid-ping")
            return real_sync(n)

        r._sync_clock = flaky
        r._clock_refreshed = -1e9  # due for refresh on the next forward
        g.next_token(2)
        assert state["failed"]
        assert g.recoveries == 1  # deliberate reconnect+replay, no desync
        assert r.clock.synced  # the re-handshake re-synced the clock
        # post-recovery decode: the shape was already compiled in this
        # worker process, so it lands in the steady-state histogram and
        # leaves the warmup gauge alone
        warm_after = w._warm_gauge.value
        hist_after = w._fwd_hist.count
        g.next_token(3)
        assert w._fwd_hist.count == hist_after + 1
        assert w._warm_gauge.value == warm_after
        g.close()
    finally:
        w.shutdown()


def test_failed_stats_fetch_poisons_stream_and_recovers(params):
    """A STATS exchange that dies mid-flight (scraper thread) flags the
    connection; the NEXT forward raises a wire fault so the master's
    reconnect+replay runs deliberately — and a later scrape works again."""
    w = Worker("w1", CFG, Topology.from_dict(
        {"w1": {"layers": ["model.layers.0-3"]}}), _loader(params),
        address="127.0.0.1:0", max_seq=CFG.max_seq_len)
    w.serve_in_background()
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{w.port}",
               "layers": ["model.layers.0-3"]},
    })
    try:
        runners = build_runners(CFG, topo, _loader(params))
        r = runners[0]
        g = DistributedGenerator(
            CFG, _head(params), runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        g.next_token(0)
        real_recv = r.conn.recv
        r.conn.recv = lambda *a, **k: (_ for _ in ()).throw(
            OSError("simulated recv timeout mid-stats"))
        from cake_tpu.runtime import wire
        with pytest.raises(wire.WireError, match="mid-exchange"):
            r.fetch_stats()
        r.conn.recv = real_recv
        assert r._poisoned is not None
        g.next_token(1)  # wire fault -> reconnect + replay, not a desync
        assert g.recoveries == 1
        assert r._poisoned is None
        assert r.fetch_stats()["ops_total"] > 0  # stream is clean again
        g.close()
    finally:
        w.shutdown()


# -- loopback acceptance smoke (`make cluster-trace-smoke`) ------------------

def test_cluster_trace_smoke_two_workers(params):
    """2-worker CPU loopback with --trace semantics: ONE Perfetto-valid
    merged trace holding spans from >= 3 pids, worker `ops.handle` nested
    (after clock rebasing) inside the master's remote-segment span, and a
    cluster report naming every worker with per-segment p50/p99, RTT, and
    clock offset — plus a straggler flag on the artificially slowed one."""
    workers = []
    for name, rng in (("w1", "0-1"), ("w2", "2-3")):
        w = Worker(name, CFG, Topology.from_dict(
            {name: {"layers": [f"model.layers.{rng}"]}}), _loader(params),
            address="127.0.0.1:0", max_seq=CFG.max_seq_len)
        w.serve_in_background()
        workers.append(w)
    # make w2 a genuine straggler: every forward pays +50ms. The margin
    # must survive a loaded CI box: with 2 workers the median is the mean,
    # so factor f flags w2 only when slow > (f/(2-f)) * fast — at f=1.2
    # that is fast < 100ms steady-state, comfortably true for a 2-layer
    # tiny forward even under full-suite load.
    real_run = workers[1]._run_ops

    def slow_run(*a, **k):
        time.sleep(0.05)
        return real_run(*a, **k)

    workers[1]._run_ops = slow_run
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{workers[0].port}",
               "layers": ["model.layers.0-1"]},
        "w2": {"host": f"127.0.0.1:{workers[1].port}",
               "layers": ["model.layers.2-3"]},
    })
    tr = trace.tracer()
    tr.start()
    try:
        runners = build_runners(CFG, topo, _loader(params))
        for r in runners:
            assert r.clock.synced and r.clock.rtt_s > 0
        g = DistributedGenerator(
            CFG, _head(params), runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        for i in range(4):
            g.next_token(i)

        stats = g.runner_stats()
        assert all("rtt_ms" in s and "clock_offset_ms" in s for s in stats)

        report = g.cluster_report(straggler_factor=1.2)
        assert set(report["workers"]) == {"w1", "w2"}
        for name, row in report["workers"].items():
            assert row["up"] is True
            assert row["forward_p50_ms"] > 0
            assert row["forward_p99_ms"] >= row["forward_p50_ms"]
            assert row["rtt_ms"] > 0
            assert row["clock_offset_ms"] is not None
            assert row["ops_total"] > 0
        assert report["stragglers"] == ["w2"]
        assert report["workers"]["w2"]["straggler"] is True
        assert len(report["segments"]) == 2
        # the merged series joined the master registry for /metrics and
        # --metrics-out parity
        snap = metrics.registry().snapshot(prefix="cluster.")
        assert snap["cluster.w2.straggler"]["value"] == 1
        assert snap["cluster.w1.up"]["value"] == 1
        g.close()
    finally:
        tr.stop()
        for w in workers:
            w.shutdown()

    doc = json.loads(json.dumps(tr.to_chrome_trace()))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    master_pid = os.getpid()
    pids = {e["pid"] for e in xs}
    assert master_pid in pids and len(pids) >= 3
    # synthetic worker pids resolve to their 'name@addr' identities
    pid_src = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
               if e["ph"] == "M" and e["name"] == "process_name"}
    rtt_spans = [e for e in xs
                 if e["name"] == "segment.remote_rtt"
                 and e["pid"] == master_pid]
    handles = [e for e in xs
               if e["name"] == "ops.handle" and e["pid"] != master_pid]
    # every request produced a digest: 2 segments x (prefill + 3 decodes)
    assert len(handles) == len(rtt_spans) == 8
    for h in handles:
        addr = pid_src[h["pid"]].split("@")[1]
        assert any(
            s["args"]["addr"] == addr
            and s["ts"] <= h["ts"]
            and h["ts"] + h["dur"] <= s["ts"] + s["dur"]
            for s in rtt_spans
        ), f"worker span not nested in its remote-segment span: {h}"
        assert h["args"]["trace_id"] == tr.trace_id
        assert h["args"]["parent_span_id"] > 0
    # sub-phase spans rode the same digests
    names = {e["name"] for e in xs if e["pid"] != master_pid}
    assert {"ops.handle", "ops.decode", "ops.forward", "ops.encode"} <= names
    tr.clear()


def test_cli_rejects_cluster_flags_without_topology(tmp_path):
    """--top/--cluster-report aggregate across workers; a local run must
    reject them loudly instead of silently ignoring them."""
    from cake_tpu import cli

    (tmp_path / "config.json").write_text(json.dumps(tiny().to_hf_dict()))
    topo = tmp_path / "t.yml"
    Topology.from_dict({
        "w": {"host": "127.0.0.1:1", "layers": ["model.layers.0-3"]},
    }).save(topo)
    with pytest.raises(SystemExit, match="cluster-report|top"):
        cli.main(["--model", str(tmp_path), "--top", "--cpu"])
    with pytest.raises(SystemExit, match="straggler-factor"):
        cli.main(["--model", str(tmp_path), "--straggler-factor", "0.5",
                  "--topology", str(topo), "--cpu"])
