"""Real 2-process multi-host plane: jax.distributed over the CPU backend.

The reference's cross-host story is hand-rolled TCP between master and
workers, exercised only by manual deployment (SURVEY.md §4). The pod path
here is the other way around — every host runs the SAME program under
jax.distributed, the global mesh spans all hosts' chips — and this test
actually runs it: two OS processes, a coordinator handshake, a global
2-device (stage=2) mesh with Gloo cross-process collectives, the
direct-to-mesh sharded weight loader (each process reads only its stages'
layers), and greedy tokens bit-identical to the single-process run.

This is the proof the round-2 verdict asked for: the mesh path is valid
under NON-addressable shards (host zeros are never device_put across
processes; params assemble via make_array_from_callback per addressable
shard)."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.utils.weights import save_llama_params

CFG = tiny()
REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mhmodel")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype="float32")
    save_llama_params(params, d)
    (d / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    return d


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cli_argv(model_dir, extra):
    return [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
            "--prompt-ids", "3,5,7", "-n", "6", "--temperature", "0",
            "--max-seq", "32", "--cpu", "--stages", "2"] + extra


def _env(device_count: int):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={device_count}"
    ).strip()
    return env


def _tokens(stdout: str) -> str:
    lines = [l for l in stdout.splitlines()
             if l and all(c.isdigit() or c == "," for c in l)]
    assert lines, f"no token line in stdout: {stdout!r}"
    return lines[-1]


def test_two_process_mesh_matches_single_process(model_dir):
    """Two coordinated processes (1 CPU device each) form a global stage=2
    mesh and decode the same greedy stream as one process with 2 devices."""
    single = subprocess.run(
        _cli_argv(model_dir, []), capture_output=True, text=True,
        timeout=240, env=_env(2), cwd=REPO,
    )
    assert single.returncode == 0, single.stderr
    want = _tokens(single.stdout)

    port = _free_port()
    procs = [
        subprocess.Popen(
            _cli_argv(model_dir, [
                "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", "2", "--process-id", str(pid),
            ]),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_env(1), cwd=REPO,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            p.kill()
    assert procs[0].returncode == 0, outs[0][1]
    assert procs[1].returncode == 0, outs[1][1]
    # both processes run the same SPMD program and emit the same stream
    assert _tokens(outs[0][0]) == want
    assert _tokens(outs[1][0]) == want


def test_two_process_sharded_load_reads_only_local_stages(model_dir):
    """Under jax.distributed each process's sharded loader materializes only
    the shards its local devices own: process 0 (stage 0) reads layers 0..1,
    process 1 reads layers 2..3 — the reference worker's own-blocks-only
    contract (worker.rs:85-98) on the pod path."""
    port = _free_port()
    driver = (
        "import sys, jax; jax.config.update('jax_platforms', 'cpu');"
        "pid = int(sys.argv[1]);"
        f"jax.distributed.initialize('127.0.0.1:{port}', 2, pid);"
        "from cake_tpu.models.config import tiny;"
        "from cake_tpu.parallel.mesh import MeshPlan;"
        "from cake_tpu.utils import sharded_load;"
        "names = [];"
        "orig = sharded_load.CheckpointReader.read2d;"
        "sharded_load.CheckpointReader.read2d = (lambda self, name, r, c, t:"
        " (names.append(name), orig(self, name, r, c, t))[1]);"
        "cfg = tiny();"
        "plan = MeshPlan.build(cfg, num_stages=2);"
        f"sharded_load.load_llama_params_on_mesh({str(repr(str(model_dir)))},"
        " cfg, plan.mesh);"
        "layers = sorted({int(n.split('.')[2]) for n in names"
        " if n.startswith('model.layers')});"
        "print('LAYERS', pid, layers)"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", driver, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_env(1), cwd=REPO,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            p.kill()
    assert procs[0].returncode == 0, outs[0][1]
    assert procs[1].returncode == 0, outs[1][1]
    half = CFG.num_hidden_layers // 2
    assert f"LAYERS 0 {list(range(half))}" in outs[0][0]
    assert f"LAYERS 1 {list(range(half, CFG.num_hidden_layers))}" in outs[1][0]
