"""Real 2-process multi-host plane: jax.distributed over the CPU backend.

The reference's cross-host story is hand-rolled TCP between master and
workers, exercised only by manual deployment (SURVEY.md §4). The pod path
here is the other way around — every host runs the SAME program under
jax.distributed, the global mesh spans all hosts' chips — and this test
actually runs it: two OS processes, a coordinator handshake, a global
2-device (stage=2) mesh with Gloo cross-process collectives, the
direct-to-mesh sharded weight loader (each process reads only its stages'
layers), and greedy tokens bit-identical to the single-process run.

This is the proof the round-2 verdict asked for: the mesh path is valid
under NON-addressable shards (host zeros are never device_put across
processes; params assemble via make_array_from_callback per addressable
shard)."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.utils.weights import save_llama_params

CFG = tiny()
REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mhmodel")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype="float32")
    save_llama_params(params, d)
    (d / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    return d


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _cli_argv(model_dir, extra):
    return [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
            "--prompt-ids", "3,5,7", "-n", "6", "--temperature", "0",
            "--max-seq", "32", "--cpu", "--stages", "2"] + extra


def _env(device_count: int):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={device_count}"
    ).strip()
    return env


def _tokens(stdout: str) -> str:
    lines = [l for l in stdout.splitlines()
             if l and all(c.isdigit() or c == "," for c in l)]
    assert lines, f"no token line in stdout: {stdout!r}"
    return lines[-1]


def test_two_process_mesh_matches_single_process(model_dir):
    """Two coordinated processes (1 CPU device each) form a global stage=2
    mesh and decode the same greedy stream as one process with 2 devices."""
    single = subprocess.run(
        _cli_argv(model_dir, []), capture_output=True, text=True,
        timeout=240, env=_env(2), cwd=REPO,
    )
    assert single.returncode == 0, single.stderr
    want = _tokens(single.stdout)

    port = _free_port()
    procs = [
        subprocess.Popen(
            _cli_argv(model_dir, [
                "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", "2", "--process-id", str(pid),
            ]),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_env(1), cwd=REPO,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            p.kill()
    assert procs[0].returncode == 0, outs[0][1]
    assert procs[1].returncode == 0, outs[1][1]
    # both processes run the same SPMD program and emit the same stream
    assert _tokens(outs[0][0]) == want
    assert _tokens(outs[1][0]) == want


_TP_DRIVER = r"""
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize('127.0.0.1:{port}', 2, pid)
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.runtime.mesh_generator import MeshGenerator
from cake_tpu.utils import sharded_load

cfg = tiny()
devs = jax.devices()
assert len(devs) == 4
# Reorder so the row-major (dp, stage, sp, tp) reshape puts one device of
# EACH process in every tp pair: [p0d0, p1d0, p0d1, p1d1] -> stage 0 tp
# group = (p0d0, p1d0). The existing 2x1 test only crosses the process
# boundary with the stage ppermute; this crosses it with the tp psum /
# all_gather.
order = [devs[0], devs[2], devs[1], devs[3]]
plan = MeshPlan.build(cfg, num_stages=2, tp=2, devices=order)
grid = plan.mesh.devices  # [dp, stage, sp, ep, tp]
spans = {{tuple(sorted(d.process_index for d in grid[0, s, 0, 0, :]))
          for s in range(2)}}
assert spans == {{(0, 1)}}, spans  # every tp pair spans both processes
params = sharded_load.load_llama_params_on_mesh(
    {model_dir!r}, cfg, plan.mesh)
g = MeshGenerator(cfg, params, plan=plan,
                  settings=SamplerSettings(temperature=0.0,
                                           repeat_penalty=1.1))
g.set_prompt([3, 5, 7])
print('TOKENS', pid, [g.next_token(i).id for i in range(6)])
"""

_SP_DRIVER = r"""
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize('127.0.0.1:{port}', 2, pid)
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.runtime.mesh_generator import MeshGenerator
from cake_tpu.utils import sharded_load

cfg = tiny()
plan = MeshPlan.build(cfg, sp=2, devices=jax.devices())
grid = plan.mesh.devices
span = tuple(sorted(d.process_index for d in grid[0, 0, :, 0, 0]))
assert span == (0, 1), span  # the sp ring crosses the process boundary
params = sharded_load.load_llama_params_on_mesh(
    {model_dir!r}, cfg, plan.mesh)
g = MeshGenerator(cfg, params, plan=plan,
                  settings=SamplerSettings(temperature=0.0,
                                           repeat_penalty=1.1))
g.set_prompt([3, 5, 7])
print('TOKENS', pid, [g.next_token(i).id for i in range(6)])
"""


_DP_SERVE_DRIVER = r"""
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize('127.0.0.1:{port}', 2, pid)
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.utils import sharded_load

cfg = tiny()
plan = MeshPlan.build(cfg, dp=2, devices=jax.devices())
grid = plan.mesh.devices
span = tuple(sorted(d.process_index for d in grid[:, 0, 0, 0, 0]))
assert span == (0, 1), span  # the dp batch axis spans both processes
params = sharded_load.load_llama_params_on_mesh(
    {model_dir!r}, cfg, plan.mesh)
g = BatchGenerator(cfg, params, plan=plan,
                   settings=SamplerSettings(temperature=0.9, top_k=20,
                                            seed=7))
g.set_prompts([[3, 5, 7], [2, 8, 4]])
outs = g.generate(6)
print('TOKENS', pid, outs)
"""


def _oracle_tokens(model_dir) -> list:
    """Single-device greedy stream from the same checkpoint (the parity
    oracle every mesh layout must reproduce)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator
    from cake_tpu.utils.weights import load_llama_params

    params = load_llama_params(model_dir, CFG.num_hidden_layers,
                               dtype=CFG.dtype)
    g = LlamaGenerator(CFG, params,
                       settings=SamplerSettings(temperature=0.0,
                                                repeat_penalty=1.1))
    g.set_prompt([3, 5, 7])
    return [g.next_token(i).id for i in range(6)]


def _run_pair(driver: str, model_dir, devices_per_proc: int):
    port = _free_port()
    script = driver.format(port=port, model_dir=str(model_dir))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_env(devices_per_proc), cwd=REPO,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    finally:
        for p in procs:
            p.kill()
    assert procs[0].returncode == 0, outs[0][1][-3000:]
    assert procs[1].returncode == 0, outs[1][1][-3000:]
    toks = []
    for pid in (0, 1):
        line = [l for l in outs[pid][0].splitlines()
                if l.startswith(f"TOKENS {pid}")]
        assert line, outs[pid][0]
        toks.append(line[-1].split(" ", 2)[2])
    return toks


def test_two_process_tp_psum_crosses_process_boundary(model_dir):
    """stage=2 x tp=2 over 2 processes x 2 devices, device order chosen so
    every tp psum/all_gather group spans BOTH processes (asserted in the
    driver): greedy tokens match the single-device oracle — the r3 verdict's
    missing proof that tensor-parallel collectives, not just the stage
    ppermute, cross a process boundary."""
    want = str(_oracle_tokens(model_dir))
    got0, got1 = _run_pair(_TP_DRIVER, model_dir, devices_per_proc=2)
    assert got0 == want and got1 == want, (got0, got1, want)


def test_two_process_sp_ring_crosses_process_boundary(model_dir):
    """sp=2 over 2 processes x 1 device: the sequence-parallel ring
    (ring-attention prefill ppermutes + sp decode psum/pmax) crosses the
    process boundary (asserted in the driver), greedy tokens match the
    single-device oracle."""
    want = str(_oracle_tokens(model_dir))
    got0, got1 = _run_pair(_SP_DRIVER, model_dir, devices_per_proc=1)
    assert got0 == want and got1 == want, (got0, got1, want)


def test_two_process_dp_serving_matches_single_process(model_dir):
    """The SERVING plane crosses hosts: BatchGenerator on a dp=2 mesh over
    2 processes x 1 device (each process owns one stream's rows; asserted
    in the driver), sampled streams identical to the single-process dp=2
    run of the same (seed, stream_id, prompt)s."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.parallel.mesh import MeshPlan
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.utils.weights import load_llama_params

    params = load_llama_params(model_dir, CFG.num_hidden_layers,
                               dtype=CFG.dtype)
    plan = MeshPlan.build(CFG, dp=2, devices=jax.devices()[:2])
    g = BatchGenerator(CFG, params, plan=plan,
                       settings=SamplerSettings(temperature=0.9, top_k=20,
                                                seed=7))
    g.set_prompts([[3, 5, 7], [2, 8, 4]])
    want = str(g.generate(6))
    got0, got1 = _run_pair(_DP_SERVE_DRIVER, model_dir, devices_per_proc=1)
    assert got0 == want and got1 == want, (got0, want)


def test_two_process_sharded_load_reads_only_local_stages(model_dir):
    """Under jax.distributed each process's sharded loader materializes only
    the shards its local devices own: process 0 (stage 0) reads layers 0..1,
    process 1 reads layers 2..3 — the reference worker's own-blocks-only
    contract (worker.rs:85-98) on the pod path."""
    port = _free_port()
    driver = (
        "import sys, jax; jax.config.update('jax_platforms', 'cpu');"
        "pid = int(sys.argv[1]);"
        f"jax.distributed.initialize('127.0.0.1:{port}', 2, pid);"
        "from cake_tpu.models.config import tiny;"
        "from cake_tpu.parallel.mesh import MeshPlan;"
        "from cake_tpu.utils import sharded_load;"
        "names = [];"
        "orig = sharded_load.CheckpointReader.read2d;"
        "sharded_load.CheckpointReader.read2d = (lambda self, name, r, c, t:"
        " (names.append(name), orig(self, name, r, c, t))[1]);"
        "cfg = tiny();"
        "plan = MeshPlan.build(cfg, num_stages=2);"
        f"sharded_load.load_llama_params_on_mesh({str(repr(str(model_dir)))},"
        " cfg, plan.mesh);"
        "layers = sorted({int(n.split('.')[2]) for n in names"
        " if n.startswith('model.layers')});"
        "print('LAYERS', pid, layers)"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", driver, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_env(1), cwd=REPO,
        )
        for pid in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240) for p in procs]
    finally:
        for p in procs:
            p.kill()
    assert procs[0].returncode == 0, outs[0][1]
    assert procs[1].returncode == 0, outs[1][1]
    half = CFG.num_hidden_layers // 2
    assert f"LAYERS 0 {list(range(half))}" in outs[0][0]
    assert f"LAYERS 1 {list(range(half, CFG.num_hidden_layers))}" in outs[1][0]


_EP_DRIVER = r"""
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize('127.0.0.1:{port}', 2, pid)
from cake_tpu.models.config import tiny_moe
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.runtime.mesh_generator import MeshGenerator
from cake_tpu.utils import sharded_load

cfg = tiny_moe()
plan = MeshPlan.build(cfg, ep=2, devices=jax.devices())
grid = plan.mesh.devices
span = tuple(sorted(d.process_index for d in grid[0, 0, 0, :, 0]))
assert span == (0, 1), span  # the expert-parallel psum crosses processes
params = sharded_load.load_llama_params_on_mesh(
    {model_dir!r}, cfg, plan.mesh)
g = MeshGenerator(cfg, params, plan=plan,
                  settings=SamplerSettings(temperature=0.0,
                                           repeat_penalty=1.1))
g.set_prompt([3, 5, 7])
print('TOKENS', pid, [g.next_token(i).id for i in range(6)])
"""


@pytest.fixture(scope="module")
def moe_model_dir(tmp_path_factory):
    from cake_tpu.models.config import tiny_moe

    cfg = tiny_moe()
    d = tmp_path_factory.mktemp("mhmoe")
    params = llama.init_params(cfg, jax.random.PRNGKey(1), dtype="float32")
    save_llama_params(params, d)
    (d / "config.json").write_text(json.dumps(cfg.to_hf_dict()))
    return d


def test_two_process_ep_psum_crosses_process_boundary(moe_model_dir):
    """ep=2 over 2 processes x 1 device: the expert-parallel combine psum
    (each process holds HALF the experts) crosses the process boundary,
    greedy tokens match the single-device oracle — the last mesh axis
    (after stage/tp/sp/dp) proven multi-host."""
    from cake_tpu.models.config import tiny_moe
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator
    from cake_tpu.utils.weights import load_llama_params

    cfg = tiny_moe()
    params = load_llama_params(moe_model_dir, cfg.num_hidden_layers,
                               dtype=cfg.dtype)
    g = LlamaGenerator(cfg, params,
                       settings=SamplerSettings(temperature=0.0,
                                                repeat_penalty=1.1))
    g.set_prompt([3, 5, 7])
    want = str([g.next_token(i).id for i in range(6)])
    got0, got1 = _run_pair(_EP_DRIVER, moe_model_dir, devices_per_proc=1)
    assert got0 == want and got1 == want, (got0, got1, want)
