"""Disaggregated prefill/decode serving (cake_tpu/disagg).

`make disagg-smoke` acceptance: a stream's KV-page snapshot round-trips
BIT-IDENTICALLY to an uninterrupted run — greedy and sampled, across
wire codecs (none always; bf16 on a bf16 cache; int8 on an
int8-quantized pool), for constrained streams resuming mid-grammar, and
for mid-window multi-page streams; an import into a full pool defers
FIFO-fair instead of dropping; pinned transfer pages survive eviction
storms (the kvpool pin/unpin regression); the transfer channel retries
through chaos-proxy kill/truncate/corrupt/stall faults and NEVER
retries a deterministic reject; and the gateway's two-stage route
(prefill tier -> KV transfer -> decode resume) serves streams
bit-identical to a direct engine, falling back to transparent
re-prefill with zero failed requests when the transfer channel dies.
"""

import json
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from cake_tpu.constrain.guide import guide_for
from cake_tpu.disagg import (
    SnapshotMismatch,
    TransferError,
    TransferRejected,
    TransferServer,
    decode_snapshot,
    encode_snapshot,
    peek_xfer_id,
    send_snapshot,
)
from cake_tpu.disagg.snapshot import SnapshotError
from cake_tpu.gateway.api import start_gateway
from cake_tpu.gateway.health import Backend, HealthMonitor
from cake_tpu.gateway.policy import make_policy, pick_decode, pick_prefill
from cake_tpu.kvpool import PagePool
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.scheduler import Scheduler
from cake_tpu.testing.chaos import ChaosProxy, parse_spec

# eos disabled (-1 never sampled): deterministic stream lengths, so every
# round-trip can compare exact token sequences
CFG = tiny(max_seq_len=64, eos_token_id=-1)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


class _FakeTok:
    """id -> letter (alnum decodes, the test_serve convention)."""

    def decode(self, ids):
        return "".join(chr(ord("a") + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - ord("a") for c in text]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(11))


def _gen(params, cfg=CFG, pool=None, quant=None, tokenizer=None,
         **settings):
    kw = {"kv_pool_pages": pool} if pool else {}
    return BatchGenerator(
        cfg, params, tokenizer=tokenizer,
        settings=SamplerSettings(**(settings or GREEDY)),
        kv_layout="paged", kv_page_size=16, kv_quant=quant, **kw)


def _drive(gen, sid, want, max_steps=400):
    """step() until stream ``sid`` holds ``want`` tokens; returns them."""
    for _ in range(max_steps):
        got = _tokens(gen, sid)
        if got is not None and len(got) >= want \
                and not gen.pending_admissions():
            return got[:want]
        gen.step()
    raise AssertionError(f"stream {sid} never reached {want} tokens")


def _tokens(gen, sid):
    for s in gen.streams:
        if s.active and not s.done and s.stream_id == sid:
            return list(s.generated)
    return None


def _retire_all(gen):
    for s in list(gen.streams):
        if s.active and not s.done:
            gen.finish(s.stream_id)


# -- snapshot format (host-only) ---------------------------------------------


def _snap_kwargs(**over):
    pages = [{"k": np.arange(96, dtype=np.float32).reshape(2, 2, 8, 3),
              "v": np.ones((2, 2, 8, 3), np.float32)}]
    kw = dict(xfer_id="xfer-1", fingerprint={"layers": 2}, codec="none",
              stream_id=3, prompt=[1, 2, 3], generated=[9, 8], pos=5,
              index=5, last_token=8,
              key=np.array([7, 9], np.uint32),
              history=np.full(8, -1, np.int32), hist_slot=2,
              guide_spec=None, guide_state=0, pages=pages)
    kw.update(over)
    return kw


class TestSnapshotFormat:
    def test_round_trip_fields(self):
        data = encode_snapshot(**_snap_kwargs(
            guide_spec={"type": "regex", "pattern": "ab"}, guide_state=4))
        s = decode_snapshot(data)
        assert (s.xfer_id, s.stream_id, s.pos, s.last_token) == \
            ("xfer-1", 3, 5, 8)
        assert s.prompt == [1, 2, 3] and s.generated == [9, 8]
        assert s.guide_spec == {"type": "regex", "pattern": "ab"}
        assert s.guide_state == 4 and s.hist_slot == 2
        np.testing.assert_array_equal(
            s.pages[0]["k"], _snap_kwargs()["pages"][0]["k"])
        assert peek_xfer_id(data) == "xfer-1"

    def test_bad_magic_version_truncation(self):
        data = encode_snapshot(**_snap_kwargs())
        with pytest.raises(SnapshotError, match="magic"):
            decode_snapshot(b"NOPE" + data[4:])
        with pytest.raises(SnapshotError, match="version"):
            decode_snapshot(data[:4] + b"\xff\x7f" + data[6:])
        with pytest.raises(SnapshotError, match="truncated"):
            decode_snapshot(data[:10])
        with pytest.raises(SnapshotError, match="truncated"):
            decode_snapshot(data[:-5])
        with pytest.raises(SnapshotError, match="trailing"):
            decode_snapshot(data + b"JUNK")

    def test_quant_pages_scales_ride_lossless(self):
        pages = [{"kq": np.arange(24, dtype=np.int8).reshape(2, 1, 4, 3),
                  "ks": np.linspace(0.1, 1, 8,
                                    dtype=np.float32).reshape(2, 1, 4),
                  "vq": np.zeros((2, 1, 4, 3), np.int8),
                  "vs": np.ones((2, 1, 4), np.float32)}]
        data = encode_snapshot(**_snap_kwargs(pages=pages, codec="int8"))
        s = decode_snapshot(data)
        # int8 payloads pass through; float32 scales are forced onto the
        # none codec — both sides bit-exact despite codec="int8"
        np.testing.assert_array_equal(s.pages[0]["kq"], pages[0]["kq"])
        np.testing.assert_array_equal(s.pages[0]["ks"], pages[0]["ks"])

    def test_quant_scales_survive_bf16_codec(self):
        # review regression: scales must ride lossless under EVERY codec
        # — a bf16 cast would round the float32 scales and silently
        # corrupt the dequantized KV on import
        scales = np.linspace(0.1, 1, 8, dtype=np.float32).reshape(2, 1, 4)
        assert not np.array_equal(  # the values a bf16 trip would lose
            scales, scales.astype("bfloat16").astype(np.float32))
        pages = [{"kq": np.arange(24, dtype=np.int8).reshape(2, 1, 4, 3),
                  "ks": scales,
                  "vq": np.zeros((2, 1, 4, 3), np.int8),
                  "vs": np.ones((2, 1, 4), np.float32)}]
        s = decode_snapshot(
            encode_snapshot(**_snap_kwargs(pages=pages, codec="bf16")))
        np.testing.assert_array_equal(s.pages[0]["ks"], pages[0]["ks"])
        np.testing.assert_array_equal(s.pages[0]["kq"], pages[0]["kq"])


# -- kvpool transfer pins (the refcount fix) ---------------------------------


class TestPagePoolPins:
    def test_pin_is_a_claim_outside_tables_and_tree(self):
        p = PagePool(8, 4)
        a = p.alloc()
        p.pin(a)
        assert p.pincount(a) == 1 and p.pinned_count == 1
        # the stream's claim retires; the pin alone keeps the page live
        assert not p.unref(a)
        assert p.refcount(a) == 1 and p.free_count == 6
        assert p.unpin(a)  # last claim: NOW it frees
        assert p.pinned_count == 0 and p.free_count == 7

    def test_unpin_unpinned_raises(self):
        p = PagePool(8, 4)
        a = p.alloc()
        with pytest.raises(ValueError, match="unpin"):
            p.unpin(a)

    def test_sink_never_pins(self):
        p = PagePool(8, 4)
        p.pin(0)
        assert p.pinned_count == 0 and not p.unpin(0)

    def test_stats_and_gauge(self):
        p = PagePool(8, 4)
        a = p.alloc()
        p.pin(a)
        assert p.stats()["pages_pinned"] == 1


# -- routing policy (tier picks) ---------------------------------------------


def _probed(addr, role="mixed", queued=0, running=0, slots=4,
            inflight=0, transfer_port=0):
    b = Backend(f"pt{addr.rsplit(':', 1)[-1]}", addr)
    load = {"queued": queued, "running": running, "max_concurrent": slots,
            "role": role, "kv_transfers_inflight": inflight}
    if transfer_port:
        load["transfer_port"] = transfer_port
    b.probe_ok(load, up_after=1)
    return b


class TestTierPolicy:
    def test_prober_records_role_and_transfer_addr(self):
        b = _probed("127.0.0.1:9001", role="decode", transfer_port=7001)
        assert b.role == "decode"
        assert b.transfer_addr() == "127.0.0.1:7001"
        assert _probed("127.0.0.1:9002").transfer_addr() is None

    def test_pick_prefill_least_queue(self):
        a = _probed("127.0.0.1:9010", role="prefill", queued=5)
        b = _probed("127.0.0.1:9011", role="prefill", queued=1)
        assert pick_prefill([a, b]) is b

    def test_pick_prefill_counts_inflight_transfers(self):
        a = _probed("127.0.0.1:9012", role="prefill", queued=1, inflight=9)
        b = _probed("127.0.0.1:9013", role="prefill", queued=2)
        assert pick_prefill([a, b]) is b

    def test_pick_decode_prefix_affinity_stable(self):
        tier = [_probed(f"127.0.0.1:902{i}", role="decode",
                        transfer_port=7000 + i) for i in range(3)]
        key = b"ids:1,2,3"
        picks = {pick_decode(tier, key=key).name for _ in range(8)}
        assert len(picks) == 1  # rendezvous: same key -> same replica

    def test_pick_decode_saturated_preferred_falls_back(self):
        # whichever replica rendezvous prefers for this key, a saturated
        # one must lose to the idle one (affinity never queues)
        busy = _probed("127.0.0.1:9030", role="decode", queued=4,
                       running=4, slots=4, transfer_port=7030)
        idle = _probed("127.0.0.1:9031", role="decode", transfer_port=7031)
        now = time.monotonic()
        assert pick_decode([busy, idle], key=b"ids:9", now=now) is idle


# -- engine round trips ------------------------------------------------------


def _export_after(gen, sid, n_tokens, codec="none"):
    _drive(gen, sid, n_tokens)
    return gen.export_stream(sid, codec=codec)


def _import_fresh(params, snap, sid=7, **gen_kw):
    """New engine with retired seed streams, snapshot attached as
    ``sid`` — the decode-replica shape (import lands in a pool whose
    slots have history)."""
    g = _gen(params, **gen_kw)
    g.set_prompts([[9, 9], [8, 8]])
    _retire_all(g)
    g.import_stream(snap, stream_id=sid)
    return g


class TestRoundTrip:
    """The acceptance bit: resumed continuation == uninterrupted one."""

    def test_greedy(self, params):
        a = _gen(params)
        a.set_prompts([[1, 2, 3, 4], [5, 6, 7]])
        snap = _export_after(a, 0, 5)
        ref = _drive(a, 0, 16)
        b = _import_fresh(params, snap)
        assert _drive(b, 7, 16) == ref

    def test_sampled(self, params):
        kw = dict(temperature=0.9, top_p=0.95, repeat_penalty=1.1,
                  seed=123)
        a = _gen(params, **kw)
        a.set_prompts([[1, 2, 3, 4], [5, 6, 7]])
        snap = _export_after(a, 0, 5)
        ref = _drive(a, 0, 16)
        # the raw per-stream key rides the snapshot: bit-identity holds
        # even though the importer has a different seed and stream id
        b = _import_fresh(params, snap, **dict(kw, seed=999))
        assert _drive(b, 7, 16) == ref

    def test_mid_window_multi_page(self, params):
        a = _gen(params)
        a.set_prompts([list(range(1, 21)), [5, 6, 7]])  # 20-token prompt
        # pos = prompt 20 + 2 fed tokens (the 3rd rides as last_token
        # still unfed): page 2 of 2, mid-page
        snap = _export_after(a, 0, 3)
        s = decode_snapshot(snap)
        assert s.n_pages == 2 and s.pos == 22 and s.last_token is not None
        ref = _drive(a, 0, 12)
        b = _import_fresh(params, snap)
        assert _drive(b, 7, 12) == ref

    def test_constrained_resumes_mid_grammar(self, params):
        tok = _FakeTok()
        spec = {"type": "regex", "pattern": "[a-d]{30}"}
        a = _gen(params, tokenizer=tok)
        a.set_prompts([[1, 2, 3], [4, 5]],
                      guides=[guide_for(spec, tok, CFG), None])
        snap = _export_after(a, 0, 4)
        parsed = decode_snapshot(snap)
        assert parsed.guide_spec == spec and parsed.guide_state != 0
        ref = _drive(a, 0, 12)
        b = _import_fresh(params, snap, tokenizer=tok)
        got = _drive(b, 7, 12)
        assert got == ref
        assert all(c in "abcd" for c in tok.decode(got))

    def test_int8_pool_int8_codec(self, params):
        a = _gen(params, quant="int8")
        a.set_prompts([[1, 2, 3, 4], [5, 6, 7]])
        snap = _export_after(a, 0, 5, codec="int8")
        ref = _drive(a, 0, 14)
        b = _import_fresh(params, snap, quant="int8")
        assert _drive(b, 7, 14) == ref

    def test_bf16_cache_bf16_codec(self):
        cfg = tiny(max_seq_len=64, eos_token_id=-1, dtype="bfloat16")
        params16 = llama.init_params(cfg, jax.random.PRNGKey(11))
        a = _gen(params16, cfg=cfg)
        a.set_prompts([[1, 2, 3, 4], [5, 6, 7]])
        snap = _export_after(a, 0, 5, codec="bf16")
        ref = _drive(a, 0, 14)
        b = _gen(params16, cfg=cfg)
        b.set_prompts([[9, 9], [8, 8]])
        _retire_all(b)
        b.import_stream(snap, stream_id=7)
        assert _drive(b, 7, 14) == ref

    def test_fingerprint_mismatch_refused(self, params):
        a = _gen(params)
        a.set_prompts([[1, 2, 3, 4]])
        snap = _export_after(a, 0, 3)
        other_cfg = tiny(max_seq_len=32, eos_token_id=-1)
        b = _gen(llama.init_params(other_cfg, jax.random.PRNGKey(11)),
                 cfg=other_cfg)
        b.set_prompts([[1]])
        _retire_all(b)
        with pytest.raises(SnapshotMismatch, match="max_seq"):
            b.import_begin(snap)

    def test_import_idempotent_by_xfer_id(self, params):
        a = _gen(params)
        a.set_prompts([[1, 2, 3, 4]])
        snap = _export_after(a, 0, 3)
        b = _gen(params)
        b.set_prompts([[9, 9]])
        _retire_all(b)
        m1 = b.import_begin(snap)
        m2 = b.import_begin(snap)  # duplicate send (retry after lost ACK)
        assert m1["xfer_id"] == m2["xfer_id"]
        assert b.imports_pending() == 1

    def test_export_requires_live_stream_and_paged(self, params):
        g = _gen(params)
        g.set_prompts([[1, 2, 3]])
        with pytest.raises(ValueError, match="no live stream"):
            g.export_stream(99)
        slot_gen = BatchGenerator(CFG, params,
                                  settings=SamplerSettings(**GREEDY))
        slot_gen.set_prompts([[1, 2, 3]])
        with pytest.raises(ValueError, match="paged"):
            slot_gen.export_stream(0)


# -- pool pressure: FIFO-fair deferral + pinned pages ------------------------


class TestPoolPressure:
    def test_import_into_full_pool_defers_fifo_fair(self, params):
        a = _gen(params)
        a.set_prompts([[1] * 40])
        snap = _export_after(a, 0, 12)  # pos 52: a 4-page snapshot

        # 3 streams x 4 pages fill the 16-page pool (15 usable + sink
        # leaves 3 free): the import's 4-page landing must wait for a
        # retirement — deferred, never dropped
        b = _gen(params, pool=16)
        b.set_prompts([[1] * 40, [2] * 40, [3] * 40])
        for sid in (0, 1, 2):
            _drive(b, sid, 12)  # pos 52: all 4 pages per stream
        defers0 = b._pagepool._defer_ctr.value
        b.import_begin(snap)
        b.import_attach(peek_xfer_id(snap), 7)
        b.enqueue([5, 6, 7], 9)  # a plain admission queued BEHIND it
        for _ in range(6):
            b.step()
        # head-of-queue import deferred; the arrival behind it must not
        # jump the line (FIFO-fair) — nothing admitted, nothing dropped
        assert b.imports_pending() == 1
        assert b.pending_admissions() == 3
        assert b._pagepool._defer_ctr.value > defers0
        ref = _drive(a, 0, 18)
        b.finish(2)  # retire one stream: 4 pages + a slot free up
        assert _drive(b, 7, 18) == ref  # import landed + resumed FIRST
        b.finish(0)  # now a slot frees for the queued prompt behind it
        assert _drive(b, 9, 2)

    def test_import_stream_foreign_blocked_head_raises(self, params):
        # review regression: a FOREIGN arrival at the FIFO head that
        # cannot start (every slot live) used to make import_stream
        # busy-loop forever — it must raise like admit() does, and the
        # begun import must be aborted (no pins left behind)
        a = _gen(params)
        a.set_prompts([[1, 2, 3, 4], [5, 6]])
        snap = _export_after(a, 0, 3)
        b = _gen(params)
        b.set_prompts([[9, 9], [8, 8]])  # every slot live, none retired
        b.enqueue([7, 7, 7], 50)  # queued prompt ahead of the attach
        with pytest.raises(RuntimeError, match="no free slot"):
            b.import_stream(snap, stream_id=7)
        assert b.imports_pending() == 0

    def test_evict_storm_cannot_free_pinned_pages(self, params):
        """Regression for the pin claim kind: pages of a
        begun-but-unattached import survive alloc/evict storms under
        pool pressure, and the eventual resume is still bit-identical."""
        a = _gen(params)
        a.set_prompts([list(range(1, 21)), [5, 6]])
        snap = _export_after(a, 0, 4)
        ref = _drive(a, 0, 12)

        b = _gen(params, pool=16)
        b.set_prompts([[7, 7, 7], [6, 6]])
        _retire_all(b)
        b.import_begin(snap)
        xid = peek_xfer_id(snap)
        for _ in range(8):  # land the pages (import tick; no attach yet)
            b.step()
            if b._imports[xid]["pages"] is not None:
                break
        pinned = list(b._imports[xid]["pages"])
        assert pinned and all(b._pagepool.pincount(p) == 1
                              for p in pinned)
        # storm: admissions + retirements churn every free page and
        # force prefix-tree eviction, while the transfer stays stalled
        for i in range(6):
            b.enqueue([i + 1] * 36, 100 + i)
            _drive(b, 100 + i, 8)
            b.finish(100 + i)
        assert all(b._pagepool.pincount(p) == 1 for p in pinned)
        for s in b.streams:  # no stream table ever claimed a pinned page
            if s.active and not s.done:
                assert not set(pinned) & set(
                    b._tables[b.streams.index(s)])
        b.import_attach(xid, 7)
        assert _drive(b, 7, 12) == ref

    def test_import_abort_releases_pins(self, params):
        a = _gen(params)
        a.set_prompts([[1, 2, 3, 4]])
        snap = _export_after(a, 0, 3)
        b = _gen(params)
        b.set_prompts([[9, 9]])
        _retire_all(b)
        b.import_begin(snap)
        xid = peek_xfer_id(snap)
        for _ in range(8):
            b.step()
            if b._imports[xid]["pages"] is not None:
                break
        free0 = b._pagepool.free_count
        assert b.import_abort(xid)
        assert b._pagepool.free_count > free0
        assert b._pagepool.pinned_count == 0
        assert not b.import_abort(xid)  # unknown now

    def test_expire_imports_sweeps_orphans(self, params):
        a = _gen(params)
        a.set_prompts([[1, 2, 3, 4]])
        snap = _export_after(a, 0, 3)
        b = _gen(params)
        b.set_prompts([[9, 9]])
        _retire_all(b)
        b.import_begin(snap)
        assert b.expire_imports(ttl_s=3600) == 0
        assert b.expire_imports(ttl_s=0.0) == 1
        assert b.imports_pending() == 0


# -- the transfer channel ----------------------------------------------------


class _StubSched:
    """submit_import-only stand-in for the TransferServer tests."""

    def __init__(self, fail: str | None = None, timeouts: int = 0):
        self.fail = fail
        self.timeouts = timeouts  # raise TimeoutError this many times
        self.calls = 0
        self.payloads: list[bytes] = []

    def submit_import(self, payload: bytes) -> dict:
        self.calls += 1
        if self.timeouts > 0:
            self.timeouts -= 1
            raise TimeoutError("engine thread did not pick up the import")
        if self.fail:
            raise ValueError(self.fail)
        self.payloads.append(bytes(payload))
        return {"xfer_id": "x"}


class TestTransferChannel:
    def test_ack_path_delivers_payload(self):
        sched = _StubSched()
        srv = TransferServer(sched).start()
        try:
            send_snapshot("127.0.0.1", srv.port, b"\x01" * 2048,
                          deadline_s=5.0)
        finally:
            srv.stop()
        assert sched.payloads == [b"\x01" * 2048]

    def test_reject_is_never_retried(self):
        sched = _StubSched(fail="fingerprint mismatch: nope")
        srv = TransferServer(sched).start()
        try:
            with pytest.raises(TransferRejected, match="fingerprint"):
                send_snapshot("127.0.0.1", srv.port, b"pay",
                              deadline_s=5.0)
        finally:
            srv.stop()
        assert sched.calls == 1  # deterministic refusal: exactly one try

    def test_engine_timeout_is_retried_not_rejected(self):
        # review regression: a busy engine thread (submit_import
        # TimeoutError) is TRANSIENT — the server must drop the
        # connection so the sender's retry delivers, never answer the
        # deterministic XFER_REJECT
        sched = _StubSched(timeouts=1)
        srv = TransferServer(sched).start()
        try:
            send_snapshot("127.0.0.1", srv.port, b"\x03" * 256,
                          deadline_s=10.0, ack_timeout_s=2.0)
        finally:
            srv.stop()
        assert sched.calls >= 2
        assert sched.payloads == [b"\x03" * 256]

    def test_unreachable_exhausts_retry_budget(self):
        with pytest.raises(TransferError, match="failed after"):
            send_snapshot("127.0.0.1", 1, b"pay", deadline_s=0.4,
                          connect_timeout_s=0.2)

    @pytest.mark.parametrize("spec", ["kill@1", "truncate@1",
                                      "corrupt@1", "stall@1=700"])
    def test_chaos_faults_recover_by_retry(self, spec):
        """One faulted connection, then clean: the sender's
        reconnect-and-resend delivers the payload intact. A resend may
        hand the receiver a duplicate (``kill`` forwards the frame
        before closing, so the ACK is what dies) — real receivers dedup
        by transfer id (`test_import_idempotent_by_xfer_id`); here the
        stub just records."""
        sched = _StubSched()
        srv = TransferServer(sched).start()
        proxy = ChaosProxy("127.0.0.1", srv.port,
                           parse_spec(spec)).start()
        try:
            send_snapshot("127.0.0.1", proxy.port, b"\x02" * 512,
                          deadline_s=10.0, ack_timeout_s=2.0)
        finally:
            proxy.stop()
            srv.stop()
        assert proxy.events, f"fault {spec} never fired"
        assert sched.payloads and all(p == b"\x02" * 512
                                      for p in sched.payloads)


# -- serve plane: roles over HTTP --------------------------------------------


def _serve_stack(params, role, **sched_kw):
    gen = _gen(params)
    sched = Scheduler(gen, queue_depth=8, request_timeout_s=60,
                      role=role, **sched_kw)
    sched.start(max_concurrent=2, warm_prompt_len=8)
    srv = start_api_server(sched)
    return srv, sched


def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


class TestServeRoles:
    def test_role_needs_disagg_engine(self, params):
        slot_gen = BatchGenerator(CFG, params,
                                  settings=SamplerSettings(**GREEDY))
        with pytest.raises(ValueError, match="paged"):
            Scheduler(slot_gen, role="prefill")
        with pytest.raises(ValueError, match="role"):
            Scheduler(_gen(params), role="bogus")

    def test_healthz_advertises_tier_fields(self, params):
        srv, sched = _serve_stack(params, "decode")
        ts = TransferServer(sched).start()
        sched.transfer_port = ts.port
        try:
            status, body = _get_json(
                f"http://127.0.0.1:{srv.port}/healthz")
            assert status == 200
            assert body["role"] == "decode"
            assert body["kv_transfers_inflight"] == 0
            assert body["transfer_port"] == ts.port
        finally:
            ts.stop()
            srv.close()
            sched.close()

    def test_resume_replay_clamps_to_max_tokens(self, params):
        # review regression: a snapshot can carry MORE generated tokens
        # than the resume request's budget — the replay must clamp at
        # max_tokens (finish "length"), not re-emit the whole snapshot
        exp = _gen(params)
        exp.set_prompts([[1, 2, 3], [4, 5]])
        ref = _drive(exp, 0, 5)
        snap = exp.export_stream(0)
        srv, sched = _serve_stack(params, "decode")
        ts = TransferServer(sched).start()
        try:
            send_snapshot("127.0.0.1", ts.port, snap, deadline_s=10.0)
            got = _sse_ids(f"http://127.0.0.1:{srv.port}", [1, 2, 3],
                           max_tokens=3,
                           _resume={"xfer_id": peek_xfer_id(snap)})
            assert got == ref[:3]
        finally:
            ts.stop()
            srv.close()
            sched.close()

    def test_prefill_replica_refuses_plain_requests(self, params):
        srv, sched = _serve_stack(params, "prefill")
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/completions",
                data=json.dumps({"prompt_ids": [1, 2, 3],
                                 "max_tokens": 4}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 400
            assert "prefill" in json.loads(ei.value.read())["error"]
        finally:
            srv.close()
            sched.close()


# -- gateway two-stage routing (end to end) ----------------------------------


class _Fleet:
    """1 prefill + 1 decode replica + gateway, with an optional chaos
    proxy on the transfer channel (the decode replica advertises the
    PROXY's port, so every KV snapshot rides through the faults)."""

    def __init__(self, params, faults=None, transfer_deadline_s=10.0):
        self.pre_srv, self.pre = _serve_stack(
            params, "prefill", transfer_deadline_s=transfer_deadline_s)
        self.dec_srv, self.dec = _serve_stack(params, "decode")
        self.ts = TransferServer(self.dec).start()
        self.proxy = None
        port = self.ts.port
        if faults is not None:
            self.proxy = ChaosProxy("127.0.0.1", self.ts.port,
                                    faults).start()
            port = self.proxy.port
        self.dec.transfer_port = port
        self.monitor = HealthMonitor(
            [Backend(f"dz{next(_SEQ)}",
                     f"127.0.0.1:{self.pre_srv.port}"),
             Backend(f"dz{next(_SEQ)}",
                     f"127.0.0.1:{self.dec_srv.port}")],
            probe_interval=0.2, up_after=1).start()
        self.gw = start_gateway(self.monitor, make_policy("p2c"))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if {b.role for b in self.monitor.routable()} >= \
                    {"prefill", "decode"}:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("tier map never discovered")
        self.url = f"http://127.0.0.1:{self.gw.port}"

    def close(self):
        self.gw.close()
        self.monitor.stop()
        if self.proxy is not None:
            self.proxy.stop()
        self.ts.stop()
        for srv, sched in ((self.pre_srv, self.pre),
                           (self.dec_srv, self.dec)):
            srv.close()
            sched.close()


_SEQ = iter(range(10_000))


def _sse_ids(url, prompt_ids, max_tokens=10, headers=None, **extra):
    req = urllib.request.Request(
        url + "/v1/completions",
        data=json.dumps({"prompt_ids": prompt_ids,
                         "max_tokens": max_tokens,
                         "stream": True, **extra}).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    ids = []
    with urllib.request.urlopen(req, timeout=120) as r:
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            data = raw[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            assert "error" not in ev, ev
            if "token" in ev:
                ids.append(ev["token"])
    return ids


def _reference(params, prompt_ids, n):
    g = _gen(params)
    g.set_prompts([prompt_ids, [5, 6]])
    return _drive(g, 0, n)


class TestGatewayTiered:
    PROMPT = [3, 1, 4, 1, 5, 9]

    def test_two_stage_route_bit_identical(self, params):
        ref = _reference(params, self.PROMPT, 10)
        fleet = _Fleet(params)
        try:
            h0 = obs_metrics.counter("disagg.handoffs").value
            got = _sse_ids(fleet.url, self.PROMPT, max_tokens=10)
            assert got == ref
            deadline = time.monotonic() + 5.0
            while obs_metrics.counter("disagg.handoffs").value <= h0:
                assert time.monotonic() < deadline, \
                    "tiered route never engaged (classic fallback?)"
                time.sleep(0.05)
        finally:
            fleet.close()

    def test_chaos_on_transfer_channel_still_bit_identical(self, params):
        """kill + truncate faults on successive transfer connections:
        the channel's retry absorbs them, the client stream is still
        bit-identical, zero failed requests."""
        ref = _reference(params, self.PROMPT, 10)
        fleet = _Fleet(params,
                       faults=parse_spec("kill@1,truncate@1"))
        try:
            for _ in range(2):  # two requests, one per scheduled fault
                assert _sse_ids(fleet.url, self.PROMPT,
                                max_tokens=10) == ref
            assert len(fleet.proxy.events) == 2
        finally:
            fleet.close()

    def test_dead_transfer_channel_reprefills_transparently(self, params):
        """Every transfer connect refused: the prefill leg fails its
        retry budget, the gateway re-prefills on the classic path — the
        client still gets the full bit-identical stream and no error."""
        ref = _reference(params, self.PROMPT, 10)
        fleet = _Fleet(params, faults=parse_spec("refuse=999"),
                       transfer_deadline_s=1.5)
        try:
            r0 = obs_metrics.counter("disagg.reprefills").value
            got = _sse_ids(fleet.url, self.PROMPT, max_tokens=10)
            assert got == ref
            assert obs_metrics.counter("disagg.reprefills").value > r0
        finally:
            fleet.close()

    def test_chaos_tiered_run_yields_one_connected_trace(self, params):
        """ISSUE 16 acceptance: a traced tiered request through gateway
        -> prefill -> (chaos-faulted) transfer -> decode reads back as
        ONE connected trace — the client's traceparent id on every span,
        the killed transfer attempt recorded as a failed-attempt span
        next to the retry that landed, and the import parented under the
        prefill tier's export via the snapshot's wire metadata."""
        import os

        from cake_tpu.obs import reqtrace
        from cake_tpu.obs import trace as obs_trace

        ref = _reference(params, self.PROMPT, 8)
        fleet = _Fleet(params, faults=parse_spec("kill@1"))
        tid = os.urandom(16).hex()
        root = os.urandom(8).hex()
        obs_trace.tracer().start(max_events=100_000)
        try:
            got = _sse_ids(
                fleet.url, self.PROMPT, max_tokens=8,
                headers={reqtrace.HEADER: f"00-{tid}-{root}-01"})
            assert got == ref
            assert fleet.proxy.events, "transfer fault never fired"
            want = {"gateway.route", "serve.queue", "serve.admit",
                    "disagg.export", "disagg.transfer", "disagg.import",
                    "session.emit"}
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                tl = reqtrace.request_log().get(tid)
                if tl is not None and want <= {s["name"]
                                               for s in tl["spans"]}:
                    break
                time.sleep(0.05)
            else:
                raise AssertionError(
                    f"merged timeline never covered {want}; last: "
                    f"{tl and sorted({s['name'] for s in tl['spans']})}")
            # one connected tree: every parent is a recorded span or
            # the client's own root span
            ids = {s["span"] for s in tl["spans"]}
            for s in tl["spans"]:
                p = s.get("parent")
                assert p is None or p in ids or p == root, \
                    f"span {s['name']} parented to unknown {p}"
            # the killed first attempt AND the retry that landed, both
            # present, failure annotated
            xfers = [s for s in tl["spans"]
                     if s["name"] == "disagg.transfer"]
            assert len(xfers) >= 2
            assert any("error" in s.get("args", {}) for s in xfers)
            assert any("error" not in s.get("args", {}) for s in xfers)
            # the decode tier's import hangs under the prefill export
            exp = next(s for s in tl["spans"]
                       if s["name"] == "disagg.export")
            imp = next(s for s in tl["spans"]
                       if s["name"] == "disagg.import")
            assert imp["parent"] == exp["span"]
            # and the tracer mirrors the same trace id end to end
            doc = obs_trace.tracer().to_chrome_trace()
            traced = {e["name"] for e in doc["traceEvents"]
                      if e.get("args", {}).get("trace") == tid}
            assert want <= traced
        finally:
            obs_trace.tracer().stop()
            obs_trace.tracer().clear()
            fleet.close()

    def test_empty_decode_tier_routes_classically(self, params):
        """1 prefill + 1 mixed: no decode tier, so the classic path
        carries everything — and never lands on the prefill replica."""
        ref = _reference(params, self.PROMPT, 8)
        pre_srv, pre = _serve_stack(params, "prefill")
        mix_srv, mix = _serve_stack(params, "mixed")
        monitor = HealthMonitor(
            [Backend(f"dz{next(_SEQ)}", f"127.0.0.1:{pre_srv.port}"),
             Backend(f"dz{next(_SEQ)}", f"127.0.0.1:{mix_srv.port}")],
            probe_interval=0.2, up_after=1).start()
        gw = start_gateway(monitor, make_policy("p2c"))
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if {b.role for b in monitor.routable()} >= \
                        {"prefill", "mixed"}:
                    break
                time.sleep(0.05)
            url = f"http://127.0.0.1:{gw.port}"
            e0 = obs_metrics.counter("disagg.exports").value
            for _ in range(3):
                assert _sse_ids(url, self.PROMPT, max_tokens=8) == ref
            assert obs_metrics.counter("disagg.exports").value == e0
        finally:
            gw.close()
            monitor.stop()
            for srv, sched in ((pre_srv, pre), (mix_srv, mix)):
                srv.close()
                sched.close()
