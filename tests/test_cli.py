"""CLI surface: flag parity with the reference + end-to-end subprocess runs."""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import pytest

from cake_tpu.cli import build_parser
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.utils.weights import save_llama_params

CFG = tiny()
REPO = Path(__file__).resolve().parents[1]


def test_defaults_match_reference():
    """Flag defaults mirror cake-core/src/lib.rs:15-64."""
    args = build_parser().parse_args(["--model", "x"])
    assert args.seed == 299792458
    assert args.sample_len == 100
    assert args.temperature == 1.0
    assert args.repeat_penalty == 1.1
    assert args.repeat_last_n == 128
    assert args.address == "127.0.0.1:10128"
    assert args.mode == "master"
    assert args.top_k is None and args.top_p is None


def test_short_n_flag():
    args = build_parser().parse_args(["--model", "x", "-n", "7"])
    assert args.sample_len == 7


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("climodel")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype="float32")
    save_llama_params(params, d)
    (d / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    return d


def _run_cli(argv, timeout=240, devices=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    if devices:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
    return subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli"] + argv,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def test_local_generation_subprocess(model_dir):
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5,7",
        "-n", "4", "--temperature", "0", "--max-seq", "32", "--cpu",
    ])
    assert r.returncode == 0, r.stderr
    assert "tok/s" in r.stderr


def test_mesh_pipeline_generation_subprocess(model_dir):
    """--stages/--tp drive the single-program mesh pipeline end-to-end."""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    r = subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
         "--prompt-ids", "3,5,7", "-n", "4", "--temperature", "0",
         "--max-seq", "32", "--cpu", "--stages", "2", "--tp", "2"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert "tok/s" in r.stderr


def test_device_ordinal_selection(model_dir):
    """--device N pins jax_default_device (reference --device, lib.rs:17-19)."""
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5", "-n", "2",
        "--temperature", "0", "--max-seq", "32", "--cpu", "--device", "0",
    ])
    assert r.returncode == 0, r.stderr
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5", "-n", "2",
        "--cpu", "--device", "99",
    ])
    assert r.returncode != 0
    assert "out of range" in r.stderr


def test_mesh_and_host_topology_flags_conflict(model_dir, tmp_path):
    topo = tmp_path / "t.yml"
    topo.write_text("w1:\n  host: 127.0.0.1:10128\n  layers:\n"
                    "    - model.layers.0-1\n")
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "1", "-n", "1",
        "--stages", "2", "--topology", str(topo),
    ])
    assert r.returncode != 0
    assert "mutually exclusive" in r.stderr


def test_device_topology_drives_mesh_path(model_dir, tmp_path):
    """A topology whose nodes carry `device:` indices selects the
    single-program mesh pipeline from YAML (the reference's one-config-plane
    contract, topology.rs:41-84) — no --stages flag needed."""
    topo = tmp_path / "mesh.yml"
    topo.write_text(
        "s0:\n  device: 0\n  layers:\n    - model.layers.0-1\n"
        "s1:\n  device: 1\n  layers:\n    - model.layers.2-3\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    r = subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
         "--prompt-ids", "3,5,7", "-n", "4", "--temperature", "0",
         "--max-seq", "32", "--cpu", "--topology", str(topo)],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert "mesh plan from topology: 2 stages" in r.stderr
    assert "tok/s" in r.stderr


def test_mixed_host_device_topology_rejected(model_dir, tmp_path):
    """Half-migrated YAML (some nodes device-indexed, some host-addressed)
    must fail loudly, not silently drop the host workers."""
    topo = tmp_path / "mixed.yml"
    topo.write_text(
        "s0:\n  device: 0\n  layers:\n    - model.layers.0-1\n"
        "w1:\n  host: 127.0.0.1:10128\n  layers:\n    - model.layers.2-3\n"
    )
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "1", "-n", "1",
        "--topology", str(topo),
    ])
    assert r.returncode != 0
    assert "mixes mesh nodes" in r.stderr


def test_device_topology_conflicts_with_stages(model_dir, tmp_path):
    topo = tmp_path / "mesh.yml"
    topo.write_text(
        "s0:\n  device: 0\n  layers:\n    - model.layers.0-1\n"
        "s1:\n  device: 1\n  layers:\n    - model.layers.2-3\n"
    )
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "1", "-n", "1",
        "--stages", "2", "--topology", str(topo),
    ])
    assert r.returncode != 0
    assert "--stages conflicts" in r.stderr


def test_prompts_file_serves_batch(model_dir, tmp_path):
    """--prompts-file decodes N prompts concurrently over the batched mesh
    pipeline and prints one output line per stream."""
    pf = tmp_path / "prompts.txt"
    pf.write_text("3,5,7\n2,4\n9,1,6,2\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    r = subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
         "--prompts-file", str(pf), "--prompts-ids", "-n", "4",
         "--temperature", "0",
         "--max-seq", "32", "--cpu", "--dp", "2", "--stages", "2", "-v"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    lines = [l for l in r.stdout.splitlines() if l.startswith("[")]
    assert len(lines) == 3 and lines[0].startswith("[0] ")
    assert "3 streams" in r.stderr and "aggregate" in r.stderr


def test_prompts_file_numeric_text_needs_explicit_mode(model_dir, tmp_path):
    """A numeric-looking line is NEVER silently id-parsed: without
    --prompts-ids it is a text prompt (and errors without a tokenizer);
    serving also rejects flags it would silently ignore
    (--prefill-chunks)."""
    pf = tmp_path / "prompts.txt"
    pf.write_text("1, 2, 3\n")
    r = _run_cli(["--model", str(model_dir), "--prompts-file", str(pf),
                  "-n", "2", "--cpu"])
    assert r.returncode != 0
    assert "tokenizer" in r.stderr
    # (--sp composes with serving since r4 — covered by
    # test_prompts_file_serves_over_sp_window)
    r = _run_cli(["--model", str(model_dir), "--prompts-file", str(pf),
                  "--prompts-ids", "-n", "2", "--cpu",
                  "--prefill-chunks", "2"])
    assert r.returncode != 0 and "--prefill-chunks" in r.stderr
    pf.write_text("hello world\n")
    r = _run_cli(["--model", str(model_dir), "--prompts-file", str(pf),
                  "--prompts-ids", "-n", "2", "--cpu"])
    assert r.returncode != 0
    assert "not a comma-separated id list" in r.stderr


def test_speculate_flag_runs_and_guards(model_dir):
    """--speculate K drives the n-gram speculative generator end-to-end —
    greedy AND sampled (r4: rejection sampling makes temperature > 0
    legal) — and still rejects paths that would ignore it."""
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5,7,3,5,7",
        "-n", "8", "--temperature", "0", "--max-seq", "64", "--cpu",
        "--speculate", "4",
    ])
    assert r.returncode == 0, r.stderr
    assert any(l and all(c.isdigit() or c == "," for c in l)
               for l in r.stdout.splitlines())
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5,7", "-n", "2",
        "--cpu", "--speculate", "4",  # default temperature 1.0: rejection
    ])                                # sampling path — runs fine now
    assert r.returncode == 0, r.stderr
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5,7", "-n", "2",
        "--temperature", "0", "--cpu", "--speculate", "4", "--sp", "2",
    ])
    assert r.returncode != 0 and "--speculate" in r.stderr


def test_speculate_runs_on_mesh_pipeline(model_dir):
    """--speculate composes with --stages/--tp: the verification pass runs
    as one program over the mesh and the token stream matches the plain
    mesh run."""
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    argv = ["--model", str(model_dir), "--prompt-ids", "3,5,7,3,5,7",
            "-n", "8", "--temperature", "0", "--max-seq", "64", "--cpu",
            "--stages", "2", "--tp", "2"]
    plain = subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli"] + argv,
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    spec = subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli"] + argv + ["--speculate", "4"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert plain.returncode == 0, plain.stderr
    assert spec.returncode == 0, spec.stderr

    def toks(out):
        return [l for l in out.splitlines()
                if l and all(c.isdigit() or c == "," for c in l)][-1]

    assert toks(spec.stdout) == toks(plain.stdout)


def test_profile_flag_writes_trace(model_dir, tmp_path):
    trace_dir = tmp_path / "trace"
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5", "-n", "3",
        "--temperature", "0", "--max-seq", "32", "--cpu",
        "--profile", str(trace_dir),
    ])
    assert r.returncode == 0, r.stderr
    assert trace_dir.exists() and any(trace_dir.rglob("*"))


def test_missing_config_errors(tmp_path):
    r = _run_cli(["--model", str(tmp_path), "--prompt-ids", "1", "-n", "1"])
    assert r.returncode != 0
    assert "config.json not found" in r.stderr


def test_failure_domain_flags_need_host_topology(model_dir):
    """--recover-deadline/--connect-retries/--op-timeout/--chaos drive
    cross-host worker links; anywhere else they must error loudly instead
    of being silently ignored (in-process: the exit fires right after
    config load)."""
    from cake_tpu import cli

    for flags, frag in (
        (["--op-timeout", "5"], "--op-timeout"),
        (["--chaos", "kill@1"], "--chaos"),
        (["--connect-retries", "2", "--recover-deadline", "9"],
         "--connect-retries"),
    ):
        with pytest.raises(SystemExit) as e:
            cli.main(["--model", str(model_dir), "--prompt-ids", "1",
                      "--cpu", "-n", "1"] + flags)
        assert frag in str(e.value) and "topology" in str(e.value)


def test_op_timeout_zero_rejected(model_dir, tmp_path):
    """--op-timeout 0 is NOT a 'no deadline' mode (0 would mean disabled
    to SO_RCVTIMEO but non-blocking to settimeout) — reject it before it
    can silently reopen the hung-peer hole."""
    from cake_tpu import cli

    topo = tmp_path / "t.yml"
    topo.write_text("w:\n  host: 127.0.0.1:1\n  layers: [model.layers.0-3]\n")
    for flag, val in (("--op-timeout", "0"), ("--recover-deadline", "-1")):
        with pytest.raises(SystemExit) as e:
            cli.main(["--model", str(model_dir), "--topology", str(topo),
                      "--prompt-ids", "1", "--cpu", "-n", "1", flag, val])
        assert "must exceed 0" in str(e.value)


def test_failure_domain_flags_rejected_in_worker_mode(model_dir):
    from cake_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["--model", str(model_dir), "--mode", "worker", "--name",
                  "w", "--topology", "whatever.yml", "--cpu",
                  "--chaos", "seed=1"])
    assert "master process" in str(e.value)


def test_kv_layout_flags_validated(model_dir):
    """--kv-layout paged rides the batched serving engine (serve /
    --prompts-file); elsewhere — and for the page knobs without paged —
    the CLI errors loudly instead of silently ignoring the layout."""
    from cake_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main(["--model", str(model_dir), "--prompt-ids", "1", "--cpu",
                  "-n", "1", "--kv-layout", "paged"])
    assert "--kv-layout paged" in str(e.value)
    for flag, val in (("--kv-page-size", "8"), ("--kv-pool-pages", "64")):
        with pytest.raises(SystemExit) as e:
            cli.main(["--model", str(model_dir), "--prompt-ids", "1",
                      "--cpu", "-n", "1", flag, val])
        assert "--kv-layout paged" in str(e.value)


def test_serve_flags_need_serve_mode(model_dir):
    """--serve-port/--max-concurrent/... configure the HTTP serving plane;
    on the one-shot master/worker paths they must error loudly instead of
    being silently ignored (and --mode serve refuses the one-shot prompt
    sources, which arrive over HTTP instead)."""
    from cake_tpu import cli

    for flags, frag in (
        (["--serve-port", "8080"], "--serve-port"),
        (["--max-concurrent", "4", "--queue-depth", "8"],
         "--max-concurrent"),
        (["--request-timeout", "30"], "--request-timeout"),
    ):
        with pytest.raises(SystemExit) as e:
            cli.main(["--model", str(model_dir), "--prompt-ids", "1",
                      "--cpu", "-n", "1"] + flags)
        assert frag in str(e.value) and "--mode serve" in str(e.value)
    with pytest.raises(SystemExit) as e:
        cli.main(["--model", str(model_dir), "--mode", "serve", "--cpu",
                  "--prompt-ids", "1"])
    assert "over HTTP" in str(e.value)
    for flags in (["--prefill-chunks", "2"], ["--top"]):
        with pytest.raises(SystemExit) as e:
            cli.main(["--model", str(model_dir), "--mode", "serve",
                      "--cpu"] + flags)
        assert "silently ignored" in str(e.value)
    for flag, val in (("--max-concurrent", "0"), ("--queue-depth", "0"),
                      ("--request-timeout", "0")):
        with pytest.raises(SystemExit) as e:
            cli.main(["--model", str(model_dir), "--mode", "serve",
                      "--cpu", flag, val])
        assert "must" in str(e.value)


@pytest.mark.slow
def test_serve_mode_e2e_with_drain(model_dir):
    """--mode serve end to end through the real CLI: SSE completion over
    HTTP, then SIGTERM drains and exits 0 (the serving plane's acceptance
    loop; the in-process surface is covered by tests/test_serve.py)."""
    import signal
    import socket
    import time
    import urllib.request

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
         "--mode", "serve", "--cpu", "--max-seq", "32",
         "--serve-port", str(port), "--max-concurrent", "2",
         "--queue-depth", "4", "--request-timeout", "60",
         "--temperature", "0"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    try:
        for _ in range(240):
            if proc.poll() is not None:
                pytest.fail(f"serve died rc={proc.returncode}: "
                            f"{proc.stderr.read().decode()[-2000:]}")
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=1)
                break
            except OSError:
                time.sleep(0.5)
        else:
            pytest.fail("serve never came up")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt_ids": [3, 5, 7], "max_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            body = r.read()
        assert body.count(b"data: ") == 6  # 4 tokens + done + [DONE]
        assert b"[DONE]" in body
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
        assert b"drained" in proc.stderr.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


def test_string_prompt_without_tokenizer_errors(model_dir):
    r = _run_cli([
        "--model", str(model_dir), "--prompt", "hello", "-n", "1", "--cpu",
    ])
    assert r.returncode != 0
    assert "--prompt-ids" in r.stderr


def test_worker_requires_name(model_dir):
    r = _run_cli(["--model", str(model_dir), "--mode", "worker"])
    assert r.returncode != 0
    assert "--name" in r.stderr


def test_master_worker_loopback_via_cli(model_dir, tmp_path):
    """The full reference deployment shape driven through the real CLI:
    `--mode worker` serves its topology-assigned layers over TCP, the
    master walks local + remote segments and streams tokens (main.rs
    master/worker dispatch, end to end)."""
    import socket
    import time

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    topo = tmp_path / "topo.yml"
    topo.write_text(
        f"w1:\n  host: 127.0.0.1:{port}\n  layers:\n    - model.layers.2-3\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    worker_log = tmp_path / "worker.log"
    with open(worker_log, "wb") as logf:
        worker = subprocess.Popen(
            [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
             "--mode", "worker", "--name", "w1", "--topology", str(topo),
             "--address", f"127.0.0.1:{port}", "--max-seq", "32", "--cpu"],
            env=env, stdout=logf, stderr=logf,  # file: no pipe-full deadlock
        )
    try:
        # wait for the worker to listen
        for _ in range(120):
            if worker.poll() is not None:
                pytest.fail(f"worker died rc={worker.returncode}: "
                            f"{worker_log.read_text()[-2000:]}")
            try:
                probe = socket.create_connection(("127.0.0.1", port),
                                                 timeout=1)
                probe.close()
                break
            except OSError:
                time.sleep(0.5)
        else:
            pytest.fail("worker never started listening: "
                        f"{worker_log.read_text()[-2000:]}")
        r = _run_cli([
            "--model", str(model_dir), "--prompt-ids", "3,5,7", "-n", "4",
            "--temperature", "0", "--max-seq", "32", "--cpu",
            "--topology", str(topo), "-v",
        ])
        assert r.returncode == 0, r.stderr
        assert "tok/s" in r.stderr
        assert f"127.0.0.1:{port}" in r.stderr  # remote segment stats logged
    finally:
        worker.terminate()
        try:
            worker.wait(timeout=30)
        except subprocess.TimeoutExpired:
            worker.kill()  # don't mask the real failure or leak the process


def test_prompts_file_serves_over_sp_window(model_dir, tmp_path):
    """--prompts-file --sp 2 (r4): the serving batch decodes against a
    sequence-sharded KV window; streams identical to the sp=1 run."""
    pf = tmp_path / "prompts.txt"
    pf.write_text("3,5,7\n2,4\n")

    def run(extra):
        r = _run_cli(["--model", str(model_dir), "--prompts-file", str(pf),
                      "--prompts-ids", "-n", "4", "--temperature", "0",
                      "--max-seq", "32", "--cpu"] + extra, devices=8)
        assert r.returncode == 0, r.stderr
        return [l for l in r.stdout.splitlines() if l.startswith("[")]

    assert run(["--sp", "2"]) == run([])
    # --speculate stays the sp == 1 serving path
    r = _run_cli(["--model", str(model_dir), "--prompts-file", str(pf),
                  "--prompts-ids", "--cpu", "--sp", "2", "--speculate", "4"],
                 timeout=120, devices=8)
    assert r.returncode != 0 and "--sp 1" in r.stderr
    # --max-seq not divisible by --sp: clean error, not a traceback
    r = _run_cli(["--model", str(model_dir), "--prompts-file", str(pf),
                  "--prompts-ids", "--cpu", "--sp", "2", "--max-seq", "31"],
                 timeout=120, devices=8)
    assert r.returncode != 0 and r.stderr.startswith("error:")
    assert "sp 2" in r.stderr and "Traceback" not in r.stderr


def test_window_override(tmp_path):
    """--window grants/narrows the attention window from the CLI; 0
    disables a checkpoint's own window."""
    import dataclasses
    import json

    import jax

    from cake_tpu.models import llama as L
    from cake_tpu.models.config import tiny
    from cake_tpu.utils.weights import save_llama_params

    cfg = tiny(max_seq_len=64)
    save_llama_params(L.init_params(cfg, jax.random.PRNGKey(0)), tmp_path,
                      cfg.num_hidden_layers)
    (tmp_path / "config.json").write_text(json.dumps(cfg.to_hf_dict()))
    base = ["--model", str(tmp_path), "--prompt-ids", "3,5,7,9,2,8,1,4",
            "-n", "6", "--temperature", "0", "--max-seq", "64", "--cpu",
            "--dtype", "f32"]
    def toks(argv):
        r = _run_cli(argv)
        assert r.returncode == 0, r.stderr[-2000:]
        return r.stdout.strip().splitlines()[-1]

    plain = toks(base)
    windowed = toks(base + ["--window", "4"])
    assert plain != windowed  # the override genuinely narrows attention
    assert toks(base + ["--window", "0"]) == plain  # 0 == no window

    # a mistral config's own window applies by default and is disabled
    # by --window 0
    mcfg = dataclasses.replace(cfg, model_type="mistral", sliding_window=4)
    (tmp_path / "config.json").write_text(json.dumps(mcfg.to_hf_dict()))
    assert toks(base + ["--window", "0"]) == plain
    assert toks(base) == windowed


def test_lookahead_and_wire_codec_flag_guards(model_dir):
    """--lookahead with --decode-block 1 and a compressing --wire-codec on
    a non-topology run are rejected loudly (not silently ignored); spelling
    out the default --wire-codec none anywhere is a harmless no-op."""
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5", "-n", "2",
        "--temperature", "0", "--max-seq", "32", "--cpu",
        "--lookahead", "--decode-block", "1",
    ])
    assert r.returncode != 0
    assert "requires --decode-block > 1" in r.stderr
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5", "-n", "2",
        "--temperature", "0", "--max-seq", "32", "--cpu",
        "--wire-codec", "int8",
    ])
    assert r.returncode != 0
    assert "host-addressed --topology" in r.stderr
    r = _run_cli([
        "--model", str(model_dir), "--prompt-ids", "3,5", "-n", "2",
        "--temperature", "0", "--max-seq", "32", "--cpu",
        "--wire-codec", "none", "--lookahead", "--decode-block", "4",
    ])
    assert r.returncode == 0, r.stderr
