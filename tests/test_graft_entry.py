"""Driver-contract tests for __graft_entry__.

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(n)`` with n virtual CPU devices in an environment whose
sitecustomize can hang JAX backend init (VERDICT round 1, weak #1). These
tests pin the hardened behavior: module import stays side-effect free and
the dryrun completes via the sanitized subprocess.
"""

import os
import subprocess
import sys


def test_import_does_not_touch_jax_backend():
    # Importing the module in a fresh interpreter must not initialize any
    # JAX backend (that is what hangs under a wedged TPU plugin).
    # Run the child with PYTHONPATH pinned to the repo root so the
    # machine's sitecustomize (which itself imports jax at interpreter
    # startup, masking the check) never loads: 'jax' absent from
    # sys.modules after import then proves the module is side-effect free.
    code = (
        "import sys; import __graft_entry__; "
        "assert 'jax' not in sys.modules, 'module import pulled in jax'; "
        "print('clean')"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, "PYTHONPATH": repo},
        cwd=repo,
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "clean" in r.stdout


def test_dryrun_multichip_subprocess():
    import __graft_entry__ as g

    # Runs in a sanitized subprocess regardless of this process's JAX state.
    g.dryrun_multichip(8)
