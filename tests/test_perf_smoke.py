"""Perf smoke: the disabled observability hot path must be near-zero.

`span()` and `flight.recorder().record()` sit on the per-token decode loop;
when tracing/flight are off they must cost an attribute check, not kwarg
formatting or dict building (the runtime call sites guard with
`rec.enabled` / precomputed span tags for exactly this). The micro-bench
bounds here are ~20x above what a laptop measures (<0.5 us/call) so CI
noise cannot trip them while a real regression — say a dict build or
f-string sneaking back onto the disabled path at 10x — still does.

`make perf-smoke` runs this module plus the codec loopback
(tests/test_wire_codec.py); both are tier-1 (`not slow`).
"""

import time

from cake_tpu.obs import flight, trace
from cake_tpu.obs.trace import span


def _best_per_call(fn, n=20_000, trials=5) -> float:
    """Median-of-trials per-call seconds (the min of several runs is the
    stable estimator for a micro-bench under CI scheduling noise)."""
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fn(n)
        times.append((time.perf_counter() - t0) / n)
    return min(times)


def test_disabled_span_is_near_zero():
    tr = trace.tracer()
    assert not tr.enabled

    def loop(n):
        for i in range(n):
            with span("decode.step", index=i):
                pass

    per_call = _best_per_call(loop)
    assert per_call < 10e-6, f"disabled span() cost {per_call * 1e6:.2f}us"


def test_disabled_flight_record_is_near_zero():
    rec = flight.recorder()
    assert not rec.enabled

    def loop(n):
        for i in range(n):
            rec.record(index=i, kind="decode", total_ms=1.0, steps=1)

    per_call = _best_per_call(loop)
    assert per_call < 10e-6, f"disabled record() cost {per_call * 1e6:.2f}us"


def test_enabled_guard_skips_field_construction():
    """The hot-path pattern: callers check `rec.enabled` before building
    record kwargs, so the disabled cost is one attribute read."""
    rec = flight.recorder()
    assert not rec.enabled

    def loop(n):
        for i in range(n):
            if rec.enabled:
                rec.record(index=i, total_ms=round(i * 0.1, 3))

    per_call = _best_per_call(loop)
    assert per_call < 2e-6, f"guarded record cost {per_call * 1e6:.2f}us"


def test_disabled_registry_instruments_are_noops():
    from cake_tpu.obs.metrics import Registry

    reg = Registry(enabled=False)
    ctr = reg.counter("hot")
    hist = reg.histogram("hot_ms")

    def loop(n):
        for _ in range(n):
            ctr.inc()
            hist.observe(1.0)

    per_call = _best_per_call(loop)
    assert per_call < 10e-6, f"null instrument cost {per_call * 1e6:.2f}us"


def test_unsampled_prof_step_is_near_zero():
    """The step-phase profiler between samples: `step_begin` pays one
    integer increment, each `phase()` site one attribute check returning
    the shared null context, `step_end` one attribute read — the whole
    unsampled step must stay in the same near-zero class as a disabled
    span (the <= 3% obs budget rides on this)."""
    from cake_tpu.obs import prof

    p = prof.StepProfiler(sample_every=10_000_000)

    def loop(n):
        for _ in range(n):
            p.step_begin()
            with p.phase("dispatch"):
                pass
            with p.phase("sync"):
                pass
            with p.phase("emit"):
                pass
            p.step_end()

    per_call = _best_per_call(loop)
    assert per_call < 10e-6, f"unsampled prof step {per_call * 1e6:.2f}us"
