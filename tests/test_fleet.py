"""Fleet elasticity (ISSUE 19): self-registration, admission shedding,
rolling restarts with session re-homing, and control-plane chaos.

`make fleet-smoke` acceptance: a gateway started with ZERO static
backends forms its fleet from `/v1/fleet/register` leases (storm-proof,
idempotent); an explicit deregister pins the member DRAINING before its
503s ever start (zero 5xx through the drain window); a lapsed lease
demotes through the probe hysteresis and is GC'd, never instantly
deleted; a saturated fleet queues interactive requests briefly and then
sheds with a fleet-derived Retry-After (batch class sheds immediately);
a gateway killed and restarted with an empty member list re-forms from
heartbeat re-registrations within one heartbeat interval; a rolling
restart migrates in-flight decode streams to a sibling over the
KV-transfer plane bit-identically; and a live 2->3->2 resize under
Poisson load completes with zero failed requests.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from cake_tpu.gateway import health as health_mod
from cake_tpu.gateway.api import start_gateway
from cake_tpu.gateway.health import (DRAINING, DYNAMIC, STATIC, UP, Backend,
                                     HealthMonitor)
from cake_tpu.gateway.policy import make_policy
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from test_gateway import _StubBackend, _get, _post, _post_sse, _url

_LOAD_OK = {"queued": 0, "running": 0, "max_concurrent": 4}


# -- helpers ----------------------------------------------------------------


def _fleet_post(gw, path: str, body: dict, timeout: float = 10.0) -> dict:
    req = urllib.request.Request(
        _url(gw) + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read() or b"{}")


def _post_sse_hook(url: str, body: dict, after_n: int, hook,
                   timeout: float = 120.0):
    """Stream one request; after ``after_n`` delivered token frames run
    ``hook()`` once (inline — the server keeps generating into the
    socket buffer meanwhile), then keep reading to the end."""
    body = dict(body, stream=True)
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events, n_tok, fired = [], 0, False
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            data = raw[len(b"data: "):]
            ev = data.decode() if data == b"[DONE]" else json.loads(data)
            events.append(ev)
            if isinstance(ev, dict) and "token" in ev:
                n_tok += 1
            if not fired and n_tok >= after_n:
                fired = True
                hook()
    assert fired, f"stream ended after {n_tok} tokens, before the hook"
    return events


def _tokens_of(events):
    return [e for e in events if isinstance(e, dict) and "token" in e]


@pytest.fixture
def empty_gateway():
    """Factory: gateway whose fleet starts EMPTY (membership formed
    purely from registrations); everything torn down at test end."""
    created = []

    def build(policy="round_robin", **monitor_kw):
        monitor_kw.setdefault("probe_interval", 0.2)
        monitor_kw.setdefault("up_after", 1)
        mon = HealthMonitor([], allow_empty=True, **monitor_kw).start()
        gw = start_gateway(mon, make_policy(policy),
                           connect_timeout=1.0, read_timeout=60.0)
        created.append((gw, mon))
        return gw, mon

    yield build
    for gw, mon in created:
        gw.close()
        mon.stop()


@pytest.fixture(scope="module")
def tiny_params():
    cfg = tiny(max_seq_len=192, eos_token_id=-1)
    return cfg, llama.init_params(cfg, jax.random.PRNGKey(0))


# -- lease-plane units ------------------------------------------------------


def test_lease_lifecycle_unit():
    b = Backend("d900", "127.0.0.1:9", registered_via=DYNAMIC)
    now = 100.0
    b.lease_renew(0.5, now=now)
    assert not b.lease_expired(now + 0.4)
    assert b.lease_expired(now + 0.6)
    assert b.lease_note_expiry(now + 0.6) is True
    assert b.lease_note_expiry(now + 0.7) is False  # once per episode
    b.lease_renew(0.5, now=now + 1.0)  # renewal re-arms the edge
    assert not b.lease_expired(now + 1.2)
    assert b.lease_note_expiry(now + 1.6) is True
    # static seeds hold no lease and are immortal to the GC
    s = Backend("s900", "127.0.0.1:9")
    assert s.registered_via == STATIC
    assert not s.lease_expired(now)
    assert s.lease_gc_due(now + 9999.0, 0.0) is False


def test_deregister_pin_blocks_probe_promotion():
    """The drain race, distilled: a 200 probe landing AFTER the explicit
    deregister must not flip the member back UP — only a fresh
    registration (the replica saying it is back) outranks the goodbye."""
    b = Backend("d901", "127.0.0.1:9", registered_via=DYNAMIC)
    b.probe_ok(_LOAD_OK, 1)
    assert b.routable()
    b.mark_deregistered()
    assert b.state == DRAINING
    for _ in range(3):
        b.probe_ok(_LOAD_OK, 1)
    assert b.state == DRAINING, "a probe promoted a deregistered member"
    b.lease_renew(5.0)
    b.probe_ok(_LOAD_OK, 1)
    assert b.routable()


# -- registration plane over HTTP -------------------------------------------


def test_register_ack_routing_and_healthz_entry(empty_gateway):
    gw, mon = empty_gateway(lease_ttl_s=5.0)
    stub = _StubBackend("ok")
    try:
        ack = _fleet_post(gw, "/v1/fleet/register", {"addr": stub.addr})
        assert ack["ok"] is True and ack["state"] == UP
        assert ack["name"].startswith("d")
        assert ack["lease_ttl_s"] == 5.0
        # the gateway dictates the heartbeat cadence: inside the TTL
        assert 0.2 <= ack["heartbeat_s"] < ack["lease_ttl_s"]

        out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
        assert out["usage"]["completion_tokens"] == 2
        assert stub.completions == 1

        health = _get(_url(gw) + "/healthz")
        entry = health["backends"][ack["name"]]
        assert entry["state"] == UP
        assert entry["registered_via"] == "dynamic"
        assert entry["lease_expires_in_s"] is not None
        assert 0 < entry["lease_expires_in_s"] <= 5.0
        assert entry["last_probe_age_s"] is not None

        # draining an unknown member is a loud 404, not a silent no-op
        with pytest.raises(urllib.error.HTTPError) as exc:
            _fleet_post(gw, "/v1/fleet/drain/127.0.0.1:1", {})
        assert exc.value.code == 404
    finally:
        stub.close()


def test_static_seed_and_dynamic_member_coexist(empty_gateway):
    """--backends stays as static seeds: no lease, never expires, never
    GC'd — and /healthz tells the two membership origins apart."""
    seed = _StubBackend("ok")
    mon = HealthMonitor([Backend("seed0", seed.addr)], probe_interval=0.2,
                        up_after=1, lease_ttl_s=5.0).start()
    gw = start_gateway(mon, make_policy("round_robin"),
                       connect_timeout=1.0, read_timeout=60.0)
    dyn = _StubBackend("ok")
    try:
        ack = _fleet_post(gw, "/v1/fleet/register", {"addr": dyn.addr})
        health = _get(_url(gw) + "/healthz")
        assert health["backends"]["seed0"]["registered_via"] == "static"
        assert health["backends"]["seed0"]["lease_expires_in_s"] is None
        assert health["backends"][ack["name"]]["registered_via"] == "dynamic"
        assert health["backends_up"] == 2
    finally:
        gw.close()
        mon.stop()
        seed.close()
        dyn.close()


def test_registration_storm_is_idempotent(empty_gateway):
    """Satellite: 100 concurrent re-registrations of ONE backend update
    one lease in place — never a phantom second member."""
    gw, mon = empty_gateway()
    stub = _StubBackend("ok")
    try:
        reg0 = health_mod.REGISTRATIONS.value
        acks: list = []

        def hit():
            try:
                acks.append(_fleet_post(gw, "/v1/fleet/register",
                                        {"addr": stub.addr}, timeout=30.0))
            except Exception as e:  # noqa: BLE001 - collected for assert
                acks.append(e)

        threads = [threading.Thread(target=hit) for _ in range(100)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        oks = [a for a in acks if isinstance(a, dict) and a.get("ok")]
        assert len(oks) == 100, f"storm lost acks: {acks}"
        assert len({a["name"] for a in oks}) == 1  # one identity
        assert [b.addr for b in mon.backends] == [stub.addr]
        assert health_mod.REGISTRATIONS.value - reg0 >= 100
        out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
        assert out["usage"]["completion_tokens"] == 2
    finally:
        stub.close()


def test_drain_window_zero_503s(empty_gateway):
    """Satellite: the deregister lands BEFORE the replica's 503s start.
    Probes are parked far away (30 s), so only the explicit deregister
    can save the probe-race window — zero failed requests through it."""
    gw, mon = empty_gateway(probe_interval=30.0)
    a, b = _StubBackend("ok"), _StubBackend("ok")
    try:
        _fleet_post(gw, "/v1/fleet/register", {"addr": a.addr})
        _fleet_post(gw, "/v1/fleet/register", {"addr": b.addr})
        # replica A announces its exit, THEN starts failing
        _fleet_post(gw, "/v1/fleet/deregister", {"addr": a.addr})
        a.mode = "draining"
        for _ in range(8):
            out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
            assert out["usage"]["completion_tokens"] == 2
        assert a.completions == 0, "a request routed into the exit"
        assert b.completions == 8
        assert mon.lookup(a.addr).state == DRAINING
        # stale deregister of an unknown member: harmless no-op
        ack = _fleet_post(gw, "/v1/fleet/deregister",
                          {"addr": "127.0.0.1:1"})
        assert ack["ok"] is True and ack["known"] is False
        # ...and the replica comes back by simply re-registering
        a.mode = "ok"
        _fleet_post(gw, "/v1/fleet/register", {"addr": a.addr})
        assert mon.lookup(a.addr).routable()
    finally:
        a.close()
        b.close()


def test_lease_expiry_demotes_then_gc_reaps(empty_gateway):
    """A crashed replica (no heartbeat, no probe answer): the lease
    expiry demotes through the hysteresis, and only after a full GC
    window does the member leave the list entirely."""
    gw, mon = empty_gateway(lease_ttl_s=0.5, lease_gc_s=0.3,
                            probe_interval=0.1, down_after=2)
    stub = _StubBackend("ok")
    exp0 = health_mod.LEASE_EXPIRED.value
    try:
        _fleet_post(gw, "/v1/fleet/register", {"addr": stub.addr})
        assert len(mon.routable()) == 1
    finally:
        stub.close()  # crash: probes fail AND renewals stop
    deadline = time.time() + 20
    while time.time() < deadline and mon.backends:
        time.sleep(0.05)
    assert not mon.backends, "expired member was never GC'd"
    assert health_mod.LEASE_EXPIRED.value > exp0


# -- admission control ------------------------------------------------------


def test_admission_queue_rides_out_brief_saturation():
    """A 429 that will clear within the admission budget: the request
    queues (gateway.queued_admissions moves) and then completes — no
    client-visible 429 for a blip."""
    from cake_tpu.gateway import api as gw_api

    flaky = _StubBackend("flaky429", retry_after="1")
    mon = HealthMonitor([Backend("adm0", flaky.addr)], probe_interval=30.0,
                        up_after=1).start()
    gw = start_gateway(mon, make_policy("round_robin"), connect_timeout=1.0,
                       read_timeout=60.0, admit_wait_s=3.0)
    try:
        q0 = gw_api.QUEUED_ADMISSIONS.value
        t0 = time.monotonic()
        out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
        wall = time.monotonic() - t0
        assert out["usage"]["completion_tokens"] == 2
        assert gw_api.QUEUED_ADMISSIONS.value > q0
        assert wall >= 0.8, f"never actually waited ({wall:.2f}s)"
        assert flaky.completions == 1  # exactly one 429, then served
    finally:
        gw.close()
        mon.stop()
        flaky.close()


def test_batch_class_sheds_immediately():
    """"class": "batch" is the load to shed first: no admission queue,
    an instant fleet-derived 429 with shed marker."""
    from cake_tpu.gateway import api as gw_api

    sat = _StubBackend("reject429", retry_after="9")
    mon = HealthMonitor([Backend("adm1", sat.addr)], probe_interval=30.0,
                        up_after=1).start()
    gw = start_gateway(mon, make_policy("round_robin"), connect_timeout=1.0,
                       read_timeout=60.0, admit_wait_s=5.0)
    try:
        q0 = gw_api.QUEUED_ADMISSIONS.value
        shed0 = gw_api.SHED.value
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2,
                             "class": "batch"})
        wall = time.monotonic() - t0
        assert exc.value.code == 429
        body = json.loads(exc.value.read())
        assert body["shed"] is True
        assert 1 <= body["retry_after_s"] <= 30
        assert int(exc.value.headers["Retry-After"]) == body["retry_after_s"]
        assert wall < 2.0, "batch class rode the admission queue"
        assert gw_api.QUEUED_ADMISSIONS.value == q0
        assert gw_api.SHED.value > shed0
    finally:
        gw.close()
        mon.stop()
        sat.close()


# -- gateway restart + control-plane chaos ----------------------------------


def test_gateway_restart_reforms_fleet_from_heartbeats():
    """Satellite: kill the gateway mid-fleet, restart it with an EMPTY
    member list on the same port — heartbeat re-registrations re-form
    the whole fleet within about one heartbeat interval, and a retrying
    client sails through the blip."""
    from cake_tpu.serve.register import Registrar
    from cake_tpu.testing.chaos import ControlFault, ControlPlaneChaos

    def _mon():
        return HealthMonitor([], probe_interval=0.2, up_after=1,
                             lease_ttl_s=0.9, allow_empty=True).start()

    a, b = _StubBackend("ok"), _StubBackend("ok")
    state = {"mon": _mon()}
    state["gw"] = start_gateway(state["mon"], make_policy("round_robin"),
                                connect_timeout=1.0, read_timeout=60.0)
    port = state["gw"].port
    url = f"http://127.0.0.1:{port}"
    # ack-driven cadence: lease_ttl 0.9 -> the gateway asks for 0.3 s
    regs = [Registrar(url, s.addr, heartbeat_s=0.25).start()
            for s in (a, b)]

    def restart():
        state["gw"].close()
        state["mon"].stop()
        state["mon"] = _mon()
        state["gw"] = start_gateway(state["mon"],
                                    make_policy("round_robin"),
                                    port=port, connect_timeout=1.0,
                                    read_timeout=60.0)

    try:
        deadline = time.time() + 10
        while time.time() < deadline and len(state["mon"].routable()) < 2:
            time.sleep(0.02)
        assert len(state["mon"].routable()) == 2

        ControlPlaneChaos(url, [a.addr, b.addr],
                          restart_fn=restart).apply(
                              ControlFault("gw_restart"))
        t0 = time.monotonic()
        deadline = time.time() + 10
        while time.time() < deadline and len(state["mon"].routable()) < 2:
            time.sleep(0.02)
        reform_s = time.monotonic() - t0
        assert len(state["mon"].routable()) == 2, \
            "fleet never re-formed after the gateway restart"
        assert reform_s < 2.0, (  # one 0.3 s heartbeat, with slack
            f"re-form took {reform_s:.2f}s — longer than a heartbeat")
        out = None
        for _ in range(50):  # the client's view: retry through the blip
            try:
                out = _post(url, {"prompt_ids": [1], "max_tokens": 2})
                break
            except (urllib.error.URLError, OSError):
                time.sleep(0.1)
        assert out is not None and out["usage"]["completion_tokens"] == 2

        # graceful leave: deregister stops the heartbeat AND the routing
        regs[0].deregister()
        assert state["mon"].lookup(a.addr).state == DRAINING
        time.sleep(0.8)  # >2 heartbeats: no zombie renewal re-joins it
        assert state["mon"].lookup(a.addr).state == DRAINING
    finally:
        for r in regs:
            r.stop()
        state["gw"].close()
        state["mon"].stop()
        a.close()
        b.close()


def test_control_plane_chaos_matrix(empty_gateway):
    """The seeded fault schedule (storms, flaps, stale deregisters,
    duplicate registrations) against a live gateway: membership stays
    sane — exactly the real members, all routable, zero 5xx after."""
    from cake_tpu.testing.chaos import (ControlFault, ControlPlaneChaos,
                                        control_schedule_from_seed)

    schedule = control_schedule_from_seed(19, n=6)
    assert ([str(f) for f in schedule]
            == [str(f) for f in control_schedule_from_seed(19, n=6)])
    with pytest.raises(ValueError):
        ControlFault("fork_bomb")
    with pytest.raises(ValueError):
        ControlPlaneChaos("http://127.0.0.1:1", ["127.0.0.1:1"]).apply(
            ControlFault("gw_restart"))  # needs a restart_fn armed

    gw, mon = empty_gateway(lease_ttl_s=2.0)
    a, b = _StubBackend("ok"), _StubBackend("ok")
    try:
        for s in (a, b):
            _fleet_post(gw, "/v1/fleet/register", {"addr": s.addr})
        chaos = ControlPlaneChaos(_url(gw), [a.addr, b.addr])
        chaos.run(schedule)
        assert chaos.events == [str(f) for f in schedule]
        deadline = time.time() + 10
        while time.time() < deadline and len(mon.routable()) < 2:
            time.sleep(0.05)
        assert len(mon.routable()) == 2
        # no phantom members survived the storm/flap/dup barrage
        assert sorted(x.addr for x in mon.backends) == sorted(
            [a.addr, b.addr])
        for _ in range(6):
            out = _post(_url(gw), {"prompt_ids": [1], "max_tokens": 2})
            assert out["usage"]["completion_tokens"] == 2
        assert a.completions + b.completions == 6
    finally:
        a.close()
        b.close()


# -- rolling restart with live migration (real engines) ---------------------


def test_rolling_restart_migrates_stream_bit_identical(tiny_params):
    """The tentpole acceptance: drain a replica mid-stream through the
    gateway — the in-flight decode stream migrates to the sibling over
    the KV-transfer plane and the client's spliced stream is
    bit-identical to an uninterrupted run."""
    from cake_tpu.serve import scheduler as scheduler_mod
    from cake_tpu.tools.loadgen import _spawn_replica

    cfg, params = tiny_params
    srv_a, sched_a, ts_a = _spawn_replica(cfg, params, paged=True,
                                          transfer=True)
    srv_b, sched_b, ts_b = _spawn_replica(cfg, params, paged=True,
                                          transfer=True)
    addr_a, addr_b = (f"127.0.0.1:{srv_a.port}", f"127.0.0.1:{srv_b.port}")
    mon = HealthMonitor([], probe_interval=0.3, up_after=1, lease_ttl_s=5.0,
                        allow_empty=True).start()
    gw = start_gateway(mon, make_policy("round_robin"),
                       connect_timeout=1.0, read_timeout=120.0)
    body = {"prompt_ids": [3, 1, 4, 1, 5, 9, 2, 6], "max_tokens": 120}
    try:
        _fleet_post(gw, "/v1/fleet/register",
                    {"addr": addr_a, "transfer_port": ts_a.port})
        # baseline: replica A alone, uninterrupted
        base_events, _ = _post_sse(_url(gw), body)
        base_tokens = _tokens_of(base_events)
        assert len(base_tokens) == 120

        migrated0 = scheduler_mod.MIGRATED.value
        acks = {}

        def drain_a():
            _fleet_post(gw, "/v1/fleet/register",
                        {"addr": addr_b, "transfer_port": ts_b.port})
            acks["drain"] = _fleet_post(gw, f"/v1/fleet/drain/{addr_a}",
                                        {}, timeout=60.0)

        events = _post_sse_hook(_url(gw), body, after_n=3, hook=drain_a)
        assert not [e for e in events
                    if isinstance(e, dict) and e.get("error")]
        assert events[-1] == "[DONE]"
        done = [e for e in events if isinstance(e, dict) and e.get("done")]
        assert len(done) == 1 and done[0]["finish_reason"] == "length"
        # the spliced stream: every token frame identical to baseline
        assert _tokens_of(events) == base_tokens
        assert acks["drain"]["ok"] is True
        assert acks["drain"]["migrate_to"]["addr"] == addr_b
        assert scheduler_mod.MIGRATED.value > migrated0, \
            "the stream never actually migrated"
        # the drained replica is out of rotation; traffic lands on B
        out = _post(_url(gw), {"prompt_ids": [1, 2], "max_tokens": 4})
        assert out["usage"]["completion_tokens"] == 4
        assert mon.lookup(addr_a).state == DRAINING
    finally:
        gw.close()
        mon.stop()
        for srv, sched, ts in ((srv_a, sched_a, ts_a),
                               (srv_b, sched_b, ts_b)):
            srv.close()
            ts.stop()
            sched.close()


def test_live_resize_under_load_zero_failures():
    """The end-state demo: a self-registered fleet grows 2->3 and
    shrinks back to 2 under open-loop Poisson load — the shrink is a
    rolling restart through the gateway's drain flow — with zero failed
    requests."""
    from cake_tpu.tools.loadgen import run_load, spawn_elastic_fleet

    handle = spawn_elastic_fleet(2, max_concurrent=2, queue_depth=16,
                                 max_seq=128)
    try:
        def cycle():
            time.sleep(0.5)
            handle.resize(3)
            time.sleep(1.0)
            handle.resize(2)

        resizer = threading.Thread(target=cycle, daemon=True)
        resizer.start()
        stats = run_load(handle.url, 24, concurrency=4, max_tokens=8,
                         rate=12.0, seed=3, stream=True, retry_429=True,
                         timeout=120.0)
        resizer.join(timeout=180)
        assert not resizer.is_alive(), "resize cycle never finished"
        assert stats["errors"] == 0, f"failed requests: {stats}"
        assert stats["completed"] == 24, f"incomplete run: {stats}"
        assert any(e.startswith("grow") for e in handle.events)
        assert any(e.startswith("drain") for e in handle.events)
        assert handle.size() == 2
    finally:
        handle.cleanup()
