"""Request-scoped fleet tracing + SLO accounting (cake_tpu/obs/reqtrace).

`make reqtrace-smoke` acceptance: traceparent headers are honored (and
malformed ones safely re-minted), spans nest/parent correctly across
threads and processes, the RequestLog merges a request's tier halves
into one timeline behind ``GET /v1/requests/<id>``, SLO verdicts and
burn-rate gauges move with traffic, a traced serve replica emits the
full span set for a real streamed request (mirrored into the Perfetto
tracer), and loadgen's goodput gate judges the same targets end to end.
"""

import json
import os
import time
import urllib.error
import urllib.request

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import reqtrace
from cake_tpu.obs import trace as obs_trace
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.scheduler import Scheduler
from cake_tpu.tools.loadgen import run_load

CFG = tiny(max_seq_len=64, eos_token_id=-1)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _serve_stack(params, slo=None):
    gen = BatchGenerator(CFG, params,
                         settings=SamplerSettings(**GREEDY))
    sched = Scheduler(gen, queue_depth=8, request_timeout_s=60, slo=slo)
    sched.start(max_concurrent=2, warm_prompt_len=8)
    srv = start_api_server(sched)
    return srv, sched


def _mint_header():
    """A client-side traceparent with a known trace id + root span."""
    tid = os.urandom(16).hex()
    root = os.urandom(8).hex()
    return tid, root, f"00-{tid}-{root}-01"


def _stream_ids(url, prompt_ids, max_tokens=6, headers=None):
    req = urllib.request.Request(
        url + "/v1/completions",
        data=json.dumps({"prompt_ids": prompt_ids,
                         "max_tokens": max_tokens,
                         "stream": True}).encode(),
        headers=dict({"Content-Type": "application/json"}, **(headers or {})))
    ids = []
    with urllib.request.urlopen(req, timeout=60) as r:
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            data = raw[len(b"data: "):]
            if data == b"[DONE]":
                break
            ev = json.loads(data)
            assert "error" not in ev, ev
            if "token" in ev:
                ids.append(ev["token"])
    return ids


def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, json.loads(r.read())


def _poll_timeline(key, want_names, deadline_s=10.0):
    """The request log fills asynchronously (gateway finish, engine-side
    finish); poll until the entry covers ``want_names``."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        tl = reqtrace.request_log().get(key)
        if tl is not None and want_names <= {s["name"]
                                             for s in tl["spans"]}:
            return tl
        time.sleep(0.05)
    raise AssertionError(
        f"timeline for {key!r} never covered {want_names}; "
        f"last: {tl and [s['name'] for s in tl['spans']]}")


def _assert_connected(tl, roots=()):
    """Every span's parent is another span in the same timeline or one
    of the known inbound roots — the one-connected-trace property."""
    ids = {s["span"] for s in tl["spans"]}
    for s in tl["spans"]:
        parent = s.get("parent")
        assert parent is None or parent in ids or parent in roots, \
            f"span {s['name']} parented to unknown {parent}"


# -- header parsing / minting ------------------------------------------------


class TestHeader:
    def test_mint_is_unique_and_wellformed(self):
        a, b = reqtrace.ReqTrace.mint(), reqtrace.ReqTrace.mint()
        assert a.trace_id != b.trace_id
        assert len(a.trace_id) == 32 and int(a.trace_id, 16)
        assert a.parent_id is None

    def test_honors_wellformed_header(self):
        tid, root, header = _mint_header()
        ctx = reqtrace.ReqTrace.from_header(header)
        assert ctx.trace_id == tid and ctx.parent_id == root

    @pytest.mark.parametrize("bad", [
        "junk", "00-zz-11-01", "00-" + "0" * 32 + "-" + "1" * 16 + "-01",
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",
        "00-" + "g" * 32 + "-" + "1" * 16 + "-01",
    ])
    def test_malformed_counts_error_and_mints(self, bad):
        e0 = obs_metrics.counter("reqtrace.header_errors").value
        ctx = reqtrace.ReqTrace.from_header(bad)
        assert len(ctx.trace_id) == 32 and ctx.parent_id is None
        assert obs_metrics.counter("reqtrace.header_errors").value == e0 + 1

    def test_missing_header_mints_without_error(self):
        e0 = obs_metrics.counter("reqtrace.header_errors").value
        assert reqtrace.ReqTrace.from_header(None).trace_id
        assert obs_metrics.counter("reqtrace.header_errors").value == e0

    def test_outbound_header_roundtrips(self):
        ctx = reqtrace.ReqTrace.mint()
        sid = ctx.add_span("x", time.time(), 1.0)
        hop = reqtrace.ReqTrace.from_header(ctx.header())
        assert hop.trace_id == ctx.trace_id and hop.parent_id == sid


# -- span recording ----------------------------------------------------------


class TestSpans:
    def test_nested_spans_parent_to_enclosing(self):
        tid, root, header = _mint_header()
        ctx = reqtrace.ReqTrace.from_header(header)
        with ctx.span("outer"):
            with ctx.span("inner"):
                pass
        inner, outer = ctx.spans()
        assert inner["parent"] == outer["span"]
        assert outer["parent"] == root
        assert inner["ms"] >= 0 and inner["pid"] == os.getpid()

    def test_failed_span_records_error_arg(self):
        ctx = reqtrace.ReqTrace.mint()
        with pytest.raises(RuntimeError):
            with ctx.span("doomed", attempt=1):
                raise RuntimeError("boom")
        (s,) = ctx.spans()
        assert s["args"] == {"attempt": 1, "error": "RuntimeError"}

    def test_event_is_zero_duration(self):
        ctx = reqtrace.ReqTrace.mint()
        ctx.event("tick", k=1)
        (s,) = ctx.spans()
        assert s["ms"] == 0.0 and s["args"]["k"] == 1

    def test_span_cap_bounds_memory(self):
        ctx = reqtrace.ReqTrace.mint()
        for i in range(reqtrace.MAX_SPANS + 16):
            ctx.add_span("s", time.time(), 0.0)
        assert len(ctx.spans()) == reqtrace.MAX_SPANS

    def test_wire_roundtrip(self):
        ctx = reqtrace.ReqTrace.mint()
        ctx.request_id = "req-1"
        sid = ctx.add_span("export", time.time(), 2.0)
        hop = reqtrace.ReqTrace.from_wire(ctx.wire())
        assert hop.trace_id == ctx.trace_id
        assert hop.parent_id == sid and hop.request_id == "req-1"
        assert reqtrace.ReqTrace.from_wire(None) is None
        assert reqtrace.ReqTrace.from_wire({}) is None

    def test_spans_mirror_into_tracer(self):
        obs_trace.tracer().start(max_events=10_000)
        try:
            ctx = reqtrace.ReqTrace.mint()
            with ctx.span("mirrored", leg=1):
                pass
            doc = obs_trace.tracer().to_chrome_trace()
        finally:
            obs_trace.tracer().stop()
            obs_trace.tracer().clear()
        evs = [e for e in doc["traceEvents"]
               if e.get("name") == "mirrored"]
        assert evs and evs[0]["args"]["trace"] == ctx.trace_id
        assert evs[0]["args"]["span"] == ctx.spans()[0]["span"]


# -- the request log ---------------------------------------------------------


class TestRequestLog:
    def test_merges_tier_halves_by_trace_id(self):
        rlog = reqtrace.RequestLog(cap=8)
        tid = os.urandom(16).hex()
        pre = reqtrace.ReqTrace(tid)
        pre.add_span("disagg.export", time.time() - 1.0, 3.0)
        rlog.put(pre)
        dec = reqtrace.ReqTrace(tid)
        dec.request_id = "req-9"
        dec.add_span("disagg.import", time.time(), 2.0)
        rlog.put(dec)
        rlog.put(pre)  # duplicate put: spans must not double
        tl = rlog.get(tid)
        assert [s["name"] for s in tl["spans"]] == \
            ["disagg.export", "disagg.import"]  # sorted by start time
        assert tl["request_id"] == "req-9"
        assert rlog.get("req-9")["trace_id"] == tid  # alias
        assert len(rlog) == 1

    def test_unknown_key_is_none(self):
        assert reqtrace.RequestLog(cap=2).get("nope") is None

    def test_bounded_eviction(self):
        rlog = reqtrace.RequestLog(cap=2)
        ctxs = [reqtrace.ReqTrace(os.urandom(16).hex()) for _ in range(3)]
        for c in ctxs:
            c.event("x")
            rlog.put(c)
        assert len(rlog) == 2
        assert rlog.get(ctxs[0].trace_id) is None
        assert rlog.get(ctxs[2].trace_id) is not None


# -- cross-tier stitching ----------------------------------------------------


class TestStitch:
    def test_foreign_spans_land_own_pid_spans_skipped(self):
        tid = os.urandom(16).hex()
        tl = {"trace_id": tid, "spans": [
            {"name": "remote.leg", "span": "aa" * 8, "t": time.time(),
             "ms": 2.0, "pid": os.getpid() + 99_999},
            {"name": "local.leg", "span": "bb" * 8, "t": time.time(),
             "ms": 1.0, "pid": os.getpid()},
        ]}
        obs_trace.tracer().start(max_events=10_000)
        try:
            assert reqtrace.stitch_timeline(tl, "b0@127.0.0.1:1") == 1
            doc = obs_trace.tracer().to_chrome_trace()
        finally:
            obs_trace.tracer().stop()
            obs_trace.tracer().clear()
        names = [e["name"] for e in doc["traceEvents"]]
        assert "remote.leg" in names and "local.leg" not in names
        # the source became its own named process track
        procs = [e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("name") == "process_name"]
        assert "b0@127.0.0.1:1" in procs

    def test_disabled_tracer_stitches_nothing(self):
        tl = {"trace_id": "t", "spans": [
            {"name": "x", "span": "cc" * 8, "t": time.time(), "ms": 1.0,
             "pid": os.getpid() + 1}]}
        assert reqtrace.stitch_timeline(tl, "src") == 0


# -- SLO policy + burn accounting --------------------------------------------


class TestSlo:
    def test_verdict_judges_set_halves_only(self):
        pol = reqtrace.SloPolicy(ttft_ms=100.0)
        assert pol.verdict(50.0, 999.0)["good"]   # tpot untargeted
        assert not pol.verdict(150.0, None)["good"]
        both = reqtrace.SloPolicy(ttft_ms=100.0, tpot_ms=10.0)
        v = both.verdict(50.0, 20.0)
        assert not v["good"] and v["ttft_ok"] and not v["tpot_ok"]
        # a missing measurement passes its half (no TPOT on a 1-token
        # reply is not a miss)
        assert both.verdict(50.0, None)["good"]
        assert not reqtrace.SloPolicy().enabled and both.enabled

    def test_tracker_counts_and_burn(self):
        g0 = obs_metrics.counter("slo.good").value
        b0 = obs_metrics.counter("slo.bad").value
        t = reqtrace.SloTracker(
            reqtrace.SloPolicy(ttft_ms=100.0, objective=0.5))
        assert t.observe(10.0, None)["good"]
        assert not t.observe(500.0, None)["good"]
        assert obs_metrics.counter("slo.good").value == g0 + 1
        assert obs_metrics.counter("slo.bad").value == b0 + 1
        snap = t.snapshot()
        # 1 bad of 2 in-window at a 0.5 error budget: burning exactly
        # at the allowed rate
        assert snap["window_n"] == 2 and snap["window_bad"] == 1
        assert snap["burn_short"] == pytest.approx(1.0)
        assert snap["burn_long"] == pytest.approx(1.0)
        assert snap["ttft_target_ms"] == 100.0

    def test_burn_zero_when_empty_or_all_good(self):
        t = reqtrace.SloTracker(reqtrace.SloPolicy(tpot_ms=50.0))
        assert t.snapshot()["burn_short"] == 0.0
        t.observe(None, 10.0)
        assert t.snapshot()["burn_short"] == 0.0


# -- serve end to end --------------------------------------------------------


SERVE_SPANS = {"serve.queue", "serve.admit", "engine.prefill",
               "decode.first_token", "session.emit"}


class TestServeTracing:
    def test_traced_request_full_span_set(self, params):
        slo = reqtrace.SloTracker(
            reqtrace.SloPolicy(ttft_ms=60_000.0, tpot_ms=60_000.0))
        srv, sched = _serve_stack(params, slo=slo)
        obs_trace.tracer().start(max_events=100_000)
        tid, root, header = _mint_header()
        try:
            url = f"http://127.0.0.1:{srv.port}"
            ids = _stream_ids(url, [1, 2, 3], max_tokens=6,
                              headers={reqtrace.HEADER: header})
            assert len(ids) == 6
            tl = _poll_timeline(tid, SERVE_SPANS)
            assert tl["trace_id"] == tid
            _assert_connected(tl, roots={root})
            # the SLO verdict rode the timeline; targets were loose
            assert tl["slo"]["good"] and tl["slo"]["ttft_ok"]
            # ... and the same timeline answers by request id
            assert tl["request_id"]
            _, by_req = _get_json(
                f"{url}/v1/requests/{tl['request_id']}")
            assert by_req["trace_id"] == tid
            # /healthz carries the burn block
            _, health = _get_json(f"{url}/healthz")
            assert health["slo"]["window_n"] >= 1
            assert health["slo"]["burn_short"] == 0.0
            # every reqtrace span mirrored into the Perfetto export
            doc = obs_trace.tracer().to_chrome_trace()
            traced = {e["name"] for e in doc["traceEvents"]
                      if e.get("args", {}).get("trace") == tid}
            assert SERVE_SPANS <= traced
            for e in doc["traceEvents"]:
                if e.get("ph") == "X":
                    assert {"name", "ts", "dur", "pid",
                            "tid"} <= set(e)
        finally:
            obs_trace.tracer().stop()
            obs_trace.tracer().clear()
            srv.close()
            sched.close()

    def test_unknown_request_404s(self, params):
        srv, sched = _serve_stack(params)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/requests/nope")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 404
        finally:
            srv.close()
            sched.close()

    def test_tight_targets_burn_the_budget(self, params):
        b0 = obs_metrics.counter("slo.bad").value
        slo = reqtrace.SloTracker(reqtrace.SloPolicy(ttft_ms=0.001))
        srv, sched = _serve_stack(params, slo=slo)
        try:
            url = f"http://127.0.0.1:{srv.port}"
            for _ in range(2):
                _stream_ids(url, [4, 5], max_tokens=3)
            assert obs_metrics.counter("slo.bad").value >= b0 + 2
            _, health = _get_json(f"{url}/healthz")
            assert health["slo"]["burn_short"] > 1.0
            assert health["slo"]["window_bad"] >= 2
        finally:
            srv.close()
            sched.close()


# -- loadgen goodput ---------------------------------------------------------


class TestLoadgenGoodput:
    def test_goodput_judges_targets(self, params):
        srv, sched = _serve_stack(params)
        try:
            url = f"http://127.0.0.1:{srv.port}"
            loose = run_load(url, 4, concurrency=2, max_tokens=4,
                             slo_ttft_ms=60_000.0, slo_tpot_ms=60_000.0)
            assert loose["completed"] == 4
            assert loose["slo"]["goodput"] == 1.0
            assert loose["slo"]["good"] == 4
            tight = run_load(url, 4, concurrency=2, max_tokens=4,
                             slo_ttft_ms=0.0001)
            assert tight["slo"]["goodput"] == 0.0
            plain = run_load(url, 2, concurrency=2, max_tokens=4)
            assert "slo" not in plain
        finally:
            srv.close()
            sched.close()

    def test_cli_goodput_gate_needs_target(self, capsys):
        from cake_tpu.tools.loadgen import main
        with pytest.raises(SystemExit):
            main(["http://127.0.0.1:1", "--slo-goodput-min", "0.9"])


# -- cli wiring --------------------------------------------------------------


class TestCliWiring:
    def test_slo_flags_build_tracker_and_gate_modes(self):
        from cake_tpu.cli import _serve_flags, _slo_tracker, build_parser
        p = build_parser()
        args = p.parse_args(["--model", "m", "--mode", "serve",
                             "--slo-ttft-ms", "120",
                             "--slo-tpot-ms", "15"])
        assert {"--slo-ttft-ms", "--slo-tpot-ms"} <= set(
            _serve_flags(args))
        t = _slo_tracker(args)
        assert t.policy.ttft_ms == 120.0 and t.policy.tpot_ms == 15.0
        bare = p.parse_args(["--model", "m", "--mode", "serve"])
        assert _slo_tracker(bare) is None
        assert "--slo-ttft-ms" not in _serve_flags(bare)
