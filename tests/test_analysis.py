"""cakelint (cake_tpu/analysis): fixture tests per checker + repo self-run.

Every checker gets at least one true-positive fixture (the bug class it
exists for) and negative fixtures (the idioms it must NOT flag — the
false-positive surface is what makes a linter ignorable). The self-run
test is the CI gate's gate: the tree at HEAD, against the committed
baseline, must be clean with no stale entries.
"""

import json
import textwrap

import pytest

from cake_tpu import analysis
from cake_tpu.analysis import baseline as baseline_mod
from cake_tpu.analysis import core
from cake_tpu.analysis.engine_ownership import EngineOwnershipChecker
from cake_tpu.analysis.guarded_by import GuardedByChecker
from cake_tpu.analysis.metrics_catalog import MetricsCatalogChecker
from cake_tpu.analysis.trace_purity import TracePurityChecker
from cake_tpu.analysis.wire_safety import WireSafetyChecker


def lint(tmp_path, source, checker, rel="pkg/mod.py"):
    """Run one checker over one snippet in a scratch repo; return
    findings."""
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return core.run_checkers([checker], roots=[str(f)], repo_root=tmp_path)


# -- CK-METRIC: metrics catalog ------------------------------------------

class TestMetricsCatalog:
    def test_undeclared_literal_flagged(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            BAD = obs_metrics.counter("wire.byte_out")  # typo'd fork
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].checker == "CK-METRIC"
        assert "wire.byte_out" in out[0].message
        assert out[0].key == "wire.byte_out"

    def test_declared_literal_ok(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            OK1 = obs_metrics.counter("wire.bytes_out")
            OK2 = obs_metrics.histogram("serve.ttft_ms")
            OK3 = obs_metrics.Gauge("worker.warmup_ms")
        """, MetricsCatalogChecker())
        assert out == []

    def test_fstring_must_match_declared_pattern(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            def make(i):
                ok = obs_metrics.Histogram(f"master.segment{i}.decode_ms")
                bad = obs_metrics.Histogram(f"master.seg{i}.decode_ms")
                return ok, bad
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].key == "master.seg*.decode_ms"

    def test_non_literal_name_flagged(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            def make(name):
                return obs_metrics.gauge(name)
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].key == "non-literal:make"

    def test_keyword_name_not_a_bypass(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            BAD = obs_metrics.counter(name="wire.byte_out")
            OK = obs_metrics.Counter(name="wire.bytes_out")
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].key == "wire.byte_out"

    def test_foreign_counter_constructor_ignored(self, tmp_path):
        # collections.Counter et al. must not be dragged into scope
        out = lint(tmp_path, """
            from collections import Counter
            c = Counter("hello world no dots".split())
        """, MetricsCatalogChecker())
        assert out == []


# -- CK-ENGINE: single engine owner --------------------------------------

class TestEngineOwnership:
    def test_direct_drive_flagged(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.runtime.batch_generator import BatchGenerator
            gen = BatchGenerator(cfg, params)
            gen.set_prompts([[1]])
            gen.step()
            gen.finish(0)
        """, EngineOwnershipChecker())
        assert {f.key for f in out} == {
            "BatchGenerator.set_prompts", "BatchGenerator.step",
            "BatchGenerator.finish"}

    def test_engine_attribute_flagged(self, tmp_path):
        out = lint(tmp_path, """
            def poke(scheduler):
                scheduler.engine.enqueue([1], 0)  # bypasses the owner
        """, EngineOwnershipChecker())
        assert len(out) == 1
        assert out[0].key == "BatchGenerator.enqueue"

    def test_scheduler_is_allowed(self, tmp_path):
        out = lint(tmp_path, """
            class Scheduler:
                def _run(self):
                    self.engine.step()
        """, EngineOwnershipChecker(), rel="cake_tpu/serve/scheduler.py")
        assert out == []

    def test_unrelated_finish_ok(self, tmp_path):
        out = lint(tmp_path, """
            def flush(stream, sess):
                stream.finish()      # TokenOutputStream, not an engine
                sess.finish("stop")  # Session, not an engine
        """, EngineOwnershipChecker())
        assert out == []


# -- CK-LOCK: _GUARDED_BY discipline -------------------------------------

class TestGuardedBy:
    def test_unlocked_touch_flagged(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_items": "_lock"}
                def peek(self):
                    return list(self._items)
        """, GuardedByChecker())
        assert len(out) == 1
        assert out[0].checker == "CK-LOCK"
        assert "Box.peek" in out[0].message

    def test_locked_touch_and_escapes_ok(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_items": "_lock"}
                def __init__(self):
                    self._items = []          # construction happens-before
                def add(self, x):
                    with self._lock:
                        self._items.append(x)
                def _clear_locked(self):
                    self._items.clear()       # caller holds the lock
        """, GuardedByChecker())
        assert out == []

    def test_shadowing_local_is_not_the_global(self, tmp_path):
        # a function-local binding that shadows a guarded global is a
        # different variable entirely (no `global` declaration)
        out = lint(tmp_path, """
            import threading
            _LOCK = threading.Lock()
            _cache = None
            _GUARDED_BY = {"_cache": "_LOCK"}

            def local_only():
                _cache = []
                _cache.append(1)
                return _cache

            def param_shadow(_cache):
                return len(_cache)

            def real_touch():
                global _cache
                _cache = []   # BAD: writes the guarded global unlocked
        """, GuardedByChecker())
        assert len(out) == 1
        assert "real_touch" in out[0].message

    def test_module_global_map(self, tmp_path):
        out = lint(tmp_path, """
            import threading
            _LOCK = threading.Lock()
            _cache = None
            _GUARDED_BY = {"_cache": "_LOCK"}

            def good():
                with _LOCK:
                    return _cache

            def bad():
                return _cache
        """, GuardedByChecker())
        assert len(out) == 1
        assert "bad" in out[0].message

    def test_suppression_comment(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n  # cakelint: ignore[CK-LOCK]
        """, GuardedByChecker())
        assert out == []

    def test_suppression_multi_id_with_spaces(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n  # cakelint: ignore[CK-WIRE, CK-LOCK]
        """, GuardedByChecker())
        assert out == []


# -- CK-JIT: trace purity -------------------------------------------------

class TestTracePurity:
    def test_time_in_jitted_fn_flagged(self, tmp_path):
        out = lint(tmp_path, """
            import time, jax
            def step(x):
                t = time.perf_counter()
                return x + t
            f = jax.jit(step)
        """, TracePurityChecker())
        assert len(out) == 1
        assert "time.perf_counter" in out[0].message

    def test_partial_and_decorator_resolved(self, tmp_path):
        out = lint(tmp_path, """
            import jax
            from functools import partial

            def inner(x, k):
                print("traced once")
                return x * k
            g = jax.jit(partial(inner, k=2))

            @partial(jax.jit, static_argnums=(0,))
            def decorated(n, x):
                REJECTED.inc()
                return x * n
        """, TracePurityChecker())
        assert {f.key for f in out} == {"inner:print",
                                        "decorated:REJECTED.inc"}

    def test_shard_map_body_checked(self, tmp_path):
        out = lint(tmp_path, """
            import random, jax
            from cake_tpu.parallel.mesh import shard_map
            def stage(x):
                return x * random.random()
            f = jax.jit(shard_map(stage, mesh=None))
        """, TracePurityChecker())
        assert len(out) == 1
        assert "random.random" in out[0].message

    def test_pure_and_host_side_ok(self, tmp_path):
        out = lint(tmp_path, """
            import time, jax
            def pure(x):
                return jax.random.fold_in(x, 1)  # keyed: fine
            f = jax.jit(pure)
            def host_loop(f, x):
                t0 = time.perf_counter()  # not traced: fine
                print(f(x))
        """, TracePurityChecker())
        assert out == []


# -- CK-WIRE: recv deadlines, resources, protocol arms --------------------

class TestWireSafety:
    def test_recv_without_timeout_flagged(self, tmp_path):
        out = lint(tmp_path, """
            def pump(conn):
                t, payload = conn.recv()
        """, WireSafetyChecker())
        assert len(out) == 1
        assert out[0].key == "recv:conn"

    def test_recv_explicit_ok(self, tmp_path):
        out = lint(tmp_path, """
            def pump(conn, sock):
                conn.recv(timeout=5.0)
                conn.recv(timeout=None)  # explicit block-forever decision
                sock.recv(4096)          # raw byte read: framing bounds it
        """, WireSafetyChecker())
        assert out == []

    def test_leaky_acquisition_flagged(self, tmp_path):
        out = lint(tmp_path, """
            import socket
            def dial(host, port, Connection):
                sock = socket.create_connection((host, port))
                sock.setsockopt(1, 2, 3)   # may raise: sock leaks
                return Connection(sock=sock)
        """, WireSafetyChecker())
        assert len(out) == 1
        assert out[0].key == "res:create_connection:dial:sock"

    def test_protected_and_immediate_ok(self, tmp_path):
        out = lint(tmp_path, """
            import socket
            def good_with(path):
                with open(path) as f:
                    return f.read()
            def good_immediate(host, Connection):
                sock = socket.create_connection((host, 1))
                return Connection(sock=sock)
            def good_protected(host, Connection):
                sock = socket.create_connection((host, 1))
                try:
                    sock.setsockopt(1, 2, 3)
                except Exception:
                    sock.close()
                    raise
                return Connection(sock=sock)
            class Owner:
                def open(self, path):
                    self._fh = open(path, "a")  # ownership moved
        """, WireSafetyChecker())
        assert out == []

    def test_read_is_not_a_release(self, tmp_path):
        # `data = sock.recv(n)` is a READ; the caller still owns the
        # socket, and the raising parse after it must keep the finding
        out = lint(tmp_path, """
            import socket
            def probe(host, parse):
                s = socket.create_connection((host, 1))
                data = s.recv(100)
                return parse(data)   # may raise: s leaks
        """, WireSafetyChecker())
        assert len(out) == 1
        assert out[0].key == "res:create_connection:probe:s"

    def test_late_try_does_not_cover_early_risk(self, tmp_path):
        # a try/finally that closes the var but starts AFTER a raising
        # statement does not protect the held-bare region before it
        out = lint(tmp_path, """
            import socket
            def serve(host, risky_setup, use):
                s = socket.create_connection((host, 1))
                risky_setup()        # raises -> s leaks
                try:
                    use(s)
                finally:
                    s.close()
        """, WireSafetyChecker())
        assert len(out) == 1
        assert out[0].key == "res:create_connection:serve:s"

    def test_adjacent_try_protects(self, tmp_path):
        # ...but the same try as the VERY NEXT statement does protect,
        # including when the acquisition sits inside its own try (the
        # chaos-proxy shape)
        out = lint(tmp_path, """
            import socket
            def dial(host, use):
                s = socket.create_connection((host, 1))
                try:
                    use(s)
                finally:
                    s.close()
            def dial_nested(host, setup, consume):
                try:
                    s = socket.create_connection((host, 1))
                except OSError:
                    return None
                try:
                    setup(s)
                except OSError:
                    s.close()
                    raise
                return consume(s)
        """, WireSafetyChecker())
        assert out == []

    def test_store_in_container_is_a_handoff(self, tmp_path):
        # storing a resource in a longer-lived owner transfers ownership
        # — both the bound and the unbound spelling
        out = lint(tmp_path, """
            import socket
            def pool_up(hosts, conns):
                for h in hosts:
                    c = socket.create_connection((h, 1))
                    conns.append(c)
            class Pool:
                def grow(self, path):
                    self.files.append(open(path))
        """, WireSafetyChecker())
        assert out == []

    def test_guarded_conditional_close_ok(self, tmp_path):
        # the worker accept-loop idiom: the guard test is part of the
        # release decision, not held-bare work
        out = lint(tmp_path, """
            def loop(listener, stop, handle):
                conn = listener.accept()
                if stop.is_set():
                    conn.close()
                    return
                handle(conn)
        """, WireSafetyChecker())
        assert out == []

    def test_msgtype_missing_arm_flagged(self, tmp_path):
        repo = tmp_path
        (repo / "proto.py").write_text(textwrap.dedent("""
            from enum import IntEnum
            class MsgType(IntEnum):
                HELLO = 1
                ORPHAN = 2
        """))
        (repo / "peer.py").write_text(textwrap.dedent("""
            from proto import MsgType
            def talk(conn):
                conn.send(MsgType.HELLO)
                conn.send(MsgType.ORPHAN, b"x")
                t, _ = conn.recv(timeout=1)
                if t == MsgType.HELLO:
                    return True
        """))
        out = core.run_checkers([WireSafetyChecker()],
                                roots=[str(repo)], repo_root=repo)
        assert [f.key for f in out] == ["MsgType.ORPHAN:dispatch"]

    def test_msgtype_pass_skipped_on_file_scoped_scan(self):
        """'never sent anywhere' is meaningless when 'anywhere' is one
        file: linting protocol.py alone must not spray bogus MsgType
        findings (the per-module arms still run)."""
        out = core.run_checkers(
            [WireSafetyChecker()],
            roots=["cake_tpu/runtime/protocol.py"])
        assert [f for f in out if f.key.startswith("MsgType.")] == []


# -- framework: baseline, suppression, CLI --------------------------------

class TestBaseline:
    def _finding(self, key="BatchGenerator.step", path="examples/x.py",
                 line=10):
        return core.Finding(checker="CK-ENGINE", path=path, line=line,
                            col=0, message="m", key=key)

    def test_suppresses_by_key_not_line(self):
        entry = baseline_mod.BaselineEntry(
            checker="CK-ENGINE", path="examples/x.py",
            key="BatchGenerator.step", justification="demo")
        new, suppressed, stale = baseline_mod.apply(
            [self._finding(line=10), self._finding(line=99)], [entry])
        assert new == [] and len(suppressed) == 2 and stale == []

    def test_stale_entry_reported(self):
        entry = baseline_mod.BaselineEntry(
            checker="CK-ENGINE", path="examples/x.py", key="gone",
            justification="was fixed")
        new, suppressed, stale = baseline_mod.apply(
            [self._finding()], [entry])
        assert len(new) == 1 and stale == [entry]

    def test_stale_respects_run_scope(self):
        # a subset run must not call live out-of-scope entries "fixed"
        entry = baseline_mod.BaselineEntry(
            checker="CK-ENGINE", path="examples/x.py",
            key="BatchGenerator.step", justification="demo")
        _, _, stale = baseline_mod.apply(
            [], [entry], checker_ids={"CK-METRIC"}, paths={"examples/x.py"})
        assert stale == []
        _, _, stale = baseline_mod.apply(
            [], [entry], checker_ids={"CK-ENGINE"}, paths={"other.py"})
        assert stale == []
        _, _, stale = baseline_mod.apply(
            [], [entry], checker_ids={"CK-ENGINE"},
            paths={"examples/x.py"})
        assert stale == [entry]

    def test_justification_required(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"version": 1, "entries": [
            {"checker": "CK-X", "path": "a.py", "key": "k"}]}))
        with pytest.raises(ValueError, match="justification"):
            baseline_mod.load(p)

    def test_roundtrip(self, tmp_path):
        p = tmp_path / "b.json"
        entries = baseline_mod.from_findings([self._finding()], "why")
        baseline_mod.save(p, entries)
        assert baseline_mod.load(p) == entries


class TestCli:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        from cake_tpu.analysis.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text("from cake_tpu.obs import metrics as m\n"
                       "c = m.counter('serve.typo_ms')\n")
        assert main([str(bad), "--json"]) == 1
        report = json.loads(capsys.readouterr().out)
        assert report["summary"]["new"] == 1
        assert report["new"][0]["checker"] == "CK-METRIC"

        base = tmp_path / "base.json"
        assert main([str(bad), "--write-baseline", str(base)]) == 0
        # stub justifications must be replaced before load() accepts
        # them — accept the stub here to prove the grandfather path
        data = json.loads(base.read_text())
        for e in data["entries"]:
            e["justification"] = "fixture"
        base.write_text(json.dumps(data))
        assert main([str(bad), "--baseline", str(base)]) == 0

        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good)]) == 0

    def test_list_and_unknown_checker(self, capsys):
        from cake_tpu.analysis.__main__ import main

        assert main(["--list"]) == 0
        listed = capsys.readouterr().out
        for cls in analysis.ALL_CHECKERS:
            assert cls.id in listed
        assert main(["--checkers", "CK-NOPE"]) == 2


# -- catalog + strict registry -------------------------------------------

class TestCatalog:
    def test_declarations_well_formed(self):
        from cake_tpu.obs import catalog

        kinds = {catalog.COUNTER, catalog.GAUGE, catalog.HISTOGRAM}
        for name, (kind, help_) in {**catalog.SERIES,
                                    **catalog.DYNAMIC}.items():
            assert kind in kinds, name
            assert help_, name
        assert catalog.is_declared("wire.bytes_out")
        assert catalog.is_declared("master.segment3.decode_ms")
        assert catalog.is_declared("cluster.w0.rtt_ms")
        assert not catalog.is_declared("wire.byte_out")
        assert catalog.kind_of("serve.ttft_ms") == catalog.HISTOGRAM
        assert catalog.kind_of("nope") is None

    def test_strict_registry_enforces_catalog(self):
        from cake_tpu.obs import metrics

        reg = metrics.Registry(enabled=True, strict=True)
        reg.counter("wire.bytes_out")  # declared: fine
        with pytest.raises(ValueError, match="not declared"):
            reg.counter("wire.byte_out")
        with pytest.raises(ValueError, match="not declared"):
            reg.register("serve.nope", metrics.Counter("serve.nope"))

    def test_every_catalog_entry_is_used(self):
        """The reverse check: a declared series nobody emits is a stale
        doc. Scan the tree for series-name literals/patterns and compare
        (the static half only — DYNAMIC families count via patterns)."""
        import ast as ast_mod

        from cake_tpu.obs import catalog

        used: set[str] = set()
        mods, _ = core.load_modules()
        for mod in mods:
            for node in ast_mod.walk(mod.tree):
                if not isinstance(node, ast_mod.Call):
                    continue
                name = core.call_name(node)
                if name.lower() not in ("counter", "gauge", "histogram"):
                    continue
                if not node.args:
                    continue
                lit = core.literal_str(node.args[0])
                pat = core.fstring_pattern(node.args[0])
                if lit:
                    used.add(lit)
                if pat:
                    used.add(pat)
        unused = [n for n in catalog.SERIES if n not in used]
        unused += [p for p in catalog.DYNAMIC if p not in used]
        assert unused == [], f"catalog entries nothing emits: {unused}"


# -- the gate's gate: repo self-run ---------------------------------------

class TestSelfRun:
    def test_repo_clean_at_head(self):
        """The tree + committed baseline = zero new findings, zero stale
        entries. This is exactly what `make lint` enforces in CI."""
        findings = analysis.run()
        entries = baseline_mod.load(core.REPO_ROOT /
                                    "analysis-baseline.json")
        new, suppressed, stale = baseline_mod.apply(findings, entries)
        assert new == [], "\n".join(f.render() for f in new)
        assert stale == [], [e.match_key for e in stale]
        # the baseline is not a dumping ground: only the deliberate
        # direct-drive sites and the protocol-compat member live there
        assert {e.checker for e in entries} <= {"CK-ENGINE", "CK-WIRE"}

    def test_every_checker_registered(self):
        ids = {c.id for c in analysis.default_checkers()}
        assert ids == {"CK-METRIC", "CK-ENGINE", "CK-LOCK", "CK-JIT",
                       "CK-WIRE"}
