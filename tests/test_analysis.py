"""cakelint (cake_tpu/analysis): fixture tests per checker + repo self-run.

Every checker gets at least one true-positive fixture (the bug class it
exists for) and negative fixtures (the idioms it must NOT flag — the
false-positive surface is what makes a linter ignorable). The self-run
test is the CI gate's gate: the tree at HEAD, against the committed
baseline, must be clean with no stale entries.
"""

import json
import textwrap
import threading

import pytest

from cake_tpu import analysis
from cake_tpu.analysis import baseline as baseline_mod
from cake_tpu.analysis import core
from cake_tpu.analysis.claims import ClaimChecker
from cake_tpu.analysis.engine_ownership import EngineOwnershipChecker
from cake_tpu.analysis.guarded_by import GuardedByChecker
from cake_tpu.analysis.metrics_catalog import MetricsCatalogChecker
from cake_tpu.analysis.thread_domains import ThreadDomainChecker
from cake_tpu.analysis.trace_purity import TracePurityChecker
from cake_tpu.analysis.wire_safety import WireSafetyChecker


def lint(tmp_path, source, checker, rel="pkg/mod.py"):
    """Run one checker over one snippet in a scratch repo; return
    findings."""
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return core.run_checkers([checker], roots=[str(f)], repo_root=tmp_path)


def lint_full(tmp_path, sources, checker):
    """Full-repo scan over ``{rel: source}`` fixtures (finalize passes
    included — what cross-file checkers need)."""
    for rel, source in sources.items():
        f = tmp_path / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(source))
    return core.run_checkers([checker], roots=[str(tmp_path)],
                             repo_root=tmp_path)


# -- CK-METRIC: metrics catalog ------------------------------------------

class TestMetricsCatalog:
    def test_undeclared_literal_flagged(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            BAD = obs_metrics.counter("wire.byte_out")  # typo'd fork
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].checker == "CK-METRIC"
        assert "wire.byte_out" in out[0].message
        assert out[0].key == "wire.byte_out"

    def test_declared_literal_ok(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            OK1 = obs_metrics.counter("wire.bytes_out")
            OK2 = obs_metrics.histogram("serve.ttft_ms")
            OK3 = obs_metrics.Gauge("worker.warmup_ms")
        """, MetricsCatalogChecker())
        assert out == []

    def test_fstring_must_match_declared_pattern(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            def make(i):
                ok = obs_metrics.Histogram(f"master.segment{i}.decode_ms")
                bad = obs_metrics.Histogram(f"master.seg{i}.decode_ms")
                return ok, bad
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].key == "master.seg*.decode_ms"

    def test_non_literal_name_flagged(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            def make(name):
                return obs_metrics.gauge(name)
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].key == "non-literal:make"

    def test_keyword_name_not_a_bypass(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.obs import metrics as obs_metrics
            BAD = obs_metrics.counter(name="wire.byte_out")
            OK = obs_metrics.Counter(name="wire.bytes_out")
        """, MetricsCatalogChecker())
        assert len(out) == 1
        assert out[0].key == "wire.byte_out"

    def test_foreign_counter_constructor_ignored(self, tmp_path):
        # collections.Counter et al. must not be dragged into scope
        out = lint(tmp_path, """
            from collections import Counter
            c = Counter("hello world no dots".split())
        """, MetricsCatalogChecker())
        assert out == []


# -- CK-ENGINE: single engine owner --------------------------------------

class TestEngineOwnership:
    def test_direct_drive_flagged(self, tmp_path):
        out = lint(tmp_path, """
            from cake_tpu.runtime.batch_generator import BatchGenerator
            gen = BatchGenerator(cfg, params)
            gen.set_prompts([[1]])
            gen.step()
            gen.finish(0)
        """, EngineOwnershipChecker())
        assert {f.key for f in out} == {
            "BatchGenerator.set_prompts", "BatchGenerator.step",
            "BatchGenerator.finish"}

    def test_engine_attribute_flagged(self, tmp_path):
        out = lint(tmp_path, """
            def poke(scheduler):
                scheduler.engine.enqueue([1], 0)  # bypasses the owner
        """, EngineOwnershipChecker())
        assert len(out) == 1
        assert out[0].key == "BatchGenerator.enqueue"

    def test_scheduler_is_allowed(self, tmp_path):
        out = lint(tmp_path, """
            class Scheduler:
                def _run(self):
                    self.engine.step()
        """, EngineOwnershipChecker(), rel="cake_tpu/serve/scheduler.py")
        assert out == []

    def test_unrelated_finish_ok(self, tmp_path):
        out = lint(tmp_path, """
            def flush(stream, sess):
                stream.finish()      # TokenOutputStream, not an engine
                sess.finish("stop")  # Session, not an engine
        """, EngineOwnershipChecker())
        assert out == []


# -- CK-LOCK: _GUARDED_BY discipline -------------------------------------

class TestGuardedBy:
    def test_unlocked_touch_flagged(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_items": "_lock"}
                def peek(self):
                    return list(self._items)
        """, GuardedByChecker())
        assert len(out) == 1
        assert out[0].checker == "CK-LOCK"
        assert "Box.peek" in out[0].message

    def test_locked_touch_and_escapes_ok(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_items": "_lock"}
                def __init__(self):
                    self._items = []          # construction happens-before
                def add(self, x):
                    with self._lock:
                        self._items.append(x)
                def _clear_locked(self):
                    self._items.clear()       # caller holds the lock
        """, GuardedByChecker())
        assert out == []

    def test_shadowing_local_is_not_the_global(self, tmp_path):
        # a function-local binding that shadows a guarded global is a
        # different variable entirely (no `global` declaration)
        out = lint(tmp_path, """
            import threading
            _LOCK = threading.Lock()
            _cache = None
            _GUARDED_BY = {"_cache": "_LOCK"}

            def local_only():
                _cache = []
                _cache.append(1)
                return _cache

            def param_shadow(_cache):
                return len(_cache)

            def real_touch():
                global _cache
                _cache = []   # BAD: writes the guarded global unlocked
        """, GuardedByChecker())
        assert len(out) == 1
        assert "real_touch" in out[0].message

    def test_module_global_map(self, tmp_path):
        out = lint(tmp_path, """
            import threading
            _LOCK = threading.Lock()
            _cache = None
            _GUARDED_BY = {"_cache": "_LOCK"}

            def good():
                with _LOCK:
                    return _cache

            def bad():
                return _cache
        """, GuardedByChecker())
        assert len(out) == 1
        assert "bad" in out[0].message

    def test_suppression_comment(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n  # cakelint: ignore[CK-LOCK]
        """, GuardedByChecker())
        assert out == []

    def test_suppression_multi_id_with_spaces(self, tmp_path):
        out = lint(tmp_path, """
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n  # cakelint: ignore[CK-WIRE, CK-LOCK]
        """, GuardedByChecker())
        assert out == []


# -- CK-JIT: trace purity -------------------------------------------------

class TestTracePurity:
    def test_time_in_jitted_fn_flagged(self, tmp_path):
        out = lint(tmp_path, """
            import time, jax
            def step(x):
                t = time.perf_counter()
                return x + t
            f = jax.jit(step)
        """, TracePurityChecker())
        assert len(out) == 1
        assert "time.perf_counter" in out[0].message

    def test_partial_and_decorator_resolved(self, tmp_path):
        out = lint(tmp_path, """
            import jax
            from functools import partial

            def inner(x, k):
                print("traced once")
                return x * k
            g = jax.jit(partial(inner, k=2))

            @partial(jax.jit, static_argnums=(0,))
            def decorated(n, x):
                REJECTED.inc()
                return x * n
        """, TracePurityChecker())
        assert {f.key for f in out} == {"inner:print",
                                        "decorated:REJECTED.inc"}

    def test_shard_map_body_checked(self, tmp_path):
        out = lint(tmp_path, """
            import random, jax
            from cake_tpu.parallel.mesh import shard_map
            def stage(x):
                return x * random.random()
            f = jax.jit(shard_map(stage, mesh=None))
        """, TracePurityChecker())
        assert len(out) == 1
        assert "random.random" in out[0].message

    def test_pure_and_host_side_ok(self, tmp_path):
        out = lint(tmp_path, """
            import time, jax
            def pure(x):
                return jax.random.fold_in(x, 1)  # keyed: fine
            f = jax.jit(pure)
            def host_loop(f, x):
                t0 = time.perf_counter()  # not traced: fine
                print(f(x))
        """, TracePurityChecker())
        assert out == []


# -- CK-WIRE: recv deadlines, resources, protocol arms --------------------

class TestWireSafety:
    def test_recv_without_timeout_flagged(self, tmp_path):
        out = lint(tmp_path, """
            def pump(conn):
                t, payload = conn.recv()
        """, WireSafetyChecker())
        assert len(out) == 1
        assert out[0].key == "recv:conn"

    def test_recv_explicit_ok(self, tmp_path):
        out = lint(tmp_path, """
            def pump(conn, sock):
                conn.recv(timeout=5.0)
                conn.recv(timeout=None)  # explicit block-forever decision
                sock.recv(4096)          # raw byte read: framing bounds it
        """, WireSafetyChecker())
        assert out == []

    def test_msgtype_missing_arm_flagged(self, tmp_path):
        repo = tmp_path
        (repo / "proto.py").write_text(textwrap.dedent("""
            from enum import IntEnum
            class MsgType(IntEnum):
                HELLO = 1
                ORPHAN = 2
        """))
        (repo / "peer.py").write_text(textwrap.dedent("""
            from proto import MsgType
            def talk(conn):
                conn.send(MsgType.HELLO)
                conn.send(MsgType.ORPHAN, b"x")
                t, _ = conn.recv(timeout=1)
                if t == MsgType.HELLO:
                    return True
        """))
        out = core.run_checkers([WireSafetyChecker()],
                                roots=[str(repo)], repo_root=repo)
        assert [f.key for f in out] == ["MsgType.ORPHAN:dispatch"]

    def test_msgtype_pass_skipped_on_file_scoped_scan(self):
        """'never sent anywhere' is meaningless when 'anywhere' is one
        file: linting protocol.py alone must not spray bogus MsgType
        findings (the per-module arms still run)."""
        out = core.run_checkers(
            [WireSafetyChecker()],
            roots=["cake_tpu/runtime/protocol.py"])
        assert [f for f in out if f.key.startswith("MsgType.")] == []

    def test_frame_const_missing_arm_flagged(self, tmp_path):
        # the declared XFER_* family is judged tree-wide like MsgType:
        # a constant with a send arm but no dispatch arm (or vice versa)
        # is protocol skew waiting to happen
        out = lint_full(tmp_path, {
            "cake_tpu/disagg/transfer.py": """
                XFER_SNAPSHOT = 32
                XFER_ACK = 33
                XFER_REJECT = 34
                def pump(conn):
                    conn.send(XFER_SNAPSHOT, b"x")
                    conn.send(XFER_ACK)
                    t, _ = conn.recv(timeout=1)
                    if t == XFER_ACK:
                        return True
                    if t == XFER_REJECT:
                        return False
            """,
        }, WireSafetyChecker())
        assert [f.key for f in out] == ["frame:XFER_SNAPSHOT:dispatch",
                                        "frame:XFER_REJECT:send"]

    def test_frame_const_both_arms_ok_cross_module(self, tmp_path):
        # arms may live in different modules (sender here, receiver
        # there) — and re-exported access (transfer.XFER_ACK) counts
        out = lint_full(tmp_path, {
            "cake_tpu/disagg/transfer.py": """
                XFER_SNAPSHOT = 32
                def send(conn):
                    conn.send(XFER_SNAPSHOT, b"x")
            """,
            "cake_tpu/disagg/receiver.py": """
                from cake_tpu.disagg import transfer
                def handle(t):
                    return t == transfer.XFER_SNAPSHOT
            """,
        }, WireSafetyChecker())
        assert out == []


# -- CK-CLAIM: declared acquire/release pairs ------------------------------

class TestClaims:
    # the fd rule (migrated from CK-WIRE arm 2): same shapes, same keys
    def test_leaky_acquisition_flagged(self, tmp_path):
        out = lint(tmp_path, """
            import socket
            def dial(host, port, Connection):
                sock = socket.create_connection((host, port))
                sock.setsockopt(1, 2, 3)   # may raise: sock leaks
                return Connection(sock=sock)
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].checker == "CK-CLAIM"
        assert out[0].key == "res:create_connection:dial:sock"

    def test_protected_and_immediate_ok(self, tmp_path):
        out = lint(tmp_path, """
            import socket
            def good_with(path):
                with open(path) as f:
                    return f.read()
            def good_immediate(host, Connection):
                sock = socket.create_connection((host, 1))
                return Connection(sock=sock)
            def good_protected(host, Connection):
                sock = socket.create_connection((host, 1))
                try:
                    sock.setsockopt(1, 2, 3)
                except Exception:
                    sock.close()
                    raise
                return Connection(sock=sock)
            class Owner:
                def open(self, path):
                    self._fh = open(path, "a")  # ownership moved
        """, ClaimChecker())
        assert out == []

    def test_read_is_not_a_release(self, tmp_path):
        # `data = sock.recv(n)` is a READ; the caller still owns the
        # socket, and the raising parse after it must keep the finding
        out = lint(tmp_path, """
            import socket
            def probe(host, parse):
                s = socket.create_connection((host, 1))
                data = s.recv(100)
                return parse(data)   # may raise: s leaks
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "res:create_connection:probe:s"

    def test_late_try_does_not_cover_early_risk(self, tmp_path):
        # a try/finally that closes the var but starts AFTER a raising
        # statement does not protect the held-bare region before it
        out = lint(tmp_path, """
            import socket
            def serve(host, risky_setup, use):
                s = socket.create_connection((host, 1))
                risky_setup()        # raises -> s leaks
                try:
                    use(s)
                finally:
                    s.close()
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "res:create_connection:serve:s"

    def test_adjacent_try_protects(self, tmp_path):
        # ...but the same try as the VERY NEXT statement does protect,
        # including when the acquisition sits inside its own try (the
        # chaos-proxy shape)
        out = lint(tmp_path, """
            import socket
            def dial(host, use):
                s = socket.create_connection((host, 1))
                try:
                    use(s)
                finally:
                    s.close()
            def dial_nested(host, setup, consume):
                try:
                    s = socket.create_connection((host, 1))
                except OSError:
                    return None
                try:
                    setup(s)
                except OSError:
                    s.close()
                    raise
                return consume(s)
        """, ClaimChecker())
        assert out == []

    def test_store_in_container_is_a_handoff(self, tmp_path):
        # storing a resource in a longer-lived owner transfers ownership
        # — both the bound and the unbound spelling
        out = lint(tmp_path, """
            import socket
            def pool_up(hosts, conns):
                for h in hosts:
                    c = socket.create_connection((h, 1))
                    conns.append(c)
            class Pool:
                def grow(self, path):
                    self.files.append(open(path))
        """, ClaimChecker())
        assert out == []

    def test_guarded_conditional_close_ok(self, tmp_path):
        # the worker accept-loop idiom: the guard test is part of the
        # release decision, not held-bare work
        out = lint(tmp_path, """
            def loop(listener, stop, handle):
                conn = listener.accept()
                if stop.is_set():
                    conn.close()
                    return
                handle(conn)
        """, ClaimChecker())
        assert out == []

    def test_second_acquisition_is_risky(self, tmp_path):
        # a second dial that raises strands the first socket — binding
        # acquires are never excluded from the held-bare risk set
        out = lint(tmp_path, """
            import socket
            def bridge(h1, h2):
                a = socket.create_connection((h1, 1))
                b = socket.create_connection((h2, 1))
                a.close()
                b.close()
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "res:create_connection:bridge:a"

    # kvpool page-claim rules
    def test_pin_handoff_after_dispatch_flagged(self, tmp_path):
        # THE import-land bug class: pins taken in a loop, collected
        # into a list, but the hand-off to the owning record sits after
        # a device dispatch — the day that dispatch raises, the pinned
        # pages leak forever (nothing ever unpins them)
        out = lint(tmp_path, """
            def land(self, rec, staging, need):
                pages = []
                for _ in range(need):
                    pid = self.pool.alloc()
                    self.pool.pin(pid)
                    self.pool.unref(pid)
                    pages.append(pid)
                self.cache = self.scatter(self.cache, staging)  # raises?
                rec["pages"] = pages
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "claim:kvpool.pin:pin:land:pages"

    def test_pin_handoff_before_dispatch_ok(self, tmp_path):
        # the fix shape: the record owns the pins BEFORE anything that
        # can raise — an abort/TTL sweep can always release them
        out = lint(tmp_path, """
            def land(self, rec, staging, need):
                pages = []
                for _ in range(need):
                    pid = self.pool.alloc()
                    self.pool.pin(pid)
                    self.pool.unref(pid)
                    pages.append(pid)
                rec["pages"] = pages
                self.cache = self.scatter(self.cache, staging)
        """, ClaimChecker())
        assert out == []

    def test_ref_loop_needs_protected_release(self, tmp_path):
        # refs over an existing table: work between the ref loop and
        # the unref loop leaks on its exception edge...
        out = lint(tmp_path, """
            def attach_bad(self, table, splice):
                for pid in table:
                    self.pool.ref(pid)
                splice()             # may raise: table's refs leak
                for pid in table:
                    self.pool.unref(pid)
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "claim:kvpool.ref:ref:attach_bad:table"

    def test_ref_loop_protected_or_handed_off_ok(self, tmp_path):
        # ...unless a try releases on the error path, or the table is
        # handed to its owner first
        out = lint(tmp_path, """
            def attach_protected(self, table, splice):
                for pid in table:
                    self.pool.ref(pid)
                try:
                    splice()
                except Exception:
                    for pid in table:
                        self.pool.unref(pid)
                    raise
            def attach_handoff(self, table, splice):
                for pid in table:
                    self.pool.ref(pid)
                self.tables.append(table)
                splice()
        """, ClaimChecker())
        assert out == []

    def test_alloc_leak_on_exception_edge(self, tmp_path):
        # binding style: a fresh page held only by a local while a
        # raising statement sits before the hand-off
        out = lint(tmp_path, """
            def grow(self, splice):
                pid = self.pool.alloc()
                splice()               # may raise: pid leaks
                self.table.append(pid)
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "res:alloc:grow:pid"

    def test_per_iteration_pin_tracked_by_name(self, tmp_path):
        # a loop pin on a plain name with no collecting list tracks the
        # NAME within the iteration: balanced-under-finally is clean,
        # bare work between pin and unpin is a leak on its exception
        # edge (not "untrackable")
        out = lint(tmp_path, """
            def scan_ok(self, streams, work):
                for s in streams:
                    pid = s.pid
                    self.pool.pin(pid)
                    try:
                        work(pid)
                    finally:
                        self.pool.unpin(pid)
        """, ClaimChecker())
        assert out == []
        out = lint(tmp_path, """
            def scan_bad(self, streams, work):
                for s in streams:
                    pid = s.pid
                    self.pool.pin(pid)
                    work(pid)          # may raise: this pin leaks
                    self.pool.unpin(pid)
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "claim:kvpool.pin:pin:scan_bad:pid"
        assert "leak" in out[0].message

    def test_untracked_tokens_get_distinct_keys(self, tmp_path):
        # two different untracked tokens in one function must not share
        # a baseline key — one grandfathered claim cannot cover the other
        out = lint(tmp_path, """
            def hold(self, i, j):
                self.pool.pin(self.slots[i])
                self.pool.pin(self.others[j])
        """, ClaimChecker())
        assert len(out) == 2
        assert len({f.key for f in out}) == 2
        assert all("untracked" in f.key for f in out)

    def test_implementing_module_excluded(self, tmp_path):
        # kvpool/table.py IS the pair's implementation: `pin` calling
        # `ref` internally must not read as an unbalanced claim
        out = lint(tmp_path, """
            class PagePool:
                def pin(self, pid):
                    self.ref(pid)
                    self._pins[pid] += 1
        """, ClaimChecker(), rel="cake_tpu/kvpool/table.py")
        assert out == []

    # disagg transfer-id rule
    def test_import_begin_dropped_flagged(self, tmp_path):
        out = lint(tmp_path, """
            def ingest(self, payload, audit):
                meta = self.engine.import_begin(payload)
                audit(meta["xfer_id"])
        """, ClaimChecker())
        assert len(out) == 1
        assert out[0].key == "res:import_begin:ingest:meta"

    def test_import_begin_returned_or_aborted_ok(self, tmp_path):
        out = lint(tmp_path, """
            def ingest(self, payload):
                meta = self.engine.import_begin(payload)
                return meta
            def probe(self, payload, validate):
                meta = self.engine.import_begin(payload)
                try:
                    validate(meta)
                except ValueError:
                    # releasing through a projection of the claim
                    # (meta["xfer_id"]) releases the claim
                    self.engine.import_abort(meta["xfer_id"])
                    raise
                return meta
        """, ClaimChecker())
        assert out == []


# -- CK-THREAD: declared thread domains ------------------------------------

_ENGINE_MOD = """
    class Engine:
        _THREAD_DOMAIN = "engine"
        _THREAD_ALIASES = ("engine",)
        _THREAD_SAFE = ("_encode",)
        def step(self): pass
        def stats(self): pass
        def _encode(self, p): pass

    class Owner:
        _THREAD_DOMAIN = "engine"
        _THREAD_ALIASES = ("owner",)
        _GUARDED_BY = {"_queue": "_cond"}
        _THREAD_SAFE = ("submit", "snapshot")
        _THREAD_OF = {"start": "engine"}
        def submit(self, sess):
            with self._cond:
                self._queue.append(sess)   # inbox hand-off: the crossing
        def snapshot(self):
            with self._cond:
                return dict(self._cached)
        def start(self):
            self.engine.step()             # engine by _THREAD_OF: fine
        def _run(self):
            self.engine.step()             # engine-domain body: fine
"""


class TestThreadDomains:
    def test_cross_domain_direct_call_flagged(self, tmp_path):
        out = lint_full(tmp_path, {
            "pkg/engine_mod.py": _ENGINE_MOD,
            "pkg/handlers.py": """
                _THREAD_DOMAIN = "handler"
                def handle(owner, prompt):
                    owner._run()                 # BAD: engine-domain method
                def handle_safe(owner, sess):
                    owner.submit(sess)           # declared crossing point
                def tokenize(engine, p):
                    return engine._encode(p)     # _THREAD_SAFE method
            """,
        }, ThreadDomainChecker())
        assert len(out) == 1
        assert out[0].checker == "CK-THREAD"
        assert out[0].key == "Owner._run:handle"
        assert "'engine'" in out[0].message and "handler" in out[0].message

    def test_crossing_point_body_checked_as_any(self, tmp_path):
        # a _THREAD_SAFE method that itself pokes domain state is
        # exactly the bug the declaration exists to catch — the
        # live-stats-walk shape this PR fixed in Scheduler.stats
        out = lint_full(tmp_path, {
            "pkg/engine_mod.py": _ENGINE_MOD,
            "pkg/bad_owner.py": """
                class Front:
                    _THREAD_DOMAIN = "engine"
                    _THREAD_SAFE = ("stats",)
                    def stats(self):
                        return self.engine.stats()   # BAD: any -> engine
            """,
        }, ThreadDomainChecker())
        assert len(out) == 1
        assert out[0].key == "Engine.stats:Front.stats"

    def test_guarded_by_lock_is_a_crossing(self, tmp_path):
        out = lint_full(tmp_path, {
            "pkg/engine_mod.py": _ENGINE_MOD,
            "pkg/locked.py": """
                _THREAD_DOMAIN = "handler"
                _GUARDED_BY = {"shared": "_table_lock"}
                def read(owner, _table_lock):
                    with _table_lock:
                        return owner._run()   # declared lock: allowed
            """,
        }, ThreadDomainChecker())
        assert out == []

    def test_dunder_and_unannotated_callers_exempt(self, tmp_path):
        out = lint_full(tmp_path, {
            "pkg/engine_mod.py": _ENGINE_MOD,
            "pkg/wrapper.py": """
                _THREAD_DOMAIN = "handler"
                class Wrapper:
                    def __init__(self, engine):
                        engine.step()   # construction happens-before
            """,
            "pkg/script.py": """
                def main(engine):
                    engine.step()       # unannotated caller: not checked
            """,
        }, ThreadDomainChecker())
        assert out == []

    def test_constructor_taint_resolves_receivers(self, tmp_path):
        # `eng = Engine()` binds the handle scope-insensitively — the
        # CK-ENGINE philosophy — so a later cross-domain call through
        # that name is caught without alias declarations
        out = lint_full(tmp_path, {
            "pkg/engine_mod.py": _ENGINE_MOD,
            "pkg/boot.py": """
                _THREAD_DOMAIN = "handler"
                from pkg.engine_mod import Engine
                eng = Engine()
                def tick():
                    eng.step()
            """,
        }, ThreadDomainChecker())
        assert len(out) == 1
        assert out[0].key == "Engine.step:tick"

    def test_any_domain_class_imposes_nothing(self, tmp_path):
        out = lint_full(tmp_path, {
            "pkg/shared.py": """
                class Box:
                    _THREAD_DOMAIN = "any"
                    def put(self, x): pass
            """,
            "pkg/handlers.py": """
                _THREAD_DOMAIN = "handler"
                from pkg.shared import Box
                box = Box()
                def handle(x):
                    box.put(x)
            """,
        }, ThreadDomainChecker())
        assert out == []


# -- the CK-THREAD runtime twin (CAKE_THREAD_STRICT) -----------------------

class TestThreadStrictTwin:
    def test_assert_fires_cross_thread_only(self):
        from cake_tpu.runtime import threadcheck

        stamp = threadcheck.DomainStamp("engine")
        prev = threadcheck.set_strict(True)
        try:
            stamp.check("unstamped-is-vacuous")  # no owner yet: passes
            stamp.stamp()
            stamp.check("same-thread-ok")
            err: list[str] = []

            def other():
                try:
                    stamp.check("BatchGenerator.step")
                except RuntimeError as e:
                    err.append(str(e))

            t = threading.Thread(target=other)
            t.start()
            t.join()
            assert len(err) == 1
            assert "BatchGenerator.step" in err[0]
            assert "engine" in err[0]
            stamp.clear()  # owner gone: checks are vacuous again
            t2 = threading.Thread(target=lambda: stamp.check("after-clear"))
            t2.start()
            t2.join()
        finally:
            threadcheck.set_strict(prev)

    def test_disabled_twin_never_raises(self):
        from cake_tpu.runtime import threadcheck

        stamp = threadcheck.DomainStamp("engine")
        prev = threadcheck.set_strict(False)
        try:
            stamp.stamp()
            t = threading.Thread(target=lambda: stamp.check("off"))
            t.start()
            t.join()  # no raise: disabled twin is a bool read
        finally:
            threadcheck.set_strict(prev)

    def test_pagepool_mutators_guarded(self):
        # the real wiring: a pool whose stamp is owned by another thread
        # refuses foreign-thread page claims, message naming the mutator
        from cake_tpu.kvpool.table import PagePool
        from cake_tpu.runtime import threadcheck

        pool = PagePool(8, 4)
        prev = threadcheck.set_strict(True)
        try:
            t = threading.Thread(target=pool._domain_stamp.stamp)
            t.start()
            t.join()
            with pytest.raises(RuntimeError, match="PagePool.alloc"):
                pool.alloc()
            pool._domain_stamp.clear()
            pid = pool.alloc()  # ownerless: single-threaded drive works
            assert pool.refcount(pid) == 1
        finally:
            threadcheck.set_strict(prev)


# -- framework: baseline, suppression, CLI --------------------------------

class TestBaseline:
    def _finding(self, key="BatchGenerator.step", path="examples/x.py",
                 line=10):
        return core.Finding(checker="CK-ENGINE", path=path, line=line,
                            col=0, message="m", key=key)

    def test_suppresses_by_key_not_line(self):
        entry = baseline_mod.BaselineEntry(
            checker="CK-ENGINE", path="examples/x.py",
            key="BatchGenerator.step", justification="demo")
        new, suppressed, stale = baseline_mod.apply(
            [self._finding(line=10), self._finding(line=99)], [entry])
        assert new == [] and len(suppressed) == 2 and stale == []

    def test_stale_entry_reported(self):
        entry = baseline_mod.BaselineEntry(
            checker="CK-ENGINE", path="examples/x.py", key="gone",
            justification="was fixed")
        new, suppressed, stale = baseline_mod.apply(
            [self._finding()], [entry])
        assert len(new) == 1 and stale == [entry]

    def test_stale_respects_run_scope(self):
        # a subset run must not call live out-of-scope entries "fixed"
        entry = baseline_mod.BaselineEntry(
            checker="CK-ENGINE", path="examples/x.py",
            key="BatchGenerator.step", justification="demo")
        _, _, stale = baseline_mod.apply(
            [], [entry], checker_ids={"CK-METRIC"}, paths={"examples/x.py"})
        assert stale == []
        _, _, stale = baseline_mod.apply(
            [], [entry], checker_ids={"CK-ENGINE"}, paths={"other.py"})
        assert stale == []
        _, _, stale = baseline_mod.apply(
            [], [entry], checker_ids={"CK-ENGINE"},
            paths={"examples/x.py"})
        assert stale == [entry]

    def test_justification_required(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"version": 1, "entries": [
            {"checker": "CK-X", "path": "a.py", "key": "k"}]}))
        with pytest.raises(ValueError, match="justification"):
            baseline_mod.load(p)

    def test_roundtrip(self, tmp_path):
        p = tmp_path / "b.json"
        entries = baseline_mod.from_findings([self._finding()], "why")
        baseline_mod.save(p, entries)
        assert baseline_mod.load(p) == entries


class TestUnusedSuppressions:
    def _scan(self, tmp_path, source, checkers):
        f = tmp_path / "mod.py"
        f.write_text(textwrap.dedent(source))
        mods, pf = core.load_modules([str(f)], repo_root=tmp_path)
        unused: list = []
        findings = core.check_modules(mods, checkers, True, pf,
                                      unused_out=unused)
        return findings, unused

    def test_unused_vs_used_ignores(self, tmp_path):
        findings, unused = self._scan(tmp_path, """
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n  # cakelint: ignore[CK-LOCK]
                def clean(self):
                    return 1  # cakelint: ignore[CK-LOCK]
        """, [GuardedByChecker()])
        assert findings == []
        # the peek ignore suppressed a live finding; the clean one
        # suppressed nothing and is reported like a stale baseline entry
        assert [(u["line"], u["ids"]) for u in unused] == [
            (7, ["CK-LOCK"])]

    def test_bare_ignore_counts_and_prose_does_not(self, tmp_path):
        findings, unused = self._scan(tmp_path, '''
            """Docs may say cakelint: ignore[CK-LOCK] without meaning it."""
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n  # cakelint: ignore
        ''', [GuardedByChecker()])
        # the docstring mention is neither a suppression nor "unused";
        # the bare comment suppresses every checker and counts as used
        assert findings == [] and unused == []

    def test_string_literal_hash_is_not_a_comment(self, tmp_path):
        # a '#' inside a string literal must neither suppress a finding
        # on that line nor read as an (unused) suppression comment —
        # comment detection is token-based, not substring-based
        findings, unused = self._scan(tmp_path, '''
            HINT = "append # cakelint: ignore[CK-LOCK] to the line"
            class Box:
                _GUARDED_BY = {"_n": "_lock"}
                def peek(self):
                    return self._n, "# cakelint: ignore[CK-LOCK]"
        ''', [GuardedByChecker()])
        assert len(findings) == 1  # the peek touch is NOT suppressed
        assert unused == []        # ...and neither string is "unused"

    def test_subset_runs_cannot_judge(self, tmp_path):
        # mirror of stale-baseline scoping: a run without the
        # suppressing checker cannot tell "unused" from "not re-checked"
        # — the CLI only passes unused_out on full all-checker scans
        f = tmp_path / "mod.py"
        f.write_text("X = 1  # cakelint: ignore[CK-LOCK]\n")
        mods, pf = core.load_modules([str(f)], repo_root=tmp_path)
        out = core.check_modules(mods, [MetricsCatalogChecker()], True, pf)
        assert out == []  # no unused_out passed -> nothing judged


class TestCli:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        from cake_tpu.analysis.__main__ import main

        bad = tmp_path / "bad.py"
        bad.write_text("from cake_tpu.obs import metrics as m\n"
                       "c = m.counter('serve.typo_ms')\n")
        assert main([str(bad), "--json"]) == 1
        report = json.loads(capsys.readouterr().out)
        assert report["summary"]["new"] == 1
        assert report["new"][0]["checker"] == "CK-METRIC"

        base = tmp_path / "base.json"
        assert main([str(bad), "--write-baseline", str(base)]) == 0
        # stub justifications must be replaced before load() accepts
        # them — accept the stub here to prove the grandfather path
        data = json.loads(base.read_text())
        for e in data["entries"]:
            e["justification"] = "fixture"
        base.write_text(json.dumps(data))
        assert main([str(bad), "--baseline", str(base)]) == 0

        good = tmp_path / "good.py"
        good.write_text("x = 1\n")
        assert main([str(good)]) == 0

    def test_list_and_unknown_checker(self, capsys):
        from cake_tpu.analysis.__main__ import main

        assert main(["--list"]) == 0
        listed = capsys.readouterr().out
        for cls in analysis.ALL_CHECKERS:
            assert cls.id in listed
        assert main(["--checkers", "CK-NOPE"]) == 2


# -- catalog + strict registry -------------------------------------------

class TestCatalog:
    def test_declarations_well_formed(self):
        from cake_tpu.obs import catalog

        kinds = {catalog.COUNTER, catalog.GAUGE, catalog.HISTOGRAM}
        for name, (kind, help_) in {**catalog.SERIES,
                                    **catalog.DYNAMIC}.items():
            assert kind in kinds, name
            assert help_, name
        assert catalog.is_declared("wire.bytes_out")
        assert catalog.is_declared("master.segment3.decode_ms")
        assert catalog.is_declared("cluster.w0.rtt_ms")
        assert not catalog.is_declared("wire.byte_out")
        assert catalog.kind_of("serve.ttft_ms") == catalog.HISTOGRAM
        assert catalog.kind_of("nope") is None

    def test_strict_registry_enforces_catalog(self):
        from cake_tpu.obs import metrics

        reg = metrics.Registry(enabled=True, strict=True)
        reg.counter("wire.bytes_out")  # declared: fine
        with pytest.raises(ValueError, match="not declared"):
            reg.counter("wire.byte_out")
        with pytest.raises(ValueError, match="not declared"):
            reg.register("serve.nope", metrics.Counter("serve.nope"))

    def test_every_catalog_entry_is_used(self):
        """The reverse check: a declared series nobody emits is a stale
        doc. Scan the tree for series-name literals/patterns and compare
        (the static half only — DYNAMIC families count via patterns)."""
        import ast as ast_mod

        from cake_tpu.obs import catalog

        used: set[str] = set()
        mods, _ = core.load_modules()
        for mod in mods:
            for node in ast_mod.walk(mod.tree):
                if not isinstance(node, ast_mod.Call):
                    continue
                name = core.call_name(node)
                if name.lower() not in ("counter", "gauge", "histogram"):
                    continue
                if not node.args:
                    continue
                lit = core.literal_str(node.args[0])
                pat = core.fstring_pattern(node.args[0])
                if lit:
                    used.add(lit)
                if pat:
                    used.add(pat)
        unused = [n for n in catalog.SERIES if n not in used]
        unused += [p for p in catalog.DYNAMIC if p not in used]
        assert unused == [], f"catalog entries nothing emits: {unused}"


# -- the gate's gate: repo self-run ---------------------------------------

class TestSelfRun:
    def test_repo_clean_at_head(self):
        """The tree + committed baseline = zero new findings, zero stale
        entries, zero unused suppressions. This is exactly what
        `make lint` enforces in CI — CK-CLAIM and CK-THREAD included."""
        mods, parse_findings = core.load_modules()
        unused: list = []
        findings = core.check_modules(mods, analysis.default_checkers(),
                                      True, parse_findings,
                                      unused_out=unused)
        entries = baseline_mod.load(core.REPO_ROOT /
                                    "analysis-baseline.json")
        new, suppressed, stale = baseline_mod.apply(findings, entries)
        assert new == [], "\n".join(f.render() for f in new)
        assert stale == [], [e.match_key for e in stale]
        assert unused == []
        # the baseline is not a dumping ground: only the deliberate
        # direct-drive sites and the protocol-compat member live there
        assert {e.checker for e in entries} <= {"CK-ENGINE", "CK-WIRE"}

    def test_every_checker_registered(self):
        ids = {c.id for c in analysis.default_checkers()}
        assert ids == {"CK-METRIC", "CK-ENGINE", "CK-LOCK", "CK-JIT",
                       "CK-WIRE", "CK-CLAIM", "CK-THREAD"}
