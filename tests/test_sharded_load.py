"""Direct-to-mesh weight loading (utils/sharded_load).

The reference worker loads only its topology-assigned blocks
(worker.rs:85-98); the mesh path's equivalent is per-shard mmap reads
assembled with jax.make_array_from_callback. Held to bitwise parity with
the full-host-load + shard_params path, and to a bounded host scratch
(never more than one layer weight materialized per read)."""

import jax
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.quant import QuantizedLinear
from cake_tpu.parallel.mesh import MeshPlan, shard_params
from cake_tpu.utils import sharded_load
from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
from cake_tpu.utils.weights import load_llama_params, save_llama_params

CFG = tiny(max_seq_len=32)


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt")
    params = llama.init_params(CFG, jax.random.PRNGKey(11))
    save_llama_params(params, d, CFG.num_hidden_layers)
    return d


def _leaves_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        assert x.shape == y.shape and x.dtype == y.dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("quantize", [None, "int8"])
def test_mesh_load_matches_host_load_then_shard(ckpt_dir, quantize):
    """Bitwise parity with load_llama_params + shard_params, bf16 and int8,
    on a stage=2 x tp=2 mesh — including the row-parallel (wo/w_down)
    quantization scales, which need the full in-axis."""
    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    got = load_llama_params_on_mesh(ckpt_dir, CFG, plan.mesh,
                                    quantize=quantize)
    want = shard_params(
        load_llama_params(ckpt_dir, CFG.num_hidden_layers, dtype=CFG.dtype,
                          quantize=quantize),
        plan.mesh,
    )
    _leaves_equal(got, want)
    for leaf_got, leaf_want in zip(jax.tree.leaves(got),
                                   jax.tree.leaves(want)):
        assert leaf_got.sharding == leaf_want.sharding


def test_mesh_load_runs_the_model(ckpt_dir):
    """The assembled params drive a real sharded decode step."""
    from cake_tpu.runtime.mesh_generator import MeshGenerator
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    params = load_llama_params_on_mesh(ckpt_dir, CFG, plan.mesh)
    settings = SamplerSettings(temperature=0.0)
    gen = MeshGenerator(CFG, params, plan=plan, settings=settings,
                        max_seq=32)
    gen.set_prompt([3, 1, 4])
    got = [gen.next_token(i).id for i in range(5)]

    host = load_llama_params(ckpt_dir, CFG.num_hidden_layers,
                             dtype=CFG.dtype)
    ref = LlamaGenerator(CFG, host, settings=settings, max_seq=32)
    ref.set_prompt([3, 1, 4])
    assert got == [ref.next_token(i).id for i in range(5)]


def test_host_scratch_bounded_to_one_layer_weight(ckpt_dir, monkeypatch):
    """No full-model (or even full-stage) host copy: every single read the
    loader issues is at most one layer's largest weight (the row-parallel
    quantize case), so peak host scratch is ~1/(stages*layers_per_stage) of
    the model — far below the old full-pytree load."""
    reads = []
    orig = sharded_load.CheckpointReader.read2d

    def spy(self, name, rows, cols, transpose):
        out = orig(self, name, rows, cols, transpose)
        if "layers" in name:
            reads.append(out.nbytes)
        return out

    monkeypatch.setattr(sharded_load.CheckpointReader, "read2d", spy)
    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    load_llama_params_on_mesh(ckpt_dir, CFG, plan.mesh, quantize="int8")
    one_layer_max = max(
        CFG.hidden_size * CFG.intermediate_size,  # w_gate/w_up/w_down
        CFG.hidden_size * CFG.hidden_size,
    ) * 4  # checkpoint stores f32
    assert reads and max(reads) <= one_layer_max


def test_int8_load_reads_each_weight_at_most_twice(ckpt_dir):
    """The scale memo bounds quantize-on-load reads: every linear's bytes
    are read at most ~2x (one full read for row-parallel scales + the
    shards' own slices), independent of tp width — not (tp+1)x."""
    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    reader_holder = {}
    orig_init = sharded_load.CheckpointReader.__init__

    def spy_init(self, model_dir):
        orig_init(self, model_dir)
        reader_holder["r"] = self

    import unittest.mock as mock

    with mock.patch.object(sharded_load.CheckpointReader, "__init__",
                           spy_init):
        load_llama_params_on_mesh(ckpt_dir, CFG, plan.mesh, quantize="int8")
    c = CFG
    d = c.head_dim
    linear_els = c.num_hidden_layers * (
        c.hidden_size * (c.num_attention_heads + 2 * c.num_key_value_heads) * d
        + c.num_attention_heads * d * c.hidden_size
        + 3 * c.hidden_size * c.intermediate_size
    )
    norm_els = c.num_hidden_layers * 2 * c.hidden_size
    other_els = (c.vocab_size * c.hidden_size   # embed
                 + c.hidden_size                # norm_f
                 + c.hidden_size * c.vocab_size)  # lm_head
    upper = (2 * linear_els + norm_els + other_els) * 4  # f32 checkpoint
    assert reader_holder["r"].bytes_read <= upper


def test_reader_accounts_bytes(ckpt_dir):
    r = sharded_load.CheckpointReader(ckpt_dir)
    w = r.read2d("model.layers.0.self_attn.q_proj.weight",
                 slice(None), slice(None), True)
    assert r.bytes_read == w.nbytes
    r.close()
