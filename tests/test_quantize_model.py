"""Pre-quantized int8 checkpoints (tools/quantize_model).

Quantize once offline, start fast forever: the stored .q8/.scale tensors
must load (host and direct-to-mesh paths) bitwise-identically to
quantize-on-load from the original checkpoint, at a fraction of the read
bytes and zero quantize compute."""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.parallel.mesh import MeshPlan, shard_params
from cake_tpu.tools.quantize_model import quantize_checkpoint
from cake_tpu.utils import sharded_load
from cake_tpu.utils.sharded_load import load_llama_params_on_mesh
from cake_tpu.utils.weights import load_llama_params, save_llama_params

CFG = tiny(max_seq_len=32)
REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def dirs(tmp_path_factory):
    src = tmp_path_factory.mktemp("src")
    params = llama.init_params(CFG, jax.random.PRNGKey(13))
    save_llama_params(params, src, CFG.num_hidden_layers)
    (src / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    out = tmp_path_factory.mktemp("q8")
    quantize_checkpoint(src, out)
    return src, out


def _leaves_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_prequantized_load_bitwise_matches_quantize_on_load(dirs):
    src, out = dirs
    want = load_llama_params(src, CFG.num_hidden_layers, dtype=CFG.dtype,
                             quantize="int8")
    got = load_llama_params(out, CFG.num_hidden_layers, dtype=CFG.dtype,
                            quantize="int8")
    _leaves_equal(got, want)


def test_prequantized_sharded_load_matches(dirs):
    src, out = dirs
    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    want = shard_params(
        load_llama_params(src, CFG.num_hidden_layers, dtype=CFG.dtype,
                          quantize="int8"),
        plan.mesh,
    )
    got = load_llama_params_on_mesh(out, CFG, plan.mesh, quantize="int8")
    _leaves_equal(got, want)


def test_prequantized_sharded_load_reads_fewer_bytes(dirs):
    """The point of the format: the int8 bytes load directly (the f32
    source weights are never read, no quantize compute)."""
    src, out = dirs
    plan = MeshPlan.build(CFG, num_stages=2, tp=2)
    reads = {}
    orig = sharded_load.CheckpointReader.__init__

    def spy(self, model_dir):
        orig(self, model_dir)
        reads[Path(model_dir)] = self

    import unittest.mock as mock

    with mock.patch.object(sharded_load.CheckpointReader, "__init__", spy):
        load_llama_params_on_mesh(src, CFG, plan.mesh, quantize="int8")
        load_llama_params_on_mesh(out, CFG, plan.mesh, quantize="int8")
    # f32 source: >= 2x reads of the full linears (row-parallel scale pass);
    # prequantized: one int8 read (1/4 the f32 bytes) + tiny scales
    assert reads[Path(out)].bytes_read < 0.5 * reads[Path(src)].bytes_read


def test_quantize_writes_bounded_shards(tmp_path):
    """Output is written incrementally in ~shard_bytes shards (host RAM
    bounded by one shard, not the checkpoint), and the loaders read the
    multi-shard result identically."""
    src = tmp_path / "src"
    params = llama.init_params(CFG, jax.random.PRNGKey(13))
    save_llama_params(params, src, CFG.num_hidden_layers)
    (src / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    out = tmp_path / "q8"
    quantize_checkpoint(src, out, shard_bytes=64 * 1024)
    index = json.loads((out / "model.safetensors.index.json").read_text())
    shards = set(index["weight_map"].values())
    assert len(shards) > 1
    want = load_llama_params(src, CFG.num_hidden_layers, dtype=CFG.dtype,
                             quantize="int8")
    got = load_llama_params(out, CFG.num_hidden_layers, dtype=CFG.dtype,
                            quantize="int8")
    _leaves_equal(got, want)


def test_linear_suffixes_derived_from_layer_map():
    """The tool's linear list is DERIVED from weights._LAYER_MAP +
    quant.LAYER_LINEARS (+ the MoE expert map) — the sites cannot drift."""
    from cake_tpu.ops.quant import LAYER_LINEARS
    from cake_tpu.tools.quantize_model import _LINEAR_SUFFIXES
    from cake_tpu.utils.weights import _LAYER_MAP, _MOE_EXPERT_MAP

    assert set(_LINEAR_SUFFIXES) == {
        _LAYER_MAP[k][0] for k in LAYER_LINEARS
    } | {p.split("{e}.")[-1] for p in _MOE_EXPERT_MAP.values()}


def test_prequantized_requires_int8_flag(dirs):
    _, out = dirs
    with pytest.raises(ValueError, match="pre-quantized"):
        load_llama_params(out, CFG.num_hidden_layers, dtype=CFG.dtype)
    plan = MeshPlan.build(CFG, num_stages=2)
    with pytest.raises(ValueError, match="pre-quantized"):
        load_llama_params_on_mesh(out, CFG, plan.mesh)


def test_prequantized_layer_range_slice_matches(dirs):
    """The worker path (layer_range, no embed/head) reads pre-quantized
    slices identically to quantize-on-load from the source — a worker can
    serve straight from a quantize_model bundle."""
    src, out = dirs
    kw = dict(dtype=CFG.dtype, layer_range=(1, 3), include_embed=False,
              include_head=False, quantize="int8")
    want = load_llama_params(src, CFG.num_hidden_layers, **kw)
    got = load_llama_params(out, CFG.num_hidden_layers, **kw)
    _leaves_equal(got, want)


def test_quantize_rejects_already_quantized_input(dirs, tmp_path):
    _, out = dirs
    with pytest.raises(ValueError, match="already pre-quantized"):
        quantize_checkpoint(out, tmp_path / "double")


def test_cli_generation_from_prequantized_checkpoint(dirs):
    """End-to-end: the CLI serves a pre-quantized dir with --quantize int8
    and produces the same stream as quantize-on-load from the source."""
    src, out = dirs
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"

    def run(model_dir):
        return subprocess.run(
            [sys.executable, "-m", "cake_tpu.cli", "--model", str(model_dir),
             "--quantize", "int8", "--prompt-ids", "3,5,7", "-n", "5",
             "--temperature", "0", "--max-seq", "32", "--cpu"],
            capture_output=True, text=True, timeout=240, env=env, cwd=REPO,
        )

    a, b = run(src), run(out)
    assert a.returncode == 0, a.stderr
    assert b.returncode == 0, b.stderr

    def toks(r):
        return [l for l in r.stdout.splitlines()
                if l and all(c.isdigit() or c == "," for c in l)][-1]

    assert toks(a) == toks(b)
