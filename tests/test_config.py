import json

from cake_tpu.models.config import LlamaConfig, llama3_8b, llama3_70b, tiny


def test_defaults_are_llama3_8b():
    c = llama3_8b()
    assert c.num_hidden_layers == 32
    assert c.num_attention_heads == 32
    assert c.num_key_value_heads == 8
    assert c.head_dim == 128
    assert c.num_kv_groups == 4
    assert c.vocab_size == 128256


def test_llama3_70b():
    c = llama3_70b()
    assert c.num_hidden_layers == 80
    assert c.hidden_size == 8192
    assert c.head_dim == 128


def test_from_hf_dict_roundtrip(tmp_path):
    d = {
        "vocab_size": 1000,
        "hidden_size": 64,
        "intermediate_size": 256,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000.0,
        "bos_token_id": 1,
        "eos_token_id": 2,
        "torch_dtype": "float16",
        "model_type": "llama",
        "unknown_hf_key": 123,
    }
    p = tmp_path / "config.json"
    p.write_text(json.dumps(d))
    c = LlamaConfig.from_hf_json(p)
    assert c.vocab_size == 1000
    assert c.num_key_value_heads == 2
    assert c.rope_theta == 10000.0
    assert c.dtype == "bfloat16"  # f16 maps to bf16 on TPU


def test_eos_ids_normalization():
    assert LlamaConfig(eos_token_id=None).eos_ids() == ()
    assert LlamaConfig(eos_token_id=5).eos_ids() == (5,)
    assert LlamaConfig(eos_token_id=[5, 6]).eos_ids() == (5, 6)


def test_tiny_is_valid():
    c = tiny()
    assert c.hidden_size % c.num_attention_heads == 0
    assert c.num_attention_heads % c.num_key_value_heads == 0
