import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.kvcache import init_cache
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator, Token


@pytest.fixture(scope="module")
def gen_setup():
    cfg = tiny(max_seq_len=64)
    params = llama.init_params(cfg, jax.random.PRNGKey(42))
    return cfg, params


def _generate(cfg, params, prompt, n, settings):
    g = LlamaGenerator(cfg, params, settings=settings)
    g.set_prompt(prompt)
    out = []
    for i in range(n):
        tok = g.next_token(i)
        out.append(tok.id)
        if tok.is_end_of_stream:
            break
    return out


def test_greedy_matches_manual_argmax(gen_setup):
    cfg, params = gen_setup
    prompt = [3, 7, 11]
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    got = _generate(cfg, params, prompt, 5, settings)

    # manual: full forward + argmax each step
    ids = list(prompt)
    cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
    logits, cache = llama.forward(params, jnp.asarray([ids], jnp.int32), cache, 0, cfg)
    expect = []
    for i in range(5):
        t = int(jnp.argmax(logits[0]))
        expect.append(t)
        logits, cache = llama.forward(
            params, jnp.asarray([[t]], jnp.int32), cache, len(ids) + i, cfg
        )
    assert got == expect


def test_generation_is_seed_deterministic(gen_setup):
    cfg, params = gen_setup
    s = SamplerSettings(temperature=0.9, top_k=20, seed=123)
    a = _generate(cfg, params, [1, 2, 3], 8, s)
    b = _generate(cfg, params, [1, 2, 3], 8, s)
    assert a == b


def test_different_seed_changes_sampled_stream(gen_setup):
    cfg, params = gen_setup
    a = _generate(cfg, params, [1, 2, 3], 12, SamplerSettings(temperature=1.5, seed=1))
    b = _generate(cfg, params, [1, 2, 3], 12, SamplerSettings(temperature=1.5, seed=2))
    assert a != b  # overwhelmingly likely at temp 1.5


def test_prompt_bucket_padding_invariance(gen_setup):
    """Prompts of lengths that fall in different pad buckets must produce the
    same greedy continuation as an unpadded forward — padding is invisible."""
    cfg, params = gen_setup
    s = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    for plen in (3, 16, 17):  # below, at, and above a bucket boundary
        prompt = list(range(2, 2 + plen))
        got = _generate(cfg, params, prompt, 3, s)
        ids = list(prompt)
        cache = init_cache(cfg, batch=1, max_seq=cfg.max_seq_len)
        logits, cache = llama.forward(
            params, jnp.asarray([ids], jnp.int32), cache, 0, cfg
        )
        expect = []
        for i in range(3):
            t = int(jnp.argmax(logits[0]))
            expect.append(t)
            logits, cache = llama.forward(
                params, jnp.asarray([[t]], jnp.int32), cache, len(ids) + i, cfg
            )
        assert got == expect, f"prompt len {plen}"


def test_eos_stops_stream(gen_setup):
    cfg, params = gen_setup
    g = LlamaGenerator(cfg, params, settings=SamplerSettings(temperature=0.0))
    g.set_prompt([1, 2])
    for i in range(40):
        tok = g.next_token(i)
        if tok.is_end_of_stream:
            assert tok.id in cfg.eos_ids()
            break
    assert g.generated_tokens() == len(g.generated_ids)


def test_repeat_penalty_reduces_repetition(gen_setup):
    cfg, params = gen_setup
    no_pen = _generate(cfg, params, [4, 4, 4], 16,
                       SamplerSettings(temperature=0.0, repeat_penalty=1.0))
    pen = _generate(cfg, params, [4, 4, 4], 16,
                    SamplerSettings(temperature=0.0, repeat_penalty=1.5,
                                    repeat_last_n=8))
    assert no_pen != pen  # penalty must alter the greedy path


def test_generator_reuse_matches_fresh(gen_setup):
    """set_prompt must fully reset per-stream state: a reused generator's
    output equals a fresh generator's for the same prompt."""
    cfg, params = gen_setup
    s = SamplerSettings(temperature=0.7, top_k=16, seed=9)
    g = LlamaGenerator(cfg, params, settings=s)
    g.set_prompt([9, 8, 7])
    _ = [g.next_token(i) for i in range(6)]
    g.set_prompt([1, 2, 3])
    reused = [g.next_token(i).id for i in range(6)]
    fresh = _generate(cfg, params, [1, 2, 3], 6, s)
    assert reused == fresh
    assert g.generated_tokens() == 6  # counter reset on new prompt


def test_cache_exhaustion_raises(gen_setup):
    cfg, params = gen_setup  # max_seq 64
    g = LlamaGenerator(cfg, params,
                       settings=SamplerSettings(temperature=0.0,
                                                repeat_penalty=1.0))
    g.set_prompt(list(range(2, 60)))
    with pytest.raises(RuntimeError, match="KV cache exhausted"):
        for i in range(20):
            g.next_token(i)


class _FakeTok:
    """Deterministic toy tokenizer: id -> chr(id)."""

    def decode(self, ids):
        return "".join(chr(ord("a") + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - ord("a") for c in text]


def test_token_stream_integration(gen_setup):
    cfg, params = gen_setup
    g = LlamaGenerator(
        cfg, params, tokenizer=_FakeTok(),
        settings=SamplerSettings(temperature=0.0, repeat_penalty=1.0),
    )
    g.set_prompt([1, 2, 3])
    texts = []
    for i in range(5):
        t = g.next_token(i)
        if t.text:
            texts.append(t.text)
        if t.is_end_of_stream:
            break
    rest = g.last()
    if rest:
        texts.append(rest)
    assert "".join(texts)  # produced some text


def test_block_decode_greedy_parity(gen_setup):
    """block_size>1 (fused lax.scan decode) streams the same greedy tokens
    as the one-program-per-token path."""
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    single = _generate(cfg, params, [5, 9, 2], 9, settings)
    g = LlamaGenerator(cfg, params, settings=settings, block_size=4)
    g.set_prompt([5, 9, 2])
    blocked = [g.next_token(i).id for i in range(9)]
    assert blocked == single


def test_block_decode_tail_of_kv_window(gen_setup):
    """Near max_seq the block path falls back to single steps instead of
    overrunning the KV window."""
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    g = LlamaGenerator(cfg, params, settings=settings, max_seq=16,
                       block_size=8)
    g.set_prompt(list(range(1, 12)))  # prefill -> pos 11; an 8-block won't fit
    out = [g.next_token(i).id for i in range(6)]
    assert len(out) == 6 and g._pos == 16
    with pytest.raises(RuntimeError, match="exhausted"):
        g.next_token(6)


def test_block_decode_new_prompt_drops_buffer(gen_setup):
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    g = LlamaGenerator(cfg, params, settings=settings, block_size=4)
    g.set_prompt([5, 9, 2])
    first = [g.next_token(i).id for i in range(6)]
    g.set_prompt([5, 9, 2])  # mid-block reset: buffer must not leak
    assert [g.next_token(i).id for i in range(6)] == first


def test_block_decode_sampled_key_schedule_invariant(gen_setup):
    """Stochastic streams are identical at any block size: per-step keys fold
    the absolute token index, not a per-block counter."""
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=7)
    a = _generate(cfg, params, [5, 9, 2, 11], 9, settings)
    g = LlamaGenerator(cfg, params, settings=settings, block_size=4)
    g.set_prompt([5, 9, 2, 11])
    b = [g.next_token(i).id for i in range(9)]
    assert a == b


@pytest.mark.parametrize("block", [1, 4, 8])
def test_lookahead_stream_bit_identical(gen_setup, block):
    """Lookahead dispatch (block N+1 enqueued from the device feedback
    token before block N's host fetch) must be invisible in the output:
    identical sampled streams at every block size."""
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.9, top_k=20, seed=11)
    g = LlamaGenerator(cfg, params, settings=settings, block_size=block)
    g.set_prompt([3, 1, 4])
    plain = [g.next_token(i).id for i in range(20)]
    g2 = LlamaGenerator(cfg, params, settings=settings, block_size=block,
                        lookahead=True)
    g2.set_prompt([3, 1, 4])
    ahead = [g2.next_token(i).id for i in range(20)]
    assert ahead == plain


def test_lookahead_window_edge_delivers_inflight(gen_setup):
    """A lookahead block dispatched up to the window edge has already
    advanced pos to max_seq; its tokens must still be delivered before
    capacity exhaustion raises — and the full stream matches plain."""
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    prompt = list(range(1, 9))  # pos 8 after prefill; 3 full 8-blocks fit
    # 25 tokens: 1 from prefill + 3 fused blocks of 8 fill the window
    g = LlamaGenerator(cfg, params, settings=settings, max_seq=32,
                       block_size=8)
    g.set_prompt(prompt)
    plain = [g.next_token(i).id for i in range(25)]
    g2 = LlamaGenerator(cfg, params, settings=settings, max_seq=32,
                        block_size=8, lookahead=True)
    g2.set_prompt(prompt)
    ahead = [g2.next_token(i).id for i in range(25)]
    assert ahead == plain and g2._pos == 32
    with pytest.raises(RuntimeError, match="exhausted"):
        g2.next_token(25)


def test_lookahead_new_prompt_drops_inflight(gen_setup):
    """set_prompt mid-stream must discard the in-flight device block (it
    belongs to the previous stream)."""
    cfg, params = gen_setup
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    g = LlamaGenerator(cfg, params, settings=settings, block_size=4,
                       lookahead=True)
    g.set_prompt([5, 9, 2])
    first = [g.next_token(i).id for i in range(6)]
    assert g._inflight is not None  # a block is pipelined mid-stream
    g.set_prompt([5, 9, 2])
    assert g._inflight is None
    assert [g.next_token(i).id for i in range(6)] == first
