"""Machinery proof for the 70B stage-slice pricing tool (r5 verdict item
7). The real measurement runs on the tunnel chip (tools_bench_queue5.sh
tier 4); this pins the tool's arithmetic and output contract at tiny dims
on CPU, like tests/test_ici_probe.py does for the ICI probe."""

import json

from cake_tpu.tools import stage_slice


def test_stage_slice_mini_rows(capsys):
    rc = stage_slice.main(["--mini", "--steps", "2", "--layers", "3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    rows = out["rows"]
    assert [r["quant"] for r in rows] == ["int8", "bf16"]
    for r in rows:
        assert r["layers_per_stage"] == 3
        assert r["stage_step_ms_measured"] > 0
        assert r["stage_prefill2048_ms_measured"] > 0
        assert r["single_stream_tok_s_projected"] > 0
        # the serialized projection is n_stages x slower than one stage
        t_tok = r["n_stages"] * (
            r["stage_step_ms_measured"] / 1e3 + r["hop_s_projected"])
        assert abs(r["single_stream_tok_s_projected"] - 1 / t_tok) < 0.5
        assert r["interleaved_aggregate_tok_s_upper"] > (
            r["single_stream_tok_s_projected"])
    assert "PROJECTIONS" in out["note"]


def test_slice_config_is_70b_geometry():
    cfg = stage_slice.slice_config(5, 8192, mini=False)
    assert (cfg.hidden_size, cfg.intermediate_size) == (8192, 28672)
    assert (cfg.num_attention_heads, cfg.num_key_value_heads) == (64, 8)
    assert cfg.num_hidden_layers == 5 and cfg.vocab_size == 128256
