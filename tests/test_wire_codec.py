"""Activation wire codec: round-trip properties, handshake negotiation,
and the compressed loopback master<->worker path.

Pins the perf_opt contract: a 2-segment loopback run under the bf16 codec
ships >= 1.9x fewer `wire.bytes_out` per decode token than `none`, int8
~4x, and compressed runs still complete generation (the codec perturbs
low-order logit bits like kv-quant does, so token parity is only asserted
for `none`).
"""

import numpy as np
import pytest

import jax

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.runner import RemoteRunner
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import protocol
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.protocol import WorkerInfo
from cake_tpu.runtime.worker import Worker


# -- codec round-trip properties --------------------------------------------

_SHAPES = [(1, 1, 32), (2, 5, 16), (7,), (3, 4)]


def _rand(shape, dtype, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(*shape) * rng.choice([1e-3, 1.0, 37.0])
    if dtype == "bfloat16":
        import ml_dtypes

        return x.astype(ml_dtypes.bfloat16)
    if np.issubdtype(np.dtype(dtype), np.integer):
        return (x * 100).astype(dtype)
    return x.astype(dtype)


@pytest.mark.parametrize("codec", protocol.CODECS)
@pytest.mark.parametrize(
    "dtype", ["float32", "bfloat16", "float16", "int32", "int8", "int64"]
)
def test_activation_roundtrip_all_dtypes(dtype, codec):
    """Every (dtype, codec) pair round-trips: shape and dtype exactly;
    values exactly for `none` and for integer dtypes under any codec
    (pass-through), within the codec's quantization bound for floats."""
    for seed, shape in enumerate(_SHAPES):
        arr = _rand(shape, dtype, seed)
        out, got_codec = protocol.decode_activation(
            protocol.encode_activation(arr, codec)
        )
        is_int = np.issubdtype(arr.dtype, np.integer)
        # integers always pass through; 2-byte floats under bf16 compress
        # nothing (and f16->bf16 would LOSE mantissa bits), so they ride
        # the none layout verbatim
        passthrough = is_int or (
            codec == "bf16" and dtype in ("bfloat16", "float16")
        )
        assert got_codec == ("none" if passthrough else codec)
        assert out.shape == arr.shape and out.dtype == arr.dtype
        f = np.asarray(arr, np.float32)
        if codec == "none" or passthrough:
            np.testing.assert_array_equal(out, arr)
        elif codec == "bf16":
            import ml_dtypes

            np.testing.assert_array_equal(
                out, arr.astype(ml_dtypes.bfloat16).astype(arr.dtype)
            )
        else:  # int8: per-row absmax, round-to-nearest -> err <= scale/2,
            # plus the cast back into a low-precision original dtype
            rows = f.reshape(-1, f.shape[-1])
            absmax = np.abs(rows).max(axis=1, keepdims=True)
            scale = absmax / 127.0
            eps_orig = {"bfloat16": 2.0 ** -8, "float16": 2.0 ** -10}.get(
                dtype, 2.0 ** -23
            )
            err = np.abs(np.asarray(out, np.float32).reshape(rows.shape)
                         - rows)
            assert (err <= scale * 0.51 + absmax * eps_orig + 1e-6).all()


def test_int8_codec_compresses_about_4x():
    x = np.random.RandomState(0).randn(1, 8, 512).astype(np.float32)
    none_len = len(protocol.encode_activation(x, "none"))
    int8_len = len(protocol.encode_activation(x, "int8"))
    bf16_len = len(protocol.encode_activation(x, "bf16"))
    assert none_len / int8_len > 3.5
    assert none_len / bf16_len > 1.9


def test_codec_counters_track_savings():
    raw0 = obs_metrics.counter("wire.codec_bytes_raw").value
    enc0 = obs_metrics.counter("wire.codec_bytes_encoded").value
    x = np.zeros((1, 4, 256), np.float32) + 1.5
    protocol.encode_activation(x, "int8")
    raw = obs_metrics.counter("wire.codec_bytes_raw").value - raw0
    enc = obs_metrics.counter("wire.codec_bytes_encoded").value - enc0
    assert raw == x.nbytes and 0 < enc < raw / 3


def test_ops_roundtrip_carries_codec():
    x = np.random.RandomState(1).randn(1, 2, 64).astype(np.float32)
    ops = [("model.layers.0", 9)]
    for codec in protocol.CODECS:
        x2, ops2, got = protocol.decode_ops(
            protocol.encode_ops(x, ops, codec)
        )
        assert got == codec and ops2 == ops and x2.shape == x.shape


def test_decode_activation_rejects_unknown_marker():
    with pytest.raises(ValueError, match="codec marker"):
        protocol.decode_activation(b"\xff\x00\x00")


def test_worker_info_codecs_default_is_none_only():
    """A pre-codec peer's handshake payload lacks the field; it must not be
    credited with compression support."""
    import dataclasses
    import json

    d = dataclasses.asdict(WorkerInfo(name="old"))
    d.pop("codecs")
    got = WorkerInfo.from_bytes(json.dumps(d).encode())
    assert got.codecs == ["none"]


# -- loopback master <-> worker under compression ----------------------------

CFG = tiny(max_seq_len=64)
# hidden wide enough that the per-token activation dominates the op-list
# JSON overhead — the >= 1.9x bf16 contract is about payload, not framing
BIG = tiny(hidden_size=512, intermediate_size=256, num_hidden_layers=2,
           max_seq_len=64)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(3))


@pytest.fixture(scope="module")
def big_params():
    return llama.init_params(BIG, jax.random.PRNGKey(4))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def _head(params):
    return {k: params[k] for k in ("embed", "norm_f", "lm_head")}


def _run_codec(cfg, params, codec, n_layers, n_tokens=4,
               worker_codec=None):
    """One loopback generation; returns (tokens, wire bytes_out per decode
    token, worker handle already shut down)."""
    w = Worker(
        "w", cfg,
        Topology.from_dict({"w": {"layers": [f"model.layers.0-{n_layers - 1}"]}}),
        _loader(params), address="127.0.0.1:0", max_seq=cfg.max_seq_len,
        wire_codec=worker_codec,
    )
    w.serve_in_background()
    topo = Topology.from_dict({
        "w": {"host": f"127.0.0.1:{w.port}",
              "layers": [f"model.layers.0-{n_layers - 1}"]},
    })
    try:
        runners = build_runners(cfg, topo, _loader(params),
                                wire_codec=codec)
        g = DistributedGenerator(
            cfg, _head(params), runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([5, 9, 2])
        toks = [g.next_token(0).id]
        out0 = obs_metrics.counter("wire.bytes_out").value
        for i in range(1, n_tokens):
            toks.append(g.next_token(i).id)
        per_tok = (obs_metrics.counter("wire.bytes_out").value - out0) / (
            n_tokens - 1
        )
        g.close()
        return toks, per_tok
    finally:
        w.shutdown()


def test_loopback_bf16_halves_wire_bytes_per_decode_token(big_params):
    """Acceptance: 2-segment loopback under --wire-codec bf16 ships
    >= 1.9x fewer wire.bytes_out per decode token than none (both request
    and mirrored reply land in the same process-global counter here)."""
    toks_none, per_none = _run_codec(BIG, big_params, "none", 2)
    toks_bf16, per_bf16 = _run_codec(BIG, big_params, "bf16", 2)
    assert len(toks_none) == len(toks_bf16) == 4
    assert per_none / per_bf16 >= 1.9, (per_none, per_bf16)


def test_loopback_int8_completes_and_shrinks_bytes(params):
    """--wire-codec int8: generation completes end-to-end and the byte
    counters shrink ~4x on the activation-dominated payload."""
    toks_none, per_none = _run_codec(CFG, params, "none", 4, n_tokens=6)
    toks_int8, per_int8 = _run_codec(CFG, params, "int8", 4, n_tokens=6)
    assert len(toks_int8) == 6
    assert all(0 <= t < CFG.vocab_size for t in toks_int8)
    assert per_int8 < per_none
    raw = obs_metrics.counter("wire.codec_bytes_raw").value
    enc = obs_metrics.counter("wire.codec_bytes_encoded").value
    assert 0 < enc < raw


def test_loopback_none_codec_stays_bit_identical(params):
    """The default codec must not perturb anything: loopback greedy tokens
    equal the all-local generator's (the existing parity contract)."""
    from cake_tpu.runtime.generator import LlamaGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    toks, _ = _run_codec(CFG, params, "none", 4, n_tokens=6)
    g = LlamaGenerator(CFG, params, settings=settings)
    g.set_prompt([5, 9, 2])
    assert toks == [g.next_token(i).id for i in range(6)]


def test_handshake_rejects_unadvertised_codec(params):
    """A worker restricted to `none` must fail the handshake of a master
    asking for int8 — at connect time, not mid-stream."""
    w = Worker(
        "w", CFG,
        Topology.from_dict({"w": {"layers": ["model.layers.0-3"]}}),
        _loader(params), address="127.0.0.1:0", max_seq=CFG.max_seq_len,
        wire_codec="none",
    )
    w.serve_in_background()
    try:
        with pytest.raises(RuntimeError, match="does not accept wire codec"):
            RemoteRunner(f"127.0.0.1:{w.port}", start=0, stop=4,
                         wire_codec="int8")
        # the advertised set is visible on the status surface
        assert w.status()["wire_codecs"] == ["none"]
    finally:
        w.shutdown()


def test_remote_runner_rejects_unknown_codec():
    with pytest.raises(ValueError, match="unknown wire codec"):
        RemoteRunner("127.0.0.1:1", start=0, stop=1, wire_codec="zstd")


def test_worker_rejects_unknown_codec(params):
    with pytest.raises(ValueError, match="unknown wire codec"):
        Worker("w", CFG,
               Topology.from_dict({"w": {"layers": ["model.layers.0-3"]}}),
               _loader(params), address="127.0.0.1:0", wire_codec="zstd")


def test_worker_enforces_codec_restriction_server_side(params):
    """A client that skips the handshake check must not smuggle a lossy
    codec onto a none-restricted worker: the serve loop rejects the op
    with an ERROR reply (and keeps serving `none` requests)."""
    from cake_tpu.runtime import wire
    from cake_tpu.runtime.protocol import MsgType

    w = Worker(
        "w", CFG,
        Topology.from_dict({"w": {"layers": ["model.layers.0-3"]}}),
        _loader(params), address="127.0.0.1:0", max_seq=CFG.max_seq_len,
        wire_codec="none",
    )
    w.serve_in_background()
    try:
        conn = wire.connect("127.0.0.1", w.port)
        conn.send(MsgType.HELLO)
        t, _ = conn.recv()
        assert t == MsgType.WORKER_INFO
        x = np.zeros((1, 1, CFG.hidden_size), np.float32)
        conn.send(MsgType.BATCH,
                  protocol.encode_ops(x, [("model.layers.0", 0)], "int8"))
        t, payload = conn.recv()
        assert t == MsgType.ERROR
        assert "not accepted" in protocol.decode_error(payload)
        conn.send(MsgType.BATCH,
                  protocol.encode_ops(x, [("model.layers.0", 0)], "none"))
        t, _ = conn.recv()
        assert t == MsgType.TENSOR
        conn.close()
    finally:
        w.shutdown()


def test_bf16_on_bf16_activation_passes_through():
    """Already-bf16 activations ride the none layout under the bf16 codec
    (no byte saving to be had; skips a full same-dtype copy per hop)."""
    import ml_dtypes

    x = np.random.RandomState(2).randn(1, 2, 64).astype(ml_dtypes.bfloat16)
    enc = protocol.encode_activation(x, "bf16")
    assert enc == protocol.encode_activation(x, "none")
    out, codec = protocol.decode_activation(enc)
    assert codec == "none" and out.dtype == x.dtype
    np.testing.assert_array_equal(out, x)
