"""70B weight-plane rehearsal at FILE scale (r4: verdict item 7).

The offline weight plane of the reference is `cake-split-model`
(cake-split-model/src/main.rs:144-223): read a sharded safetensors index,
keep only the bytes a node owns. The mesh-path equivalent here is
`utils/sharded_load.load_llama_params_on_mesh` over a REAL multi-shard
`model.safetensors.index.json` — this test rehearses the full 70B file
geometry (80 stacked layers, multiple shard files, pre-quantized `.q8`
tensors from tools/quantize_model) at tiny dims and proves, by byte
accounting, that

- each of the 16 pipeline stages' layer bytes is exactly 1/16 of the
  stacked-layer total (a stage reads its 5 layers, nothing else), and
- the loader reads the checkpoint once: total bytes ~= the checkpoint's
  tensor payload (no per-shard read amplification from the 16-way mesh),

and times the load (the number recorded in BASELINE.md's weight-plane
row)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]

INNER = r"""
import json, re, time
from pathlib import Path

import jax
assert len(jax.devices()) == 16, jax.devices()
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.tools.quantize_model import quantize_checkpoint
from cake_tpu.utils import sharded_load
from cake_tpu.utils.weights import save_llama_params

cfg = tiny(num_hidden_layers=80, num_attention_heads=8,
           num_key_value_heads=4, hidden_size=64, intermediate_size=128,
           vocab_size=256, max_seq_len=32)
root = Path(r"{tmp}")
bf = root / "bf16"
params = llama.init_params(cfg, jax.random.PRNGKey(0))
save_llama_params(params, bf, cfg.num_hidden_layers)

# pre-quantized multi-shard checkpoint (~1 MiB shards -> several files,
# the real 70B index geometry at miniature scale)
q8 = root / "q8"
quantize_checkpoint(bf, q8, shard_bytes=1 << 20)
index = json.loads((q8 / "model.safetensors.index.json").read_text())
shard_files = sorted(set(index["weight_map"].values()))
assert len(shard_files) >= 3, shard_files
payload = index["metadata"]["total_size"]

# per-stage byte attribution: bucket every read by the layer index in the
# tensor name (stage s owns layers [5s, 5s+5) at stage=16 over 80 layers)
stage_bytes = [0] * 16
other_bytes = [0]
layer_re = re.compile(r"model\.layers\.(\d+)\.")

def account(name, nbytes):
    m = layer_re.match(name)
    if m:
        stage_bytes[int(m.group(1)) // 5] += nbytes
    else:
        other_bytes[0] += nbytes

orig1, orig2 = (sharded_load.CheckpointReader.read1d,
                sharded_load.CheckpointReader.read2d)

def read1d(self, name, sl=slice(None)):
    out = orig1(self, name, sl)
    account(name, out.nbytes)
    return out

def read2d(self, name, rows, cols, transpose):
    out = orig2(self, name, rows, cols, transpose)
    account(name, out.nbytes)
    return out

sharded_load.CheckpointReader.read1d = read1d
sharded_load.CheckpointReader.read2d = read2d

plan = MeshPlan.build(cfg, num_stages=16, devices=jax.devices())
t0 = time.perf_counter()
loaded = sharded_load.load_llama_params_on_mesh(
    q8, cfg, plan.mesh, quantize="int8")
for leaf in jax.tree.leaves(loaded):
    leaf.block_until_ready()
dt = time.perf_counter() - t0

total_layer = sum(stage_bytes)
# every stage's layer bytes == exactly 1/16 of the stacked-layer total
for s, b in enumerate(stage_bytes):
    assert b == total_layer // 16, (s, b, total_layer)
# read-once: total attributed bytes ~= the checkpoint payload. The int8
# path re-derives nothing (pre-quantized), and replicated leaves
# (embed/norm/head) are memoized to one read despite 16 addressable
# shards. Scales are f32 in both. Allow a few % for dtype/layout edges.
grand = total_layer + other_bytes[0]
assert abs(grand - payload) / payload < 0.05, (grand, payload)

q = loaded["layers"]["wq"].q
assert q.shape == (80, 64, 64) and str(q.dtype) == "int8"
print(json.dumps({
    "shards": len(shard_files),
    "payload_bytes": payload,
    "stage_layer_bytes": stage_bytes[0],
    "load_s": round(dt, 3),
    "mb_per_s": round(payload / dt / 1e6, 1),
}))
print("fileplane ok")
"""


def test_80layer_multishard_q8_load_stage16(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=16"]
    )
    r = subprocess.run(
        [sys.executable, "-c", INNER.replace("{tmp}", str(tmp_path))],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "fileplane ok" in r.stdout
    stats = json.loads(r.stdout.strip().splitlines()[-2])
    assert stats["shards"] >= 3
    assert stats["load_s"] > 0
