"""Observability layer (cake_tpu/obs): metrics registry, span tracer with
Chrome trace-event export, per-token flight recorder, and the instrumented
runtime — a loopback master↔worker run whose wire byte counters must agree
across the master's flight records, the worker's status page, and the
registry; plus the CLI smoke (`make trace-smoke`) that validates every
``--trace``/``--metrics-out``/``--flight-log`` artifact parses."""

import json
import threading
import urllib.request

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import flight, metrics, trace
from cake_tpu.obs.metrics import Histogram, Registry
from cake_tpu.obs.trace import span
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.worker import Worker

CFG = tiny(max_seq_len=32)


# -- metrics registry --------------------------------------------------------

def test_counter_concurrent_increments():
    r = Registry(enabled=True)
    c = r.counter("hits")
    n_threads, n_inc = 8, 500

    def worker():
        for _ in range(n_inc):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_inc
    assert r.counter("hits") is c  # get-or-create returns the same series


def test_histogram_concurrent_observes_and_bucketing():
    h = Histogram("lat", buckets=(1.0, 10.0, 100.0))

    def worker():
        for _ in range(100):
            h.observe(0.5)
            h.observe(5.0)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 800
    assert h.min == 0.5 and h.max == 5.0
    snap = h.snapshot()
    assert snap["count"] == 800
    assert snap["buckets"]["1.0"] == 400  # every 0.5 lands in le=1.0
    assert snap["buckets"]["10.0"] == 400


def test_histogram_percentiles_within_bucket_bounds():
    h = Histogram("p", buckets=(1.0, 10.0, 100.0))
    for _ in range(50):
        h.observe(0.5)
    for _ in range(40):
        h.observe(5.0)
    for _ in range(10):
        h.observe(50.0)
    assert 0.5 <= h.percentile(0.5) <= 1.0
    assert 10.0 <= h.percentile(0.99) <= 50.0
    # clamped to the observed range, never past max
    assert h.percentile(1.0) == 50.0
    assert Histogram("empty").percentile(0.5) == 0.0


def test_registry_type_conflict_and_disabled_nulls():
    r = Registry(enabled=True)
    r.counter("x")
    with pytest.raises(TypeError):
        r.gauge("x")
    off = Registry(enabled=False)
    null = off.counter("y")
    null.inc()  # no-op, no error
    null.observe(1.0)
    assert off.snapshot() == {}


def test_registry_json_and_prometheus_dumps(tmp_path):
    r = Registry(enabled=True)
    r.counter("wire.bytes_out").inc(123)
    r.gauge("hbm.used_gib").set(1.5)
    h = r.histogram("step_ms", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    p = tmp_path / "metrics.json"
    r.dump_json(str(p))
    snap = json.loads(p.read_text())
    assert snap["wire.bytes_out"] == {"type": "counter", "value": 123}
    assert snap["step_ms"]["count"] == 2
    assert "p50" in snap["step_ms"] and "p99" in snap["step_ms"]
    prom = r.to_prometheus()
    assert "cake_wire_bytes_out 123" in prom
    assert 'cake_step_ms_bucket{le="1.0"} 1' in prom
    assert "cake_step_ms_count 2" in prom


# -- span tracer -------------------------------------------------------------

def test_span_disabled_is_shared_noop():
    tr = trace.tracer()
    assert not tr.enabled
    s1, s2 = span("a"), span("b", k=1)
    assert s1 is s2  # the shared null context manager
    with s1:
        pass


def test_chrome_trace_export_is_valid_trace_event_json():
    tr = trace.tracer()
    tr.start()
    try:
        with span("outer", seg=0):
            with span("inner"):
                pass

        def other_thread():
            with span("threaded"):
                pass

        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
    finally:
        tr.stop()
    doc = json.loads(json.dumps(tr.to_chrome_trace()))  # JSON round-trip
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} >= {"outer", "inner", "threaded"}
    # complete events only (no unmatched B/E), sorted ts, sane durations
    assert all(e["ph"] in ("X", "M") for e in evs)
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    assert all(e["dur"] >= 0 for e in xs)
    assert all(isinstance(e["pid"], int) and isinstance(e["tid"], int)
               for e in xs)
    inner = next(e for e in xs if e["name"] == "inner")
    assert inner["args"]["parent"] == "outer"  # per-thread span stack
    threaded = next(e for e in xs if e["name"] == "threaded")
    assert "parent" not in threaded.get("args", {})
    tr.clear()


def test_tracer_event_cap_counts_drops():
    tr = trace.tracer()
    tr.start(max_events=2)
    try:
        for _ in range(5):
            with span("s"):
                pass
    finally:
        tr.stop()
    assert len(tr.to_chrome_trace()["traceEvents"]) >= 2
    assert tr.dropped == 3
    tr.clear()


# -- flight recorder ---------------------------------------------------------

def test_flight_recorder_ring_totals_and_jsonl(tmp_path):
    rec = flight.FlightRecorder(capacity=4)
    rec.record(index=0, kind="decode")  # disabled: dropped
    assert rec.records() == []
    p = tmp_path / "flight.jsonl"
    rec.enable(path=str(p))
    rec.record(index=0, kind="prefill", total_ms=3.0, wire_bytes_out=7,
               segments_ms=[1.0, 2.0])
    for i in range(1, 6):
        rec.record(index=i, kind="decode", total_ms=1.0, wire_bytes_out=10,
                   segments_ms=[0.25, 0.5], recovery=i == 3)
    rows = rec.records()
    assert len(rows) == 4  # bounded ring: oldest aged out
    assert all(r["kind"] == "decode" for r in rows)
    totals = rec.totals()
    assert totals["records"] == 4 and totals["by_kind"] == {"decode": 4}
    assert totals["wire_bytes_out"] == 40
    assert totals["recovery"] == 1
    assert totals["segments_ms"] == [1.0, 2.0]
    # the JSONL stream kept every record (writes flush in batches; close()
    # drains the tail), one parseable object per line
    rec.close()
    lines = [json.loads(ln) for ln in p.read_text().splitlines()]
    assert len(lines) == 6
    assert lines[0]["kind"] == "prefill" and lines[0]["t"] > 0
    rec.record(index=9, kind="decode")  # closed: dropped again
    assert len(rec.records()) == 4


# -- instrumented runtime: loopback master <-> worker ------------------------

@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(7))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def test_loopback_wire_bytes_consistent_and_spans_recorded(params):
    """Two-segment decode (remote worker layers 0-1, local layers 2-3):
    the master's flight-recorder wire totals must equal the worker's own
    payload byte counters, the status page must expose nonzero wire
    metrics, and the Chrome trace must hold the canonical span set."""
    w = Worker("w1", CFG, Topology.from_dict(
        {"w1": {"layers": ["model.layers.0-1"]}}), _loader(params),
        address="127.0.0.1:0", max_seq=CFG.max_seq_len)
    w.serve_in_background()
    status_port = w.start_status_server(0)
    topo = Topology.from_dict({
        "w1": {"host": f"127.0.0.1:{w.port}",
               "layers": ["model.layers.0-1"]},
    })
    tr = trace.tracer()
    rec = flight.recorder()
    rec.clear()
    rec.enable()
    tr.start()
    try:
        runners = build_runners(CFG, topo, _loader(params))
        g = DistributedGenerator(
            CFG, {k: params[k] for k in ("embed", "norm_f", "lm_head")},
            runners,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        )
        g.set_prompt([3, 5, 7])
        for i in range(4):
            g.next_token(i)

        stats = g.runner_stats()
        assert [s["layers"] for s in stats] == ["0-1", "2-3"]
        # 4 forwards per segment, first is warm-up -> 3 histogram samples
        assert all(s["calls"] == 3 for s in stats)
        assert all(s["avg_ms"] > 0 and s["warmup_ms"] > 0 for s in stats)
        assert all(s["p50_ms"] > 0 and s["p99_ms"] >= s["p50_ms"]
                   for s in stats)
        assert g.tokens_per_sec() is None or g.tokens_per_sec() > 0

        totals = rec.totals()
        assert totals["by_kind"] == {"prefill": 1, "decode": 3}
        assert len(totals["segments_ms"]) == 2  # one slot per segment
        assert totals["wire_bytes_out"] > 0 and totals["wire_bytes_in"] > 0

        with urllib.request.urlopen(
            f"http://127.0.0.1:{status_port}/", timeout=10
        ) as r:
            st = json.loads(r.read())
        # payload-level agreement: every byte the master's flight records
        # say went out arrived as worker bytes_in, and vice versa
        assert st["bytes_in"] == totals["wire_bytes_out"] > 0
        assert st["bytes_out"] == totals["wire_bytes_in"] > 0
        m = st["metrics"]
        assert m["wire.bytes_out"]["value"] > 0
        assert m["wire.bytes_in"]["value"] > 0
        assert m["wire.crc_failures"]["value"] == 0
        # 4 forwards: the first op of each activation shape (prefill and
        # the first decode — both compile) lands in the warmup gauge, the
        # steady-state rest in the histogram
        assert m["worker.forward_ms"]["count"] >= 2
        assert m["worker.warmup_ms"]["value"] > 0
        assert m["wire.serialize_ms"]["count"] >= 4

        with urllib.request.urlopen(
            f"http://127.0.0.1:{status_port}/metrics", timeout=10
        ) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            prom = r.read().decode()
        assert "cake_wire_bytes_out" in prom

        g.close()
        # the exit-time --metrics-out dump runs after close(): the
        # per-segment series must still be in the registry
        reg_snap = metrics.registry().snapshot(prefix="master.segment")
        assert reg_snap["master.segment0.decode_ms"]["count"] == 3
        assert reg_snap["master.segment1.warmup_ms"]["value"] > 0
    finally:
        tr.stop()
        rec.disable()
        w.shutdown()

    names = {e["name"] for e in tr.to_chrome_trace()["traceEvents"]
             if e["ph"] == "X"}
    assert names >= {"prefill", "decode.step", "decode.segment",
                     "wire.send", "wire.recv", "segment.remote_rtt",
                     "segment.local_scan", "sample", "worker.forward"}
    tr.clear()
    rec.clear()


# -- CLI smoke (`make trace-smoke`) ------------------------------------------

@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    from cake_tpu.utils.weights import save_llama_params

    d = tmp_path_factory.mktemp("obsmodel")
    p = llama.init_params(tiny(), jax.random.PRNGKey(0), dtype="float32")
    save_llama_params(p, d)
    (d / "config.json").write_text(json.dumps(tiny().to_hf_dict()))
    return d


def test_trace_smoke_cli_artifacts_parse(model_dir, tmp_path):
    """Tiny CPU-only decode with every obs flag: the Chrome trace, metrics
    JSON, and flight JSONL must all parse and hold the expected series.
    Runs cli.main in-process (the flag wiring and the exit-time artifact
    writes are the same code path; a subprocess would spend ~20s of suite
    budget re-importing jax for no extra coverage — test_cli.py already
    pins the subprocess surface)."""
    from cake_tpu import cli, obs

    trace_p = tmp_path / "trace.json"
    metrics_p = tmp_path / "metrics.json"
    flight_p = tmp_path / "flight.jsonl"
    obs.registry().reset(prefix="generator.")
    rc = cli.main([
        "--model", str(model_dir), "--prompt-ids", "3,5,7", "-n", "4",
        "--temperature", "0", "--max-seq", "32", "--cpu",
        "--log-level", "debug", "--trace", str(trace_p),
        "--metrics-out", str(metrics_p), "--flight-log", str(flight_p),
    ])
    # the in-process --log-level debug reconfigured root logging; put it
    # back before the rest of the suite runs (jax debug logs are chatty)
    obs.setup_logging("info")
    assert rc == 0

    doc = json.loads(trace_p.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "prefill" in names
    assert names & {"decode.step", "decode.block"}

    snap = json.loads(metrics_p.read_text())
    assert snap["generator.prefill_ms"]["count"] == 1
    assert snap["generator.decode_ms"]["count"] >= 1

    recs = [json.loads(ln) for ln in flight_p.read_text().splitlines()]
    assert recs[0]["kind"] == "prefill"
    assert any(rec["kind"] == "decode" for rec in recs)
    # the exit path stopped the tracer and closed the flight recorder
    assert not trace.tracer().enabled
    assert not flight.recorder().enabled
    trace.tracer().clear()
    flight.recorder().clear()
