"""Structured generation (cake_tpu/constrain): grammar-constrained
decoding, stop sequences, and logprobs across the engine and serve plane.

`make constrain-smoke` acceptance: regex/JSON-schema -> token-DFA -> mask
round trips (unicode/byte-level tokenizer edges included), the disk-cache
hit path, schema-constrained serve requests returning valid JSON through
the full HTTP plane, the masked decode step compiling once per shape (no
retrace per token OR per grammar), stop-string holdback across SSE chunk
boundaries, logprobs against a numpy softmax reference, and the
determinism guard: unconstrained streams are bit-identical whether or not
the mask/logprob plumbing is active around them.
"""

import json
import re
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from cake_tpu.constrain import fsm as fsm_mod
from cake_tpu.constrain import (
    Guide,
    RegexError,
    build_token_dfa,
    json_schema_to_regex,
)
from cake_tpu.constrain.guide import DEAD_ENDS
from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops import sampling
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.serve import session as serve_session
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.engine import SingleStreamEngine
from cake_tpu.serve.scheduler import Scheduler
from cake_tpu.serve.session import Session

# EOS *enabled* (unlike test_serve): constrained streams must be able to
# terminate exactly when their grammar completes
CFG = tiny(max_seq_len=128, eos_token_id=2)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)
EOS = 2


class AsciiTok:
    """id -> one printable-ASCII char (mod 95). Many-to-one on purpose:
    several ids share each char, like merged BPE vocab entries."""

    def decode(self, ids):
        return "".join(chr(32 + (i % 95)) for i in ids)

    def encode(self, text):
        return [ord(c) - 32 for c in text]


def _ascii_vocab(n=CFG.vocab_size):
    t = AsciiTok()
    return [t.decode([i]) for i in range(n)]


# small hand-rolled vocab for DFA unit tests: single chars + multi-char +
# unicode + an empty-string token (undecodable id)
TOY_VOCAB = [chr(c) for c in range(32, 127)] + ["ab", "12", "é", "∑x", ""]
TOY_EOS = (3,)  # id 3 = '#': its TEXT must never satisfy a transition


def tid(s: str) -> int:
    return TOY_VOCAB.index(s)


SCHEMA = {
    "type": "object",
    "properties": {
        "a": {"type": "integer"},
        "ok": {"type": "boolean"},
    },
    "required": ["a", "ok"],
}


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(7))


@pytest.fixture(scope="module")
def server(params):
    """BatchGenerator with tokenizer + logprob capacity 3 behind the
    HTTP API — the full structured-output serving surface."""
    gen = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                         settings=SamplerSettings(**GREEDY), logprobs=3)
    sched = Scheduler(gen, queue_depth=4, request_timeout_s=120)
    sched.start(max_concurrent=2)
    srv = start_api_server(sched)
    yield srv
    srv.close()
    sched.close()


def _post(srv, body: dict, timeout: float = 120.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post_sse(srv, body: dict, timeout: float = 120.0):
    body = dict(body, stream=True)
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    events = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for raw in r:
            raw = raw.strip()
            if not raw.startswith(b"data: "):
                continue
            data = raw[len(b"data: "):]
            events.append(data.decode() if data == b"[DONE]"
                          else json.loads(data))
    return events


# -- regex -> token DFA ---------------------------------------------------

class TestTokenDfa:
    def test_digit_run_masks_transitions_accepting(self):
        d = build_token_dfa("[0-9]+", TOY_VOCAB, eos_ids=TOY_EOS)
        m0 = d.mask_bool(0)
        allowed = {TOY_VOCAB[i] for i in range(len(TOY_VOCAB)) if m0[i]}
        assert allowed == set("0123456789") | {"12"}  # multi-char token
        assert not d.accepting[0]
        s1 = int(d.trans[0, tid("7")])
        assert d.accepting[s1]
        assert d.mask_bool(s1)[TOY_EOS[0]]  # EOS allowed once accepting
        s2 = int(d.trans[0, tid("12")])  # two chars in one token
        assert d.accepting[s2]

    def test_empty_string_token_never_allowed(self):
        d = build_token_dfa(".*", TOY_VOCAB, eos_ids=TOY_EOS)
        empty = len(TOY_VOCAB) - 1
        assert TOY_VOCAB[empty] == ""
        assert not d.mask_bool(0)[empty]  # zero-width = infinite no-op

    def test_eos_id_never_matches_as_text(self):
        # id 3 decodes to '#'; pattern '#' must be satisfied only by the
        # OTHER '#' token, never by the EOS id
        d = build_token_dfa("#", TOY_VOCAB, eos_ids=TOY_EOS)
        m0 = d.mask_bool(0)
        assert not m0[TOY_EOS[0]]
        assert m0[tid("#")] or True  # '#' is id 3 itself in TOY_VOCAB?
        # TOY_VOCAB has exactly one '#', which IS the eos id -> dead end
        assert tid("#") == TOY_EOS[0]
        assert not m0.any()

    def test_unicode_tokens_walk_the_dfa(self):
        d = build_token_dfa("é+(∑x)?", TOY_VOCAB, eos_ids=TOY_EOS)
        m0 = d.mask_bool(0)
        assert m0[tid("é")]
        assert not m0[tid("a")]
        s1 = int(d.trans[0, tid("é")])
        assert d.accepting[s1]
        assert d.mask_bool(s1)[tid("∑x")]  # 2-codepoint token in one hop
        s2 = int(d.trans[s1, tid("∑x")])
        assert d.accepting[s2]
        # grammar exhausted: only EOS remains
        m2 = d.mask_bool(s2)
        assert {i for i in range(len(TOY_VOCAB)) if m2[i]} == {TOY_EOS[0]}

    def test_quantifiers_classes_alternation(self):
        d = build_token_dfa("(a|b){2,3}[^0-9x]?", TOY_VOCAB,
                            eos_ids=TOY_EOS)
        s = 0
        for ch in "ab":
            s = int(d.trans[s, tid(ch)])
            assert s >= 0
        assert d.accepting[s]
        m = d.mask_bool(s)
        assert m[tid("a")] and m[tid("q")] and not m[tid("5")]
        assert not m[tid("x")]

    def test_guide_advance_and_dead_end(self):
        d = build_token_dfa("A\x07", TOY_VOCAB, eos_ids=TOY_EOS)
        g = Guide(d)
        assert g.allows(tid("A")) and not g.dead_end
        assert g.advance(tid("A"))
        # \x07 (BEL) exists in no vocab string: nothing can be emitted
        assert g.dead_end
        assert not g.advance(tid("B"))

    def test_regex_errors(self):
        for bad in ("(a", "a)", "[z-a]", "*a", "a{3,1}"):
            with pytest.raises(RegexError):
                build_token_dfa(bad, TOY_VOCAB, eos_ids=TOY_EOS)


class TestJsonSchema:
    def test_lowering_matches_python_re(self):
        pat = json_schema_to_regex(SCHEMA)
        assert re.fullmatch(pat, '{"a": -42, "ok": true}')
        assert re.fullmatch(pat, '{"a": 0, "ok": false}')
        assert not re.fullmatch(pat, '{"a": 1.5, "ok": true}')
        assert not re.fullmatch(pat, '{"ok": true, "a": 1}')

    def test_types_enum_array_string(self):
        assert re.fullmatch(json_schema_to_regex({"type": "null"}), "null")
        num = json_schema_to_regex({"type": "number"})
        assert re.fullmatch(num, "-3.25") and re.fullmatch(num, "17")
        en = json_schema_to_regex({"enum": ["hi", 3, None]})
        for lit in ('"hi"', "3", "null"):
            assert re.fullmatch(en, lit)
        arr = json_schema_to_regex(
            {"type": "array", "items": {"type": "boolean"},
             "maxItems": 2})
        for lit in ("[]", "[true]", "[true, false]"):
            assert re.fullmatch(arr, lit)
        assert not re.fullmatch(arr, "[true, true, true]")
        s = json_schema_to_regex({"type": "string", "maxLength": 3})
        assert re.fullmatch(s, '"ab"') and not re.fullmatch(s, '"abcd"')

    def test_bounded_termination(self):
        # the lowered automaton is acyclic: greedily walking ANY allowed
        # path must reach only-EOS within a bounded number of tokens
        pat = json_schema_to_regex(SCHEMA)
        d = build_token_dfa(pat, _ascii_vocab(), eos_ids=(EOS,))
        g = Guide(d)
        for _ in range(64):
            m = g.mask_bool()
            choices = np.flatnonzero(m)
            assert len(choices)
            if list(choices) == [EOS]:
                break
            nxt = next(int(c) for c in choices if c != EOS)
            assert g.advance(nxt)
        else:
            pytest.fail("schema DFA did not terminate in 64 tokens")

    def test_unsupported_schema_raises(self):
        with pytest.raises(RegexError):
            json_schema_to_regex({"type": "object",
                                  "properties": {"x": {"$ref": "#/x"}}})
        with pytest.raises(RegexError):
            json_schema_to_regex({"oneOf": []})


class TestDiskCache:
    def test_disk_cache_hit_path(self, tmp_path):
        vocab = TOY_VOCAB
        hits0 = fsm_mod.FSM_CACHE_HITS.value
        miss0 = fsm_mod.FSM_CACHE_MISSES.value
        fsm_mod._MEMO.clear()
        d1 = fsm_mod.compile_constraint("[a-f]{2,4}", vocab,
                                        eos_ids=TOY_EOS,
                                        cache_dir=str(tmp_path))
        assert fsm_mod.FSM_CACHE_MISSES.value == miss0 + 1
        assert list(tmp_path.glob("*.npz"))
        fsm_mod._MEMO.clear()  # force the DISK path, not the memo
        d2 = fsm_mod.compile_constraint("[a-f]{2,4}", vocab,
                                        eos_ids=TOY_EOS,
                                        cache_dir=str(tmp_path))
        assert fsm_mod.FSM_CACHE_HITS.value == hits0 + 1
        np.testing.assert_array_equal(d1.trans, d2.trans)
        np.testing.assert_array_equal(d1.mask_bits, d2.mask_bits)
        np.testing.assert_array_equal(d1.accepting, d2.accepting)
        # memo path counts as a hit too
        fsm_mod.compile_constraint("[a-f]{2,4}", vocab, eos_ids=TOY_EOS,
                                   cache_dir=str(tmp_path))
        assert fsm_mod.FSM_CACHE_HITS.value == hits0 + 2


# -- engine integration ---------------------------------------------------

def _json_guide(vocab=None):
    pat = json_schema_to_regex(SCHEMA)
    return Guide(build_token_dfa(pat, vocab or _ascii_vocab(),
                                 eos_ids=(EOS,)))


class TestEngine:
    def test_constrained_stream_valid_json_others_bit_identical(self,
                                                                params):
        base = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                              settings=SamplerSettings(**GREEDY))
        base.set_prompts([[5, 6, 7], [8, 9, 10]])
        ref = base.generate(24)

        gen = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                             settings=SamplerSettings(**GREEDY))
        gen.set_prompts([[5, 6, 7], [8, 9, 10]],
                        guides=[None, _json_guide()])
        out = gen.generate(40)
        # the unconstrained neighbor is bit-identical to its solo run —
        # mask plumbing (row 0 = all-ones) must not perturb it
        assert out[0][:24] == ref[0]
        s1 = gen.streams[1]
        assert s1.end_reason == "eos"
        text = AsciiTok().decode([t for t in s1.generated if t != EOS])
        obj = json.loads(text)
        assert isinstance(obj["a"], int) and isinstance(obj["ok"], bool)

    def test_logprobs_engine_streams_bit_identical(self, params):
        base = BatchGenerator(CFG, params,
                              settings=SamplerSettings(**GREEDY))
        base.set_prompts([[5, 6, 7], [8, 9, 10]])
        ref = base.generate(16)
        gen = BatchGenerator(CFG, params,
                             settings=SamplerSettings(**GREEDY),
                             logprobs=4)
        gen.set_prompts([[5, 6, 7], [8, 9, 10]])
        assert gen.generate(16) == ref

    def test_greedy_top1_logprob_is_emitted_token(self, params):
        # repeat_penalty 1.0: raw-logit argmax IS the sampled token, so
        # the reported top-1 id must equal the emitted id every step
        gen = BatchGenerator(
            CFG, params,
            settings=SamplerSettings(temperature=0.0, repeat_penalty=1.0),
            logprobs=2)
        gen.set_prompts([[5, 6, 7]])
        rows = [gen.step() for _ in range(6)]
        toks = [r[0] for r in rows if r[0] is not None]
        assert toks
        for t in toks:
            assert t.logprobs is not None and len(t.logprobs) == 2
            assert t.logprobs[0][0] == t.id
            assert t.logprobs[0][1] <= 0.0

    def test_masked_program_compiles_once_per_shape(self, params):
        """The acceptance pin: N constrained tokens across TWO different
        grammars = zero retraces beyond the initial compile(s) for the
        (batch, table-capacity) shape."""
        gen = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                             settings=SamplerSettings(**GREEDY))
        gen.set_prompts([[5, 6], [7, 8]])
        for s in gen.streams:
            s.done = True
        gen.enqueue([5, 6, 7], 10, guide=_json_guide())
        sl = None
        for _ in range(80):
            gen.step()
            sl = next((s for s in gen.streams if s.stream_id == 10), None)
            if sl is not None and sl.done:
                break
        assert sl is not None and sl.done and sl.end_reason == "eos"
        c1 = gen._masked_jit._cache_size()
        assert c1 <= 2  # first dispatch + committed-sharding steady state
        # a different grammar, same table capacity: NO new compile
        g2 = Guide(build_token_dfa("x=[0-9]{1,4};", _ascii_vocab(),
                                   eos_ids=(EOS,)))
        gen.enqueue([5, 6, 7], 11, guide=g2)
        sl = None
        for _ in range(80):
            gen.step()
            sl = next((s for s in gen.streams if s.stream_id == 11), None)
            if sl is not None and sl.done:
                break
        assert sl is not None and sl.done
        text = AsciiTok().decode([t for t in sl.generated if t != EOS])
        assert re.fullmatch(r"x=[0-9]{1,4};", text)
        assert gen._masked_jit._cache_size() == c1

    def test_dead_end_sets_constraint_reason_and_counter(self, params):
        dead0 = DEAD_ENDS.value
        # after 'A', the grammar demands \x07 — no vocab string has it
        g = Guide(build_token_dfa("A\x07B", _ascii_vocab(),
                                  eos_ids=(EOS,)))
        gen = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                             settings=SamplerSettings(**GREEDY))
        gen.set_prompts([[5, 6, 7]], guides=[g])
        gen.generate(4)
        s = gen.streams[0]
        assert s.done and s.end_reason == "constraint"
        assert DEAD_ENDS.value == dead0 + 1
        assert not gen._guides  # guide released with the stream

    def test_logit_bias_forces_token_and_validates(self, params):
        st = SamplerSettings(temperature=0.0, repeat_penalty=1.0,
                             logit_bias=((7, 1e4),))
        gen = BatchGenerator(CFG, params, settings=st)
        gen.set_prompts([[5, 6]])
        out = gen.generate(3)
        assert out[0] == [7, 7, 7]
        with pytest.raises(ValueError, match="out of range"):
            BatchGenerator(CFG, params, settings=SamplerSettings(
                logit_bias=((CFG.vocab_size, 1.0),)))

    def test_eos_ids_public_property(self, params):
        gen = BatchGenerator(CFG, params,
                             settings=SamplerSettings(**GREEDY))
        assert gen.eos_ids == frozenset(CFG.eos_ids())
        sse = SingleStreamEngine(
            LlamaGenerator(CFG, params, settings=SamplerSettings(**GREEDY)))
        assert sse.eos_ids == frozenset(CFG.eos_ids())

    def test_guides_do_not_compose_with_speculation(self, params):
        gen = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                             settings=SamplerSettings(**GREEDY), spec_k=4)
        with pytest.raises(ValueError, match="speculation"):
            gen.set_prompts([[5, 6, 7]], guides=[_json_guide()])
        # the serve path: enqueue must raise IMMEDIATELY (scheduler turns
        # ValueError into a 400) — deferring to the attach inside step()
        # would read as an engine fault and drain the whole server
        gen.set_prompts([[5, 6, 7]])
        for s in gen.streams:
            s.done = True
        with pytest.raises(ValueError, match="speculation"):
            gen.enqueue([5, 6], 9, guide=_json_guide())

    def test_warm_constrain_precompiles_masked_program(self, params):
        gen = BatchGenerator(CFG, params, tokenizer=AsciiTok(),
                             settings=SamplerSettings(**GREEDY))
        sched = Scheduler(gen, queue_depth=2)
        sched.start(max_concurrent=2, warm_prompt_len=8,
                    warm_constrain=True)
        try:
            assert gen._masked_jit is not None
            assert gen._masked_jit._cache_size() >= 1
        finally:
            sched.stop(drain=False, timeout_s=10)

    def test_logprobs_with_adaptive_block_ladder(self, params):
        # ladder rungs must carry the logprob outputs too (a 4-tuple
        # rung under logprobs_k>0 crashed the unpack)
        gen = BatchGenerator(CFG, params,
                             settings=SamplerSettings(**GREEDY),
                             logprobs=2, block_size=2, block_size_max=8)
        gen.set_prompts([[5, 6, 7]])
        rows = [gen.step() for _ in range(12)]
        toks = [r[0] for r in rows if r and r[0] is not None]
        assert len(toks) >= 12
        assert all(t.logprobs is not None for t in toks)

    def test_single_stream_generator_guide(self, params):
        gen = LlamaGenerator(CFG, params, tokenizer=AsciiTok(),
                             settings=SamplerSettings(**GREEDY))
        gen.set_prompt([5, 6, 7])
        gen.set_guide(Guide(build_token_dfa("ok=[a-z]{2,5}!",
                                            _ascii_vocab(),
                                            eos_ids=(EOS,))))
        toks = []
        for i in range(24):
            t = gen.next_token(i)
            if t.is_end_of_stream:
                break
            toks.append(t.id)
        text = AsciiTok().decode(toks)
        assert re.fullmatch(r"ok=[a-z]{2,5}!", text)

    def test_unsupported_generator_refuses_guide(self, params):
        from cake_tpu.runtime.mesh_generator import MeshGenerator

        gen = MeshGenerator(CFG, params,
                            settings=SamplerSettings(**GREEDY))
        with pytest.raises(ValueError, match="constrained"):
            gen.set_guide(_json_guide())


# -- stop-string holdback -------------------------------------------------

class TestStopHoldback:
    def _drain_tokens(self, sess):
        out = []
        while not sess.events.empty():
            ev = sess.events.get_nowait()
            if ev[0] == "token":
                out.append((ev[1], ev[2]))
        return out

    def test_match_across_token_boundaries_never_leaks(self):
        sess = Session([1], max_tokens=32, stop=["bcd"])
        for tok, txt in ((10, "a"), (11, "b"), (12, "c")):
            sess.on_token(tok, txt)
        # "abc" could still become "a" + "bcd": only 'a' may flush
        assert self._drain_tokens(sess) == [(10, "a")]
        sess.on_token(13, "d")
        assert sess.stop_hit
        assert self._drain_tokens(sess) == []  # b,c,d are the stop string
        assert sess.generated == [10]
        sess.finish("length")
        done = sess.events.get_nowait()
        assert done[0] == "done" and done[1] == "stop" and done[3] is None

    def test_partial_prefix_flushes_when_disproved(self):
        sess = Session([1], max_tokens=32, stop=["XYZ"])
        sess.on_token(1, "X")
        sess.on_token(2, "Y")
        assert self._drain_tokens(sess) == []  # plausible prefix: held
        sess.on_token(3, "Q")  # "XYQ" can no longer match
        assert self._drain_tokens(sess) == [(1, "X"), (2, "Y"), (3, "Q")]
        assert not sess.stop_hit

    def test_straddling_token_contributes_pre_match_tail(self):
        sess = Session([1], max_tokens=32, stop=["bc"])
        sess.on_token(1, "ab")  # 'a' is output, 'b' opens the match
        sess.on_token(2, "cd")
        assert sess.stop_hit
        assert self._drain_tokens(sess) == []
        assert sess.generated == []  # both ids straddle/contain the stop
        sess.finish("length")
        done = sess.events.get_nowait()
        assert done[1] == "stop" and done[3] == "a"

    def test_zero_width_events_hold_with_following_text(self):
        # detok withheld text: the None-text token's chars surface later
        # attributed to the next token — its id must not leak early
        sess = Session([1], max_tokens=32, stop=["mn"])
        sess.on_token(1, "k")
        sess.on_token(2, None)
        sess.on_token(3, "m")  # could open "mn"
        assert self._drain_tokens(sess) == [(1, "k")]
        sess.on_token(4, "np")
        assert sess.stop_hit
        assert sess.generated == [1]

    def test_match_inside_detok_tail(self):
        sess = Session([1], max_tokens=32, stop=["uv"])
        sess.on_token(1, "s")
        sess.finish("length", tail_text="tuvw")
        assert sess.stop_hit and sess.finish_reason == "stop"
        evs = []
        while not sess.events.empty():
            evs.append(sess.events.get_nowait())
        assert evs[0][:3] == ("token", 1, "s")
        assert evs[-1][0] == "done" and evs[-1][1] == "stop"
        assert evs[-1][3] == "t"  # tail truncated at the match


# -- serve plane ----------------------------------------------------------

class TestServe:
    def test_schema_constrained_request_returns_valid_json(self, server):
        out = _post(server, {
            "prompt_ids": [5, 6, 7], "max_tokens": 48,
            "response_format": {"type": "json_schema", "schema": SCHEMA},
        })
        assert out["finish_reason"] == "eos"
        obj = json.loads(out["text"])
        assert isinstance(obj["a"], int) and isinstance(obj["ok"], bool)
        # and streaming: assembled SSE text parses too
        evs = _post_sse(server, {
            "prompt_ids": [5, 6, 7], "max_tokens": 48,
            "response_format": {"type": "json_schema", "schema": SCHEMA},
        })
        text = "".join(e.get("text") or "" for e in evs
                       if isinstance(e, dict) and not e.get("done"))
        text += next(e.get("text") or "" for e in evs
                     if isinstance(e, dict) and e.get("done"))
        assert json.loads(text) == obj

    def test_regex_response_format(self, server):
        out = _post(server, {
            "prompt_ids": [8, 9], "max_tokens": 24,
            "response_format": {"type": "regex",
                                "pattern": "v=[0-9]{1,3}(\\.[0-9])?"},
        })
        assert out["finish_reason"] == "eos"
        assert re.fullmatch(r"v=[0-9]{1,3}(\.[0-9])?", out["text"])

    def test_dead_end_finish_reason_constraint(self, server):
        out = _post(server, {
            "prompt_ids": [5, 6], "max_tokens": 8,
            "response_format": {"type": "regex", "pattern": "Q\x07Z"},
        })
        assert out["finish_reason"] == "constraint"

    def test_stop_string_sse_holdback(self, server):
        full = _post(server, {"prompt_ids": [5, 6, 7],
                              "max_tokens": 16})["text"]
        sub = full[3:6]
        assert len(sub) == 3
        evs = _post_sse(server, {"prompt_ids": [5, 6, 7],
                                 "max_tokens": 16, "stop": [sub]})
        done = next(e for e in evs
                    if isinstance(e, dict) and e.get("done"))
        assert done["finish_reason"] == "stop"
        streamed = "".join(e.get("text") or "" for e in evs
                           if isinstance(e, dict) and "token" in e)
        text = streamed + (done.get("text") or "")
        assert sub not in text
        assert text == full[:3]
        # eos still reports "eos", distinct from stop-string "stop"
        out = _post(server, {
            "prompt_ids": [5, 6], "max_tokens": 24,
            "response_format": {"type": "regex", "pattern": "[a-z]{1,4}"},
        })
        assert out["finish_reason"] == "eos"

    def test_logprobs_in_events_and_usage(self, server):
        evs = _post_sse(server, {"prompt_ids": [5, 6, 7],
                                 "max_tokens": 4, "logprobs": 2})
        toks = [e for e in evs if isinstance(e, dict) and "token" in e]
        assert len(toks) == 4
        for e in toks:
            assert len(e["logprobs"]) == 2
            assert e["logprobs"][0]["logprob"] <= 0.0
        done = next(e for e in evs
                    if isinstance(e, dict) and e.get("done"))
        assert len(done["usage"]["logprobs"]) == 4

    def test_structured_knob_rejections(self, server):
        for body, frag in (
            ({"logprobs": 9}, "capacity"),
            ({"logit_bias": {"999999": 1.0}}, "out of range"),
            ({"logit_bias": {"5": 2.0}}, "compiles one sampler"),
            ({"response_format": {"type": "nope"}}, "response_format"),
            ({"response_format": {"type": "regex", "pattern": "(a"}},
             "response_format"),
            ({"stop": []}, "stop"),
            ({"stop": "x" * 9 * 9, "extra_stop": None}, None),
        ):
            if frag is None:
                continue
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(server, dict({"prompt_ids": [5], "max_tokens": 2},
                                   **body))
            assert exc.value.code == 400
            assert frag in json.loads(exc.value.read())["error"]

    def test_stop_matches_counter_moves(self, server):
        before = serve_session.STOP_MATCHES.value
        full = _post(server, {"prompt_ids": [8, 9, 10],
                              "max_tokens": 12})["text"]
        _post(server, {"prompt_ids": [8, 9, 10], "max_tokens": 12,
                       "stop": [full[2:4]]})
        assert serve_session.STOP_MATCHES.value > before

    def test_concurrent_constrained_and_plain_clients(self, server):
        """A constrained and an unconstrained stream share the batch; the
        plain stream's ids match its solo run (composition invariance
        through the masked program's row-0 path)."""
        solo = _post(server, {"prompt_ids": [11, 12, 13],
                              "max_tokens": 10})
        results = {}

        def plain():
            results["plain"] = _post(server, {
                "prompt_ids": [11, 12, 13], "max_tokens": 10})

        def constrained():
            results["json"] = _post(server, {
                "prompt_ids": [5, 6, 7], "max_tokens": 48,
                "response_format": {"type": "json_schema",
                                    "schema": SCHEMA}})

        threads = [threading.Thread(target=f)
                   for f in (plain, constrained)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert results["plain"]["token_ids"] == solo["token_ids"]
        json.loads(results["json"]["text"])


# -- logprob math ---------------------------------------------------------

def test_topk_logprobs_vs_numpy_reference():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(3, 64)).astype(np.float32) * 3
    vals, ids = sampling.topk_logprobs(jax.numpy.asarray(logits), 5)
    vals, ids = np.asarray(vals), np.asarray(ids)
    ref = logits - np.log(np.exp(
        logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) \
        - logits.max(-1, keepdims=True)
    for b in range(3):
        order = np.argsort(ref[b])[::-1][:5]
        np.testing.assert_array_equal(ids[b], order)
        np.testing.assert_allclose(vals[b], ref[b][order], rtol=1e-5,
                                   atol=1e-5)


def test_unpack_mask_bits_round_trip():
    rng = np.random.default_rng(1)
    for v in (8, 13, 256):
        mask = rng.integers(0, 2, size=(4, v)).astype(np.uint8)
        packed = np.packbits(mask, axis=1, bitorder="little")
        out = np.asarray(sampling.unpack_mask_bits(
            jax.numpy.asarray(packed), v))
        np.testing.assert_array_equal(out, mask.astype(bool))
