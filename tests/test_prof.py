"""The engine profiling plane (cake_tpu/obs/prof).

`make prof-smoke` acceptance: profiling never changes the stream (prof-on
vs prof-off streams bit-identical), a sampled step records the per-phase
breakdown plus the recent-step ring, the retrace sentinel counts backend
compiles and flags exactly the steady-state decode-phase ones (warn by
default, raise under CAKE_PROF_STRICT=1), /debug/prof answers live on a
serve replica, a --trace run nests prof.* phase spans under the request
spans in one timeline, and the benchdiff gate exits nonzero exactly on a
regressed ledger.
"""

import json
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import prof
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator
from cake_tpu.serve.api import start_api_server
from cake_tpu.serve.scheduler import Scheduler

# eos disabled (-1 never sampled): stream lengths are deterministic
CFG = tiny(max_seq_len=64, eos_token_id=-1)
GREEDY = dict(temperature=0.0, repeat_penalty=1.1)


class _FakeTok:
    def decode(self, ids):
        return "".join(chr(ord("a") + (i % 26)) for i in ids)

    def encode(self, text):
        return [ord(c) - ord("a") for c in text]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(7))


@pytest.fixture
def prof_env():
    """Save/restore the process-singleton profiler + sentinel around each
    test (sampling stride is a global knob; findings/steady are global
    state the next suite must not inherit)."""
    p, s = prof.profiler(), prof.sentinel()
    prev = p.sample_every
    yield
    p.set_sample(prev)
    p.reset()
    s.reset()


def _collect(gen, prompt, sid, steps):
    # prime like the scheduler does: a live batch of retired slots, so
    # enqueue rides the continuous-admission path
    gen.set_prompts([[0], [0]])
    for s in gen.streams:
        s.done = True
    gen.enqueue(prompt, sid)
    out = []
    for _ in range(steps):
        for t in gen.step():
            if t is not None:
                out.append(t.id)
    return out


# -- step-phase profiler ------------------------------------------------------

def test_prof_on_off_streams_bit_identical(params, prof_env):
    """Sampling every step must not perturb the emitted stream — the
    profiler reads clocks, it never touches engine state."""
    prompt = [3, 1, 4, 1, 5, 9]

    prof.profiler().set_sample(0)
    g_off = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                           settings=SamplerSettings(**GREEDY))
    ids_off = _collect(g_off, prompt, sid=1, steps=20)

    prof.profiler().set_sample(1)
    g_on = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                          settings=SamplerSettings(**GREEDY))
    ids_on = _collect(g_on, prompt, sid=1, steps=20)

    assert ids_off and ids_off == ids_on


def test_sampled_step_records_phases_and_ring(params, prof_env):
    prof.profiler().reset()
    prof.profiler().set_sample(1)
    gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                         settings=SamplerSettings(**GREEDY))
    _collect(gen, [2, 7, 1, 8], sid=1, steps=12)

    rep = prof.report()
    assert rep["sample_every"] == 1
    assert rep["sampled_steps"] >= 12
    # the decode hot path stamps these on every sampled pass
    for name in ("dispatch", "sync", "emit"):
        assert rep["phases"][name]["count"] > 0, name
    # admission ran at least once (the enqueue's prefill chunks)
    assert rep["phases"]["admit"]["count"] > 0
    ring = rep["recent_steps"]
    assert ring and all(
        r["engine"] == "batch" and "total_ms" in r for r in ring)
    assert any(r["phases"] for r in ring)
    # memory arm: host watermarks always resolve on Linux
    assert rep["memory"]["host"]["rss_bytes"] > 0


def test_disabled_profiler_records_nothing(params, prof_env):
    prof.profiler().reset()
    prof.profiler().set_sample(0)
    gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                         settings=SamplerSettings(**GREEDY))
    _collect(gen, [2, 7, 1, 8], sid=1, steps=8)
    rep = prof.report()
    assert rep["sampled_steps"] == 0
    assert rep["recent_steps"] == []


# -- retrace sentinel ---------------------------------------------------------

def test_retrace_sentinel_flags_steady_decode_compile(prof_env):
    sent = prof.sentinel()
    sent.install()
    sent.reset()
    f = jax.jit(lambda x: x * 2 + 1)
    a4, a8, a16 = jnp.zeros((4,)), jnp.zeros((8,)), jnp.zeros((16,))

    # warmup compile inside the decode phase: counted, not a finding
    with sent.decode_phase():
        f(a4)
    assert sent.compiles.value >= 1
    assert sent.retraces.value == 0

    sent.mark_steady()
    # steady compile OUTSIDE a decode dispatch (a new prompt-bucket
    # prefill, say) is legitimate — still not a finding
    f(a8)
    assert sent.retraces.value == 0

    # steady + decode-phase + new shape = the retrace finding
    with sent.decode_phase():
        f(a16)
    assert sent.retraces.value == 1
    findings = sent.findings()
    assert len(findings) == 1
    assert findings[0]["compile_ms"] > 0

    # the cache-hit path must not re-flag: same shape again, no compile
    with sent.decode_phase():
        f(a16)
    assert sent.retraces.value == 1


def test_retrace_sentinel_strict_raises(prof_env, monkeypatch):
    sent = prof.sentinel()
    sent.install()
    sent.reset()
    g = jax.jit(lambda x: x - 3)
    b4, b8 = jnp.zeros((4,)), jnp.zeros((8,))
    with sent.decode_phase():
        g(b4)
    sent.mark_steady()
    monkeypatch.setenv("CAKE_PROF_STRICT", "1")
    with pytest.raises(prof.RetraceError):
        with sent.decode_phase():
            g(b8)
    assert sent.retraces.value == 1


# -- live /debug/prof ---------------------------------------------------------

def test_debug_prof_served_live(params, prof_env):
    prof.profiler().set_sample(1)
    gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                         settings=SamplerSettings(**GREEDY))
    sched = Scheduler(gen, queue_depth=4, request_timeout_s=120)
    sched.start(max_concurrent=2)
    srv = start_api_server(sched)
    try:
        url = f"http://127.0.0.1:{srv.port}"
        req = urllib.request.Request(
            url + "/v1/completions",
            data=json.dumps({"prompt": "abcd", "max_tokens": 8}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            r.read()
        with urllib.request.urlopen(url + "/debug/prof", timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            rep = json.loads(r.read())
    finally:
        srv.close()
        sched.close()
    for key in ("phases", "recent_steps", "compiles", "retraces",
                "memory", "sample_every"):
        assert key in rep, key
    assert rep["phases"]["dispatch"]["count"] > 0
    assert rep["compiles"] >= 0


# -- trace nesting ------------------------------------------------------------

def test_phase_spans_nest_under_request_spans(params, prof_env):
    """One --trace timeline carries BOTH the reqtrace request spans and
    the prof.* phase spans, with the phases inside the request window."""
    from cake_tpu.obs import trace as obs_trace

    prof.profiler().set_sample(1)
    tr = obs_trace.tracer()
    tr.start()
    try:
        gen = BatchGenerator(CFG, params, tokenizer=_FakeTok(),
                             settings=SamplerSettings(**GREEDY))
        sched = Scheduler(gen, queue_depth=4, request_timeout_s=120)
        sched.start(max_concurrent=2)
        srv = start_api_server(sched)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/completions",
                data=json.dumps(
                    {"prompt": "abcd", "max_tokens": 10}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                assert r.status == 200
                r.read()
        finally:
            srv.close()
            sched.close()
    finally:
        tr.stop()
    doc = tr.to_chrome_trace()
    tr.clear()
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    prof_evs = [e for e in evs if e["name"].startswith("prof.")]
    req_evs = [e for e in evs
               if e["name"] in ("serve.queue", "engine.prefill",
                                "session.emit")]
    assert prof_evs, "no prof.* phase spans in the trace"
    assert req_evs, "no request spans in the trace"
    lo = min(e["ts"] for e in req_evs)
    hi = max(e["ts"] + e.get("dur", 0) for e in req_evs)
    inside = [e for e in prof_evs if lo <= e["ts"] <= hi]
    assert inside, "no phase span inside the request window"


# -- benchdiff gate -----------------------------------------------------------

def _ledger(tmp_path, rows, name="ledger.jsonl"):
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(p)


def _row(metric, value, unit, **extra):
    return {"metric": metric, "value": value, "unit": unit,
            "device": "cpu", "stamp": "2026-08-07T00:00:00Z", **extra}


def test_benchdiff_passes_steady_ledger(tmp_path, capsys):
    from cake_tpu.tools import benchdiff

    led = _ledger(tmp_path, [
        _row("decode_tok", 100.0, "tokens/s"),
        _row("decode_tok", 104.0, "tokens/s"),
        _row("ttft_ms", 12.0, "ms"),
        _row("ttft_ms", 11.0, "ms"),
        _row("obs_pct", 1.5, "%"),
        _row("obs_pct", 2.0, "%"),
    ])
    rc = benchdiff.main(["--ledger", led,
                         "--baseline", str(tmp_path / "nope.json")])
    assert rc == 0
    assert "REGRESSED" not in capsys.readouterr().out


def test_benchdiff_fails_on_regression(tmp_path, capsys):
    from cake_tpu.tools import benchdiff

    led = _ledger(tmp_path, [
        _row("decode_tok", 100.0, "tokens/s"),
        _row("decode_tok", 10.0, "tokens/s"),  # -90%: past any gate
    ])
    rc = benchdiff.main(["--ledger", led,
                         "--baseline", str(tmp_path / "nope.json")])
    assert rc == 1
    assert "REGRESSED" in capsys.readouterr().out


def test_benchdiff_overhead_rows_gate_on_points(tmp_path):
    from cake_tpu.tools import benchdiff

    # a 4% overhead leg is inside the default 10-point budget — even
    # though a lucky -4% leg sits in the history (a min-of-history gate
    # would call this +8pp and start creeping toward red)
    led = _ledger(tmp_path, [
        _row("obs_pct", -4.0, "%"), _row("obs_pct", 4.0, "%"),
    ])
    assert benchdiff.main(["--ledger", led]) == 0
    # ...11.5% overhead busts the budget regardless of history
    led = _ledger(tmp_path, [
        _row("obs_pct", -4.0, "%"), _row("obs_pct", 11.5, "%"),
    ], name="bad.jsonl")
    assert benchdiff.main(["--ledger", led]) == 1


def test_benchdiff_ignores_cross_device_history(tmp_path):
    from cake_tpu.tools import benchdiff

    # a tpu row's 10x number must not gate the cpu smoke that follows
    rows = [
        dict(_row("decode_tok", 5000.0, "tokens/s"), device="TPU v5e"),
        _row("decode_tok", 100.0, "tokens/s"),
        _row("decode_tok", 95.0, "tokens/s"),
    ]
    assert benchdiff.main(["--ledger", _ledger(tmp_path, rows)]) == 0
