"""Multi-stream serving over a sequence-sharded window (sp > 1, r4).

Until r4, sequence parallelism was the single-stream long-context plane
(per-row positions raised in `ops/attention.py`). Now per-row frontiers
flow through the sp owner-masked KV write (`ring.sp_cache_write` with
``pos [B]``) and the per-row-masked distributed flash decode
(`ring.attend_stats`/`sp_decode_attend`), so N concurrent streams can
decode against a KV window sharded across chips — the composition that
serves many LONG streams on a chip set (window HBM splits over sp while
the batch splits over dp). Admission / prefix store / speculation /
interleave remain sp == 1 and are gated with clear errors.

The bar: streams match the sp=1 serving oracle token-for-token (sp
reassembles the exact softmax via pmax/psum, so logits agree to reduction
order; greedy and sampled tokens agree exactly on these shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.runtime.batch_generator import BatchGenerator

CFG = tiny(max_seq_len=64)
PROMPTS = [[5, 9, 2, 11, 3], [3, 1, 4, 1, 5, 9], [7, 7, 2], [2, 8, 1, 6]]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _run(params, settings, n, plan=None, **kw):
    g = BatchGenerator(CFG, params, plan=plan, settings=settings, **kw)
    g.set_prompts([list(p) for p in PROMPTS])
    return g.generate(n)


@pytest.mark.parametrize("mesh_kw", [
    dict(sp=2),
    dict(sp=2, dp=2),
    dict(sp=2, num_stages=2, tp=2),
])
@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_sp_serving_matches_flat_oracle(params, mesh_kw, temp):
    settings = SamplerSettings(temperature=temp, top_k=20, seed=11,
                               repeat_penalty=1.1)
    want = _run(params, settings, 8)
    plan = MeshPlan.build(CFG, **mesh_kw)
    got = _run(params, settings, 8, plan=plan, block_size=4)
    assert got == want


def test_sp_serving_int8_kv(params):
    """The quantized cache rides the sp owner-masked per-row writes."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    want = _run(params, settings, 8, kv_quant="int8")
    plan = MeshPlan.build(CFG, sp=2)
    got = _run(params, settings, 8, plan=plan, kv_quant="int8")
    assert got == want


def test_sp_serving_long_window_per_stream_parity(params):
    """The point of the composition: each stream's tokens at an sp-sharded
    window match its SOLO single-device run (per-row frontiers correct on
    every shard)."""
    from cake_tpu.runtime.generator import LlamaGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    plan = MeshPlan.build(CFG, sp=2)
    g = BatchGenerator(CFG, params, plan=plan, settings=settings)
    g.set_prompts([list(p) for p in PROMPTS])
    outs = g.generate(8)
    for prompt, got in zip(PROMPTS, outs):
        solo = LlamaGenerator(CFG, params, settings=settings)
        solo.set_prompt(list(prompt))
        want = [solo.next_token(i).id for i in range(8)]
        assert got == want


def test_sp_serving_gates_unsupported_features(params):
    settings = SamplerSettings(temperature=0.0)
    plan = MeshPlan.build(CFG, sp=2)
    with pytest.raises(ValueError, match="sp == 1"):
        BatchGenerator(CFG, params, plan=plan, settings=settings, spec_k=4)
    g = BatchGenerator(CFG, params, plan=plan, settings=settings)
    g.set_prompts([list(p) for p in PROMPTS])
    with pytest.raises(ValueError, match="sp == 1"):
        g.enqueue([1, 2, 3], stream_id=9)
    with pytest.raises(ValueError, match="sp == 1"):
        g.admit([1, 2, 3], stream_id=9)
    assert not g._interleave  # interleaved schedules are sp == 1


def test_sp_cache_write_per_row_owner_masking():
    """Unit: per-row writes land on each row's owner shard only (emulated
    shard-locally: two shards' slices written by the [B] path)."""
    from cake_tpu.ops.ring import sp_cache_write

    b, kh, s_l, d = 3, 2, 4, 8
    kc = jnp.zeros((b, kh, s_l, d))
    vc = jnp.zeros((b, kh, s_l, d))
    kn = jnp.ones((b, kh, 1, d))
    vn = 2 * jnp.ones((b, kh, 1, d))
    pos = jnp.asarray([1, 5, 6], jnp.int32)  # rows 1,2 live on shard 1
    # shard 0 (start 0): only row 0 in range
    k0, v0 = sp_cache_write(kc, vc, kn, vn, pos, 0)
    assert (np.asarray(k0)[0, :, 1] == 1).all()
    assert (np.asarray(k0)[1] == 0).all() and (np.asarray(k0)[2] == 0).all()
    # shard 1 (start 4): rows 1 (slot 1) and 2 (slot 2)
    k1, v1 = sp_cache_write(kc, vc, kn, vn, pos, 4)
    assert (np.asarray(k1)[1, :, 1] == 1).all()
    assert (np.asarray(v1)[2, :, 2] == 2).all()
    assert (np.asarray(k1)[0] == 0).all()
