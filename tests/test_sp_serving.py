"""Multi-stream serving over a sequence-sharded window (sp > 1, r4).

Until r4, sequence parallelism was the single-stream long-context plane
(per-row positions raised in `ops/attention.py`). Now per-row frontiers
flow through the sp owner-masked KV write (`ring.sp_cache_write` with
``pos [B]``) and the per-row-masked distributed flash decode
(`ring.attend_stats`/`sp_decode_attend`), so N concurrent streams can
decode against a KV window sharded across chips — the composition that
serves many LONG streams on a chip set (window HBM splits over sp while
the batch splits over dp). r5: continuous admission, the prefix store,
sliding-window attention, speculation, AND the interleaved schedules
compose with sp > 1 too (chunk-replicated fed/staging blocks + the
windowed sp masks + per-row range writes + sp-aware cycle loops); the
one path still serialized at sp > 1 is GPipe microbatch prefill.

The bar: streams match the sp=1 serving oracle token-for-token (sp
reassembles the exact softmax via pmax/psum, so logits agree to reduction
order; greedy and sampled tokens agree exactly on these shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import MeshPlan
from cake_tpu.runtime.batch_generator import BatchGenerator

CFG = tiny(max_seq_len=64)
PROMPTS = [[5, 9, 2, 11, 3], [3, 1, 4, 1, 5, 9], [7, 7, 2], [2, 8, 1, 6]]


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(5))


def _run(params, settings, n, plan=None, **kw):
    g = BatchGenerator(CFG, params, plan=plan, settings=settings, **kw)
    g.set_prompts([list(p) for p in PROMPTS])
    return g.generate(n)


@pytest.mark.parametrize("mesh_kw", [
    dict(sp=2),
    dict(sp=2, dp=2),
    dict(sp=2, num_stages=2, tp=2),
])
@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_sp_serving_matches_flat_oracle(params, mesh_kw, temp):
    settings = SamplerSettings(temperature=temp, top_k=20, seed=11,
                               repeat_penalty=1.1)
    want = _run(params, settings, 8)
    plan = MeshPlan.build(CFG, **mesh_kw)
    got = _run(params, settings, 8, plan=plan, block_size=4)
    assert got == want


def test_sp_serving_int8_kv(params):
    """The quantized cache rides the sp owner-masked per-row writes."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    want = _run(params, settings, 8, kv_quant="int8")
    plan = MeshPlan.build(CFG, sp=2)
    got = _run(params, settings, 8, plan=plan, kv_quant="int8")
    assert got == want


def test_sp_serving_long_window_per_stream_parity(params):
    """The point of the composition: each stream's tokens at an sp-sharded
    window match its SOLO single-device run (per-row frontiers correct on
    every shard)."""
    from cake_tpu.runtime.generator import LlamaGenerator

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    plan = MeshPlan.build(CFG, sp=2)
    g = BatchGenerator(CFG, params, plan=plan, settings=settings)
    g.set_prompts([list(p) for p in PROMPTS])
    outs = g.generate(8)
    for prompt, got in zip(PROMPTS, outs):
        solo = LlamaGenerator(CFG, params, settings=settings)
        solo.set_prompt(list(prompt))
        want = [solo.next_token(i).id for i in range(8)]
        assert got == want


def test_sp_interleaved_schedule_matches_serialized(params):
    """r5: the interleaved-microbatch schedule composes with sp too — on
    an sp x stage mesh with a stage-divisible batch the dispatches take
    the interleaved program and streams stay bit-identical to the
    serialized sp run (the last serving-plane sp gate is gone; only
    GPipe microbatch PREFILL stays serialized at sp > 1)."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    plan = MeshPlan.build(CFG, sp=2, num_stages=2)
    g = BatchGenerator(CFG, params, plan=plan, settings=settings)
    assert g._interleave  # auto-engaged on the staged sp mesh
    g.set_prompts([list(PROMPTS[0]), list(PROMPTS[1])])
    got = g.generate(8)
    g2 = BatchGenerator(CFG, params, plan=plan, settings=settings,
                        interleave=False)
    g2.set_prompts([list(PROMPTS[0]), list(PROMPTS[1])])
    assert got == g2.generate(8)


def test_sp_cache_write_per_row_owner_masking():
    """Unit: per-row writes land on each row's owner shard only (emulated
    shard-locally: two shards' slices written by the [B] path)."""
    from cake_tpu.ops.ring import sp_cache_write

    b, kh, s_l, d = 3, 2, 4, 8
    kc = jnp.zeros((b, kh, s_l, d))
    vc = jnp.zeros((b, kh, s_l, d))
    kn = jnp.ones((b, kh, 1, d))
    vn = 2 * jnp.ones((b, kh, 1, d))
    pos = jnp.asarray([1, 5, 6], jnp.int32)  # rows 1,2 live on shard 1
    # shard 0 (start 0): only row 0 in range
    k0, v0 = sp_cache_write(kc, vc, kn, vn, pos, 0)
    assert (np.asarray(k0)[0, :, 1] == 1).all()
    assert (np.asarray(k0)[1] == 0).all() and (np.asarray(k0)[2] == 0).all()
    # shard 1 (start 4): rows 1 (slot 1) and 2 (slot 2)
    k1, v1 = sp_cache_write(kc, vc, kn, vn, pos, 4)
    assert (np.asarray(k1)[1, :, 1] == 1).all()
    assert (np.asarray(v1)[2, :, 2] == 2).all()
    assert (np.asarray(k1)[0] == 0).all()


@pytest.mark.parametrize("mesh_kw", [dict(sp=2), dict(sp=2, num_stages=2)])
def test_sp_admission_enqueue_matches_sp1_oracle(params, mesh_kw):
    """r5: continuous admission over a sequence-sharded window — the
    arrival's chunks run replicated over sp into the sp-sharded staging
    cache (range writes + chunk attend); the admitted stream and the
    untouched neighbor both match the sp=1 run token-for-token."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    new_prompt = [2, 8, 1, 7, 6, 5, 4, 3]  # 8 tokens -> 2 chunks of 4

    def run(plan):
        g = BatchGenerator(CFG, params, plan=plan, settings=settings,
                           admit_chunk=4)
        g.set_prompts([list(PROMPTS[0]), list(PROMPTS[1])])
        g.step(), g.step()
        g.streams[0].done = True
        g.enqueue(list(new_prompt), stream_id=7)
        for _ in range(12):
            g.step()
        admitted = next(s for s in g.streams if s.stream_id == 7)
        neighbor = next(s for s in g.streams if s.stream_id == 1)
        return list(admitted.generated), list(neighbor.generated)

    want_adm, want_nb = run(None)  # sp == 1 oracle
    got_adm, got_nb = run(MeshPlan.build(CFG, **mesh_kw))
    assert len(got_adm) >= 4
    assert got_adm == want_adm
    assert got_nb == want_nb


def test_sp_shared_prefix_and_store_match_sp1(params):
    """r5: the shared-prefix batch prefill (prefix staged once, broadcast,
    remainders at offset) and a later arrival's prefix-store hit both run
    over the sp-sharded staging cache and match the sp=1 oracle."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    shared = [7, 3, 9, 1, 4, 6, 2, 8, 5, 11, 13, 12]  # 12-token prefix
    prompts = [shared + [20], shared + [21, 22]]
    arrival = shared + [23]

    def run(plan):
        g = BatchGenerator(CFG, params, plan=plan, settings=settings,
                           prefix_share_min=8, prefix_block=4)
        g.set_prompts([list(p) for p in prompts])
        outs = g.generate(4)
        g.streams[0].done = True
        g.enqueue(list(arrival), stream_id=9)
        for _ in range(12):
            g.step()
        adm = next(s for s in g.streams if s.stream_id == 9)
        return outs, list(adm.generated), g._prefix_hits

    want_outs, want_adm, hits1 = run(None)
    got_outs, got_adm, hits2 = run(MeshPlan.build(CFG, sp=2))
    assert got_outs == want_outs
    n = min(len(got_adm), len(want_adm))
    assert n >= 4 and got_adm[:n] == want_adm[:n]
    # the arrival actually hit the stored prefix row on both layouts
    assert hits1 >= 1 and hits2 >= 1


def test_sp_windowed_serving_matches_sp1(params):
    """r5: sliding-window attention composes with sp — the window's lower
    bound masks each shard's local slice and out-of-window shards drop out
    of the psum merge. Decode past the window matches the sp=1 windowed
    oracle (the r4 NotImplementedError is gone)."""
    wcfg = tiny(model_type="mistral", sliding_window=8, max_seq_len=64)
    wparams = llama.init_params(wcfg, jax.random.PRNGKey(5))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)

    def run(plan):
        g = BatchGenerator(wcfg, wparams, plan=plan, settings=settings)
        g.set_prompts([list(p) for p in PROMPTS])
        return g.generate(16)  # prompt+16 > window: lower bound active

    want = run(None)
    got = run(MeshPlan.build(wcfg, sp=2))
    assert got == want


def test_sp_windowed_ring_prefill_matches_sp1(params):
    """r5: windowed RING prefill — a long prompt sharded over sp=4 chunks
    with a window smaller than a chunk, so some visiting blocks are wholly
    out-of-window (the lax.cond compute-skip path) and the rest fold the
    window lower bound into their blockwise mask."""
    wcfg = tiny(model_type="mistral", sliding_window=4, max_seq_len=64)
    wparams = llama.init_params(wcfg, jax.random.PRNGKey(5))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    long_prompt = [(i * 7) % 29 + 1 for i in range(32)]  # 32 = 4 x 8-chunks

    def run(plan):
        g = BatchGenerator(wcfg, wparams, plan=plan, settings=settings)
        g.set_prompts([list(long_prompt), list(PROMPTS[0])])
        return g.generate(8)

    want = run(None)
    got = run(MeshPlan.build(wcfg, sp=4))
    assert got == want


def test_sp_range_cache_write_spans_shards():
    """Unit: a chunk spanning a shard boundary writes each shard's
    in-range slots only (emulated shard-locally on both shards)."""
    from cake_tpu.ops.ring import sp_range_cache_write

    b, kh, s_l, d = 2, 2, 4, 8
    kc = jnp.zeros((b, kh, s_l, d))
    vc = jnp.zeros((b, kh, s_l, d))
    c = 3
    kn = jnp.arange(1, c + 1, dtype=jnp.float32).reshape(1, 1, c, 1)
    kn = jnp.broadcast_to(kn, (b, kh, c, d))
    vn = 10.0 * kn
    pos0 = 3  # global slots 3, 4, 5
    # shard 0 (start 0): only global slot 3 (chunk idx 0) in range
    k0, v0 = sp_range_cache_write(kc, vc, kn, vn, pos0, 0)
    assert (np.asarray(k0)[:, :, 3] == 1).all()
    assert (np.asarray(k0)[:, :, :3] == 0).all()
    # shard 1 (start 4): global slots 4, 5 -> local 0, 1 (chunk idx 1, 2)
    k1, v1 = sp_range_cache_write(kc, vc, kn, vn, pos0, 4)
    assert (np.asarray(k1)[:, :, 0] == 2).all()
    assert (np.asarray(v1)[:, :, 1] == 30).all()
    assert (np.asarray(k1)[:, :, 2:] == 0).all()


@pytest.mark.parametrize("rounds", [1, 4])
def test_sp_spec_serving_matches_sp1(params, rounds):
    """r5: batched speculation over the sequence-sharded window — each
    row's K+1 verification block runs chunk-replicated over sp with
    per-row range writes; greedy streams match the sp=1 run on their
    common prefix (rounds=1: host loop; rounds=4: fused chain)."""
    cfg = tiny(max_seq_len=256, eos_token_id=-1)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompts = [[5, 9, 2, 5, 9, 2, 5, 9], [7, 1, 3, 7, 1, 3, 7, 1]]

    def run(plan):
        g = BatchGenerator(cfg, params, plan=plan, settings=settings,
                           spec_k=4, spec_rounds=rounds)
        g.set_prompts([list(p) for p in prompts])
        for _ in range(25):
            g.step()
        return [list(s.generated) for s in g.streams], g.stats()

    want, _ = run(None)
    got, st = run(MeshPlan.build(cfg, sp=2))
    assert st["spec_dispatches"] >= 1  # speculation actually engaged
    for g_row, w_row in zip(got, want):
        n = min(len(g_row), len(w_row))
        assert n >= 16
        assert g_row[:n] == w_row[:n]


def test_sp_single_stream_mesh_speculation_matches_plain(params):
    """r5: MeshSpeculativeGenerator over sp=2 — the single-stream
    verification pass (build_sharded_verify) runs against the
    sequence-sharded cache and stays bit-identical to plain decode."""
    from cake_tpu.runtime.generator import LlamaGenerator
    from cake_tpu.runtime.speculative import MeshSpeculativeGenerator

    cfg = tiny(max_seq_len=64, eos_token_id=-1)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]

    plain = LlamaGenerator(cfg, params, settings=settings)
    plain.set_prompt(list(prompt))
    want = [plain.next_token(i).id for i in range(16)]

    g = MeshSpeculativeGenerator(cfg, params, settings=settings, sp=2,
                                 spec_k=4)
    g.set_prompt(list(prompt))
    got = [g.next_token(i).id for i in range(16)]
    assert got == want


def test_sp_admission_int8_kv_matches_sp1(params):
    """r5: the quantized staging cache rides the sp range writes too
    (quantize-on-write through _leaf_pairs; the chunk attend reads the
    round-tripped values, same as the sp=1 int8 admission oracle)."""
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)

    def run(plan):
        g = BatchGenerator(CFG, params, plan=plan, settings=settings,
                           kv_quant="int8", admit_chunk=4)
        g.set_prompts([list(PROMPTS[0]), list(PROMPTS[1])])
        g.step(), g.step()
        g.streams[0].done = True
        g.enqueue([2, 8, 1, 7, 6, 5], stream_id=7)
        for _ in range(10):
            g.step()
        return [list(s.generated) for s in g.streams]

    want = run(None)
    got = run(MeshPlan.build(CFG, sp=2))
    assert got == want
