"""Chaos matrix: fault injection at every protocol state must be survivable.

The reference dies on any link fault (SURVEY §5, client.rs:52-61). Here a
seeded frame-aware proxy (cake_tpu.testing.chaos) kills/stalls/corrupts/
truncates/blackholes the master<->worker stream at exact frames — at
handshake, in the ping plane, at the prefill op, and at decode — and every
greedy stream must come out BIT-IDENTICAL to the fault-free local run (the
recovery replay is deterministic), or fail with a clear error inside the
deadline. Plus: replica failover, the hung-peer ``recv`` deadline at the
wire level, and the consecutive-recovery reset satellites.
"""

import threading
import time

import jax
import pytest

from cake_tpu.models import llama
from cake_tpu.models.config import tiny
from cake_tpu.obs import flight
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.runner import RemoteRunner
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import wire
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.master import DistributedGenerator, build_runners
from cake_tpu.runtime.retry import RetryPolicy, retry_call
from cake_tpu.runtime.worker import Worker
from cake_tpu.testing.chaos import ChaosProxy, parse_spec, schedule_from_seed

CFG = tiny(max_seq_len=64)
SETTINGS = dict(temperature=0.0, repeat_penalty=1.1)
PROMPT = [5, 9, 2]
N_TOK = 7

# request-frame numbers on one master connection (1-based): HELLO, then
# the CLOCK_PINGS-ping clock exchange, then the first BATCH (prefill)
PREFILL_F = 2 + RemoteRunner.CLOCK_PINGS
DECODE_F = PREFILL_F + 1


@pytest.fixture(scope="module", autouse=True)
def _reset_fault_counters():
    """The injected faults deliberately trip the process-global wire/
    recovery counters (CRC failures, timeouts, recoveries); later test
    modules assert those start at zero, so put them back when this
    module's chaos is over."""
    from cake_tpu.obs import metrics as obs_metrics

    yield
    for name in ("wire.crc_failures", "wire.timeouts", "master.recoveries",
                 "master.failovers", "recover.backoff_ms"):
        obs_metrics.registry().counter(name).reset()


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(3))


def _loader(params):
    return lambda lo, hi: jax.tree.map(lambda a: a[lo:hi], params["layers"])


def _head(params):
    return {k: params[k] for k in ("embed", "norm_f", "lm_head")}


@pytest.fixture(scope="module")
def golden(params):
    """Fault-free greedy stream — every chaos case must reproduce it."""
    g = LlamaGenerator(CFG, params, settings=SamplerSettings(**SETTINGS))
    g.set_prompt(PROMPT)
    return [g.next_token(i).id for i in range(N_TOK)]


@pytest.fixture(scope="module")
def worker(params):
    """One worker serving all layers, shared by the matrix cases (workers
    are stateless across connections; each case brings its own proxy).
    Warmed through one fault-free exchange so the tight-op-timeout cases
    measure a WEDGED peer, never a cold XLA compile."""
    w = Worker("w", CFG, Topology.from_dict(
        {"w": {"layers": ["model.layers.0-3"]}}), _loader(params),
        address="127.0.0.1:0", max_seq=CFG.max_seq_len)
    w.serve_in_background()
    g = _gen(f"127.0.0.1:{w.port}", params)
    g.set_prompt(PROMPT)
    for i in range(2):  # prefill + decode shapes compiled
        g.next_token(i)
    g.close()
    yield w
    w.shutdown()


def _gen(addr_or_addrs, params, **runner_kw):
    hosts = ([addr_or_addrs] if isinstance(addr_or_addrs, str)
             else list(addr_or_addrs))
    topo = Topology.from_dict({
        "w": {"host": hosts, "layers": ["model.layers.0-3"]},
    })
    runner_kw.setdefault("recover_deadline_s", 5.0)
    runners = build_runners(CFG, topo, _loader(params), **runner_kw)
    return DistributedGenerator(CFG, _head(params), runners,
                                settings=SamplerSettings(**SETTINGS))


# -- the matrix --------------------------------------------------------------
# (spec, runner kwargs, min recoveries) — spec directives apply to
# successive connections: conn 0 is the build_runners handshake, conn 1 the
# set_prompt reconnect that carries prefill + decode.
MATRIX = [
    # handshake state: killed / refused connects, healed by --connect-retries
    ("kill@1", dict(connect_retries=2), 0),
    ("refuse=2", dict(connect_retries=3), 0),
    # connections absorbed by a multi-connect refuse must NOT consume the
    # faults scheduled after it: the schedule continues with the build
    # handshake that finally got through (`none`) and the kill still
    # fires on the set_prompt connection after it
    (f"refuse=2,none,kill@{DECODE_F}", dict(connect_retries=3), 1),
    # ping plane: die mid clock exchange at handshake
    ("kill@3", dict(connect_retries=2), 0),
    # prefill op: connection dropped right after the op went out
    (f"none,kill@{PREFILL_F}", {}, 1),
    # decode op: drop, cut mid-frame, flip payload bytes (worker-side CRC),
    # flip reply bytes (master-side CRC)
    (f"none,kill@{DECODE_F}", {}, 1),
    (f"none,truncate@{DECODE_F}", {}, 1),
    (f"none,corrupt@{DECODE_F}", {}, 1),
    (f"none,corrupt@r{DECODE_F}", {}, 1),
    # hung peer: reply held past --op-timeout / swallowed forever
    (f"none,stall@{DECODE_F}=900", dict(op_timeout_s=0.3), 1),
    (f"none,blackhole@{DECODE_F}", dict(op_timeout_s=0.3), 1),
]


@pytest.mark.parametrize("spec,kw,min_rec", MATRIX,
                         ids=[m[0] for m in MATRIX])
def test_chaos_matrix_stream_survives_bit_identical(
        worker, params, golden, spec, kw, min_rec):
    with ChaosProxy("127.0.0.1", worker.port, parse_spec(spec)) as proxy:
        g = _gen(proxy.addr, params, **kw)
        g.set_prompt(PROMPT)
        got = [g.next_token(i).id for i in range(N_TOK)]
        assert got == golden, f"stream diverged under chaos {spec}"
        assert g.recoveries >= min_rec
        assert proxy.events, "the scheduled fault never fired"
        g.close()


def test_chaos_failure_inside_deadline(worker, params):
    """When recovery CANNOT succeed (every reconnect refused), the stream
    must fail with the give-up error within the configured budgets — not
    hang, not loop forever."""
    # conn 0 clean handshake, conn 1 killed at decode, every later
    # connect refused
    faults = parse_spec(f"none,kill@{DECODE_F},refuse=1000")
    with ChaosProxy("127.0.0.1", worker.port, faults) as proxy:
        g = _gen(proxy.addr, params, recover_deadline_s=0.3)
        g.set_prompt(PROMPT)
        g.next_token(0)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="consecutive recovery"):
            for i in range(1, N_TOK):
                g.next_token(i)
        # cap * per-replica budget, plus slack for the jittered backoff
        assert time.monotonic() - t0 < 10.0
        g.close()


def test_chaos_seed_reproducible():
    """The acceptance contract: a failure seen under ``--chaos seed=N`` is
    reproducible from N alone."""
    assert schedule_from_seed(1337) == schedule_from_seed(1337)
    assert schedule_from_seed(1337, n=4) == schedule_from_seed(1337, n=4)
    assert schedule_from_seed(1337) != schedule_from_seed(7331)
    # specs round-trip through their string form (events log those)
    fs = parse_spec("kill@7,stall@2=500,corrupt@r3")
    assert parse_spec(",".join(str(f) for f in fs)) == fs


# -- replica failover --------------------------------------------------------

def test_replica_failover_mid_stream(params, golden):
    """Topology `host:` lists are a failover order: when the primary's
    recovery deadline expires mid-stream, the segment moves to the next
    replica, the replay rebuilds its KV, and the greedy stream stays
    bit-identical. Counters + stats must show the move."""
    node = Topology.from_dict({"w": {"layers": ["model.layers.0-3"]}})
    wa = Worker("w", CFG, node, _loader(params), address="127.0.0.1:0",
                max_seq=CFG.max_seq_len)
    wb = Worker("w", CFG, node, _loader(params), address="127.0.0.1:0",
                max_seq=CFG.max_seq_len)
    wa.serve_in_background()
    wb.serve_in_background()
    rec = flight.recorder()
    rec.clear()
    rec.enable()
    try:
        g = _gen([f"127.0.0.1:{wa.port}", f"127.0.0.1:{wb.port}"], params,
                 recover_deadline_s=0.4)
        g.set_prompt(PROMPT)
        got = [g.next_token(i).id for i in range(3)]
        wa.shutdown()  # primary gone for good
        got += [g.next_token(i).id for i in range(3, N_TOK)]
        assert got == golden
        assert g.recoveries >= 1 and g.failovers == 1
        (entry,) = g.runner_stats()
        assert entry["replica"] == "2/2"
        assert entry["ident"] == f"127.0.0.1:{wb.port}"
        assert any(r.get("failover") for r in rec.records())
        assert any(r.get("recovery") for r in rec.records())
        g.close()
    finally:
        rec.disable()
        wb.shutdown()
        wa.shutdown()


# -- hung peer at the wire level (satellite) ---------------------------------

@pytest.mark.parametrize("force_py", [False, True],
                         ids=["native", "python"])
def test_recv_deadline_fires_on_silent_peer(force_py):
    """Connection.recv defaults its deadline to the connect timeout (the
    seed set settimeout(None) and a wedged peer blocked forever); expiry
    raises WireTimeout, on both transports, in bounded time."""
    lst = wire.Listener("127.0.0.1", 0, force_python=force_py)
    try:
        conn = wire.connect("127.0.0.1", lst.port, timeout_ms=400,
                            force_python=force_py)
        assert conn.timeout_s == pytest.approx(0.4)
        t0 = time.monotonic()
        with pytest.raises(wire.WireTimeout):
            conn.recv()  # default deadline = connect timeout
        assert 0.2 < time.monotonic() - t0 < 5.0
        conn.close()
    finally:
        lst.close()


def test_connections_have_keepalive():
    """TCP keepalive on both ends so a vanished peer (no FIN) eventually
    faults a blocked recv instead of pinning it — and, worker-side, the
    connection's KV caches — forever."""
    import socket as pysocket

    lst = wire.Listener("127.0.0.1", 0, force_python=True)
    try:
        server_side = {}

        def srv():
            server_side["conn"] = lst.accept()

        th = threading.Thread(target=srv, daemon=True)
        th.start()
        conn = wire.connect("127.0.0.1", lst.port, force_python=True)
        th.join(timeout=5)
        for c in (conn, server_side["conn"]):
            assert c._sock.getsockopt(pysocket.SOL_SOCKET,
                                      pysocket.SO_KEEPALIVE) == 1
        conn.close()
        server_side["conn"].close()
    finally:
        lst.close()


def test_native_connection_has_keepalive():
    import os
    import socket as pysocket

    if wire.native_lib() is None:
        pytest.skip("no native wire lib")
    lst = wire.Listener("127.0.0.1", 0)
    try:
        threading.Thread(target=lst.accept, daemon=True).start()
        conn = wire.connect("127.0.0.1", lst.port)
        assert conn.is_native
        probe = pysocket.socket(fileno=os.dup(conn._fd))
        try:
            assert probe.getsockopt(pysocket.SOL_SOCKET,
                                    pysocket.SO_KEEPALIVE) == 1
        finally:
            probe.close()
        conn.close()
    finally:
        lst.close()


# -- retry/backoff policy (satellite) ----------------------------------------

def test_retry_policy_deadline_budget():
    """retry_call spends at most the deadline, sleeps with full jitter,
    and re-raises the LAST transport error on exhaustion."""
    import random

    calls = {"n": 0}
    slept = []

    def always_fails():
        calls["n"] += 1
        raise OSError(f"down {calls['n']}")

    with pytest.raises(OSError, match="down"):
        retry_call(always_fails, RetryPolicy(deadline_s=0.5, base_s=0.01),
                   rng=random.Random(0), sleep=slept.append,
                   clock=_FakeClock(slept).read)
    assert calls["n"] >= 2
    assert all(s <= 2.0 for s in slept)  # cap_s honored
    # non-transport errors are never retried
    def config_error():
        calls["n"] += 1
        raise RuntimeError("does not serve layers")

    calls["n"] = 0
    with pytest.raises(RuntimeError):
        retry_call(config_error, RetryPolicy(deadline_s=5.0))
    assert calls["n"] == 1


class _FakeClock:
    """Monotonic clock driven by the recorded sleeps (no real waiting)."""

    def __init__(self, slept: list):
        self._slept = slept

    def read(self) -> float:
        return sum(self._slept)


def test_retry_attempt_cap():
    calls = {"n": 0}

    def fails():
        calls["n"] += 1
        raise OSError("nope")

    with pytest.raises(OSError):
        retry_call(fails, RetryPolicy(deadline_s=None, max_attempts=3,
                                      base_s=0.001, cap_s=0.001))
    assert calls["n"] == 3


# -- worker-side failure domain (satellite) ----------------------------------

def test_worker_logs_and_drops_connection_on_stream_fault(worker, caplog):
    """A connection-level fault in the worker's handler (here: a frame
    whose CRC check fires) must not kill the thread silently: it is
    logged, the socket closed, the live-connection count restored — and
    the worker keeps serving new connections."""
    import logging
    import struct

    from cake_tpu.runtime.protocol import MsgType

    live0 = worker._conns_live  # stale handlers from earlier cases may linger
    conn = wire.connect("127.0.0.1", worker.port, force_python=True)
    conn.send(MsgType.HELLO)
    t, _ = conn.recv()
    assert t == MsgType.WORKER_INFO
    with caplog.at_level(logging.WARNING, logger="cake_tpu.worker"):
        # frame with a deliberately wrong CRC trailer: recv() on the
        # worker raises WireError outside the per-op handler
        hdr = wire._HEADER.pack(wire.MAGIC, int(MsgType.BATCH), 4)
        conn._sock.sendall(hdr + b"abcd" + struct.pack("<I", 0xDEADBEEF))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and worker._conns_live > live0:
            time.sleep(0.05)
    assert worker._conns_live <= live0
    assert any("connection lost" in r.message for r in caplog.records)
    conn.close()
    # the worker is still accepting and serving
    c2 = wire.connect("127.0.0.1", worker.port)
    c2.send(MsgType.HELLO)
    t, _ = c2.recv()
    assert t == MsgType.WORKER_INFO
    c2.send(MsgType.GOODBYE)
    c2.close()


# -- consecutive-recovery reset (satellite) ----------------------------------

def test_consec_recoveries_reset_per_prompt(worker, params):
    """The MAX_CONSEC_RECOVERIES cap guards one stream's recovery loop; a
    long session's recoveries must not accumulate across prompts until a
    healthy stream trips it spuriously."""
    g = _gen(f"127.0.0.1:{worker.port}", params)
    g.set_prompt(PROMPT)
    g.next_token(0)
    g._consec_recoveries = DistributedGenerator.MAX_CONSEC_RECOVERIES
    g.set_prompt(PROMPT)
    assert g._consec_recoveries == 0
    g.next_token(0)  # and the fresh stream generates fine
    g.close()


# -- CLI plumbing (make chaos-smoke; slow: subprocess model loads) -----------

@pytest.mark.slow
def test_cli_chaos_flag_end_to_end(tmp_path):
    """`--chaos kill@N` on a real master CLI run: the fault fires on the
    proxied link, recovery replays, and stdout carries the same token ids
    as the fault-free run."""
    import json
    import os
    import socket
    import subprocess
    import sys
    from pathlib import Path

    from cake_tpu.utils.weights import save_llama_params

    repo = Path(__file__).resolve().parents[1]
    d = tmp_path / "model"
    d.mkdir()
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype="float32")
    save_llama_params(params, d)
    (d / "config.json").write_text(json.dumps(CFG.to_hf_dict()))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    topo = tmp_path / "topo.yml"
    topo.write_text(
        f"w:\n  host: 127.0.0.1:{port}\n"
        f"  layers: [model.layers.0-3]\n"
    )
    env = dict(os.environ, PYTHONPATH=str(repo), JAX_PLATFORMS="cpu")
    base = [sys.executable, "-m", "cake_tpu.cli", "--model", str(d),
            "--topology", str(topo), "--prompt-ids", "5,9,2", "-n", "6",
            "--temperature", "0.0", "--cpu", "--max-seq", "64"]
    worker = subprocess.Popen(
        [sys.executable, "-m", "cake_tpu.cli", "--model", str(d),
         "--topology", str(topo), "--mode", "worker", "--name", "w",
         "--cpu", "--address", f"127.0.0.1:{port}", "--max-seq", "64"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:  # wait for the worker to listen
            try:
                socket.create_connection(("127.0.0.1", port), 1).close()
                break
            except OSError:
                time.sleep(0.3)
        clean = subprocess.run(base, env=env, cwd=repo, capture_output=True,
                               text=True, timeout=240)
        assert clean.returncode == 0, clean.stderr
        chaotic = subprocess.run(
            base + ["--chaos", f"none,kill@{DECODE_F}",
                    "--recover-deadline", "10"],
            env=env, cwd=repo, capture_output=True, text=True, timeout=240)
        assert chaotic.returncode == 0, chaotic.stderr
        assert "chaos enabled" in chaotic.stderr
        assert "reconnecting and replaying" in chaotic.stderr
        assert chaotic.stdout.strip() == clean.stdout.strip()
    finally:
        worker.terminate()
        worker.wait(timeout=10)


# -- acceptance smoke (make chaos-smoke) -------------------------------------

def test_chaos_smoke_kill_and_stall_acceptance(params, tmp_path):
    """ISSUE-4 acceptance: a seeded 2-worker loopback generation survives
    (a) a worker process kill+restart inside --recover-deadline and (b) a
    mid-frame stall longer than --op-timeout, with a token stream
    identical to the fault-free run, counters and flight-record flags
    reflecting each injected fault, and the seed reproducing the
    schedule."""
    topo_a = Topology.from_dict({"a": {"layers": ["model.layers.0-1"]}})
    topo_b = Topology.from_dict({"b": {"layers": ["model.layers.2-3"]}})
    wa = Worker("a", CFG, topo_a, _loader(params), address="127.0.0.1:0",
                max_seq=CFG.max_seq_len)
    wb = Worker("b", CFG, topo_b, _loader(params), address="127.0.0.1:0",
                max_seq=CFG.max_seq_len)
    wa.serve_in_background()
    wb.serve_in_background()
    b_port = wb.port
    restarted: list = []

    # fault-free 2-worker golden stream first (also warms both workers'
    # XLA compiles — the warm-up run keeps the GENEROUS default op
    # timeout; only the chaos run below tightens it, to catch the stall)
    def two_worker_gen(a_addr, op_timeout_s=None):
        topo = Topology.from_dict({
            "a": {"host": a_addr, "layers": ["model.layers.0-1"]},
            "b": {"host": f"127.0.0.1:{b_port}",
                  "layers": ["model.layers.2-3"]},
        })
        return DistributedGenerator(
            CFG, _head(params),
            build_runners(CFG, topo, _loader(params),
                          op_timeout_s=op_timeout_s,
                          recover_deadline_s=10.0),
            settings=SamplerSettings(**SETTINGS))

    g0 = two_worker_gen(f"127.0.0.1:{wa.port}")
    g0.set_prompt(PROMPT)
    golden2 = [g0.next_token(i).id for i in range(N_TOK)]
    g0.close()

    # (b) mid-frame stall on worker a's link, longer than --op-timeout.
    # The schedule is data, reproducible from its string (or seed) form —
    # the same law schedule_from_seed obeys. The 2s op timeout is tight
    # enough to catch the 8s stall fast but leaves the restarted worker
    # room to recompile its jit (a fresh Worker instance pays the XLA
    # trace again) without burning MAX_CONSEC_RECOVERIES on timeouts.
    faults = parse_spec(f"none,stall@{DECODE_F}=8000")
    assert schedule_from_seed(1337) == schedule_from_seed(1337)  # seed law
    rec = flight.recorder()
    rec.clear()
    rec.enable(path=str(tmp_path / "flight.jsonl"))
    from cake_tpu.obs import metrics as obs_metrics

    recov_ctr = obs_metrics.registry().counter("master.recoveries")
    recov0 = recov_ctr.value
    try:
        with ChaosProxy("127.0.0.1", wa.port, faults) as proxy:
            g = two_worker_gen(proxy.addr, op_timeout_s=2.0)
            g.set_prompt(PROMPT)
            got = [g.next_token(i).id for i in range(3)]  # rides the stall
            assert g.recoveries >= 1, "stall > op-timeout must recover"

            # (a) kill worker b's PROCESS and restart it on the same port
            # inside the recovery deadline (the restart races the backoff
            # loop, which keeps retrying the refused connect)
            wb.shutdown()

            def restart():
                time.sleep(0.5)
                w2 = Worker("b", CFG, topo_b, _loader(params),
                            address=f"127.0.0.1:{b_port}",
                            max_seq=CFG.max_seq_len)
                w2.serve_in_background()
                restarted.append(w2)

            th = threading.Thread(target=restart, daemon=True)
            th.start()
            got += [g.next_token(i).id for i in range(3, N_TOK)]
            th.join(timeout=10)

            assert got == golden2, "stream diverged across kill + stall"
            assert g.recoveries >= 2  # one per injected fault
            assert g.failovers == 0  # no replicas involved: same addresses
            assert recov_ctr.value - recov0 == g.recoveries
            recs = rec.records()
            assert sum(1 for r in recs if r.get("recovery")) >= 2
            assert proxy.events  # the stall actually fired
            g.close()
    finally:
        rec.close()
        for w in [wa] + restarted:
            w.shutdown()
